"""TPU storage engine: the ``tablet_storage_engine=tpu`` data plane.

The north-star component (BASELINE.json): scans, MVCC merge-on-read,
predicate filtering and aggregate pushdown execute as device programs over
columnar runs demand-paged into HBM through the residency manager
(storage.residency, bounded by --tpu_hbm_budget_bytes; the host
ColumnarRun stays authoritative), while writes, the memtable, and exact
tie/varlen handling stay host-side. Query results are required to be
identical to CpuStorageEngine (the oracle) — the engine-diff tests
enforce it.

Read-path policy (correctness first, device fast path where it's sound):

- single-source scans (one run covers the range, memtable empty there):
  device evaluates visibility + range + numeric predicates exactly; varlen
  (string) predicates produce a candidate SUPERSET that the host verifies
  during materialization.
- multi-source scans (several overlapping runs and/or a live memtable):
  each run reports candidate keys from the device without predicate
  filtering (a column's latest value may live in another source, so
  per-source predicate evaluation is unsound — see ops/scan.py); the host
  merges versions across sources per candidate key (storage.merge) and
  applies predicates. Memtable keys in range are always candidates.
- aggregates push down to the device (per-block partials, exact integer
  limb sums) only when the scan is single-source and every predicate is
  device-exact; otherwise they fall back to the row path + host Aggregator.

Reference analog of the seam/merge behavior: DocRowwiseIterator over an
IntentAwareIterator merging regular/provisional sources
(src/yb/docdb/doc_rowwise_iterator.cc, intent_aware_iterator.h:81).
"""

from __future__ import annotations

import bisect
import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.schema import Schema
from yugabyte_db_tpu.ops import agg_fold
from yugabyte_db_tpu.ops import encodings
from yugabyte_db_tpu.ops import scan as dscan
from yugabyte_db_tpu.ops.device_run import (DeviceRun, device_label,
                                            dtype_kind, padded_blocks,
                                            plane_nbytes)
from yugabyte_db_tpu.storage.residency import device_nbytes, hbm_cache
from yugabyte_db_tpu.storage.breaker import CircuitBreaker
from yugabyte_db_tpu.storage.columnar import ColumnarRun
from yugabyte_db_tpu.storage import host_page
from yugabyte_db_tpu.storage.cpu_engine import Aggregator, RowMaterializer
from yugabyte_db_tpu.storage.engine import StorageEngine, register_engine
from yugabyte_db_tpu.storage.memtable import MemTable, make_memtable
from yugabyte_db_tpu.storage.merge import merge_versions
from yugabyte_db_tpu.storage.row_version import MAX_HT, RowVersion
from yugabyte_db_tpu.storage.scan_spec import ScanResult, ScanSpec
from yugabyte_db_tpu.utils import planes as P
from yugabyte_db_tpu.utils.fault_injection import FaultInjected, maybe_fault
from yugabyte_db_tpu.utils.jitting import compile_contract
from yugabyte_db_tpu.utils.metrics import (count_flush_path,
                                           count_host_verify_rows,
                                           count_swallowed)

# Failures the circuit breaker attributes to the DEVICE path: injected
# dispatch faults and runtime errors out of the device framework
# (compile/dispatch/transfer). Deliberately narrow — Status-carrying
# errors (e.g. a propagated deadline) and programming errors
# (Type/Key/Index) are NOT device faults and propagate unchanged.
DEVICE_FAULT_TYPES = (FaultInjected, RuntimeError)

WINDOW_BLOCKS = 8          # blocks per device dispatch on the row path
PAD_BLOCKS = 64            # run block-axis padding (multiple of every window)
# Compaction unions at/below this size take the host-vectorized
# retention mask (ops.compact.gc_mask_host): the link's fixed
# per-dispatch fence + index upload costs more than ~15 numpy passes.
HOST_GC_MASK_MAX = 2_000_000


# Round-robin cursor for --tpu_run_placement=round_robin (module-level:
# placement balances across ALL engines in the process, which is the
# point — one tserver, one local mesh).
_PLACE_LOCK = threading.Lock()
_PLACE_NEXT = 0


def _place_run():
    """The device a new run's planes will live on, per
    --tpu_run_placement."""
    from yugabyte_db_tpu.utils.flags import FLAGS

    global _PLACE_NEXT
    devs = jax.local_devices()
    try:
        policy = FLAGS.get("tpu_run_placement")
    except KeyError:
        policy = "default"
    if policy != "round_robin" or len(devs) == 1:
        return devs[0]
    with _PLACE_LOCK:
        d = devs[_PLACE_NEXT % len(devs)]
        _PLACE_NEXT += 1
    return d


class TpuRun:
    """A columnar run plus its managed device residency.

    ``.dev`` demand-uploads the run's DeviceRun through the process-wide
    residency cache (storage.residency) and may be evicted once the
    access returns when --tpu_hbm_budget_bytes is under pressure; the
    host ColumnarRun stays authoritative and re-uploads on the next
    access. Hold a :meth:`pin` across multi-dispatch windows so the
    accounting can't drop planes a dispatch still references."""

    def __init__(self, crun: ColumnarRun, device_tracker=None,
                 device=None):
        self.crun = crun
        self.host_index = None  # storage.host_page.HostPageIndex, lazy
        self._dev_nbytes_hint: int | None = None
        # The owning device: every demand (re-)upload for this run
        # targets it, so eviction/readmission cycles never migrate a
        # run's bytes into another chip's budget bucket.
        self.jax_device = device if device is not None else _place_run()
        self._res_key = hbm_cache().register(
            self, device_tracker, "run",
            device=device_label(self.jax_device))

    def _build_dev(self):
        d = DeviceRun(self.crun, PAD_BLOCKS, device=self.jax_device)
        return d, d.nbytes

    def _nbytes_hint(self) -> int:
        if self._dev_nbytes_hint is None:
            self._dev_nbytes_hint = plane_nbytes(self.crun, PAD_BLOCKS)
        return self._dev_nbytes_hint

    @property
    def dev(self) -> DeviceRun:
        return self.device()

    def device(self, priority: str | None = None) -> DeviceRun:
        return hbm_cache().acquire(self._res_key, self._build_dev,
                                   nbytes_hint=self._nbytes_hint(),
                                   priority=priority)

    def pin(self, priority: str | None = None) -> DeviceRun:
        """Acquire + pin the device planes until :meth:`unpin` — the
        issue→finish dispatch windows' eviction guard."""
        return hbm_cache().pin(self._res_key, self._build_dev,
                               nbytes_hint=self._nbytes_hint(),
                               priority=priority)

    def unpin(self) -> None:
        hbm_cache().unpin(self._res_key)

    def peek_device(self) -> DeviceRun | None:
        """The resident DeviceRun if its planes are on device right now
        (e.g. just seeded by the device flush), else None — no demand
        upload, no LRU touch.  Lets the mesh stack update feed from
        already-resident planes without paying for a miss."""
        return hbm_cache().peek(self._res_key)

    def invalidate_device(self) -> None:
        """Drop any resident planes for a run that stays live (host
        planes rebuilt in place, e.g. ALTER adding columns).  The
        residency registration survives, so the next access demand
        re-uploads through the cache — budgeted and tracker-accounted,
        not the unmanaged unregistered-owner fallback."""
        hbm_cache().release(self._res_key)

    def retire(self) -> None:
        """Run leaving the run set for good (compaction, restore,
        close): drop resident planes and the registration itself."""
        hbm_cache().invalidate(self._res_key)

    def seed_device(self, dev: DeviceRun) -> None:
        """Admit an already-built DeviceRun (the device flush output) as
        this run's resident payload — budgeted and tracker-accounted
        like any demand upload; a no-op hit if something already
        uploaded. Eviction works normally afterwards: the host planes
        stay authoritative and the next access re-uploads."""
        hbm_cache().acquire(self._res_key, lambda: (dev, dev.nbytes),
                            nbytes_hint=self._nbytes_hint())

    def pallas_tensors(self, col_order: tuple):
        """Device tensors in the pallas kernel's ref order (bool planes
        cast to int32, cmp planes sliced), cached on — and evicted
        with — the run's residency entry."""
        cache = hbm_cache()
        aux_key = ("pallas", col_order)
        t = cache.aux_get(self._res_key, aux_key)
        if t is None:
            from yugabyte_db_tpu.ops import pallas_agg

            t = pallas_agg.gather_tensors(self.dev.arrays, col_order)
            cache.aux_put(self._res_key, aux_key, t, device_nbytes(t))
        return t


class _MaskedRun:
    """A TpuRun view with substituted device arrays (the delta overlay's
    valid-masked primary). Shares the source's ColumnarRun."""

    class _Dev:
        def __init__(self, B, arrays):
            self.B = B
            self.arrays = arrays

    def __init__(self, source: "TpuRun", arrays: dict):
        self.crun = source.crun
        self.source = source
        self.dev = _MaskedRun._Dev(source.dev.B, arrays)


class _CodePred:
    """A string predicate promoted to a device-EXACT int32 compare
    against a dictionary-encoded column's code plane
    (--tpu_plane_encoding): the per-run dictionary is sorted, so the
    engine bisects the literal into a code bound and the kernel compares
    codes — no host verify round, unlike the prefix-plane superset path.
    ``value`` is the already-translated int32 code bound; ``op`` is the
    (possibly rewritten) code compare to apply."""

    __slots__ = ("column", "op", "value")

    def __init__(self, column: str, op: str, value: int):
        self.column = column
        self.op = op
        self.value = value


class _OverlayState:
    """Cached delta-overlay state (TpuStorageEngine._overlay): the
    masked primary, the key-sorted dirty rows with a parallel key list
    and by-key map (what the incremental copy-on-write update bisects
    into), the cleared primary row indices, the memtable version count
    the state includes, and the per-read-point host-partial cache."""

    __slots__ = ("masked", "rows", "keys", "by_key", "idx", "mem_count",
                 "partial")

    def __init__(self, masked, rows, keys, by_key, idx, mem_count):
        self.masked = masked
        self.rows = rows
        self.keys = keys
        self.by_key = by_key
        self.idx = idx
        self.mem_count = mem_count
        self.partial: dict = {}


# _overlay_apply_delta verdict: the delta can't be applied (no memtable
# log) and the caller must rebuild from scratch.
_OVERLAY_REBUILD = object()


class TpuStorageEngine(StorageEngine):
    def __init__(self, schema: Schema, options: dict | None = None):
        super().__init__(schema, options)
        self.memtable = make_memtable()
        self.runs: list[TpuRun] = []
        self.mat = RowMaterializer(schema)
        self.flushed_frontier_ht = 0
        self.rows_per_block = self.options.get("rows_per_block", 2048)
        self._kinds = {c.col_id: dtype_kind(c.dtype)
                       for c in schema.value_columns}
        self._dtypes = {c.col_id: c.dtype for c in schema.value_columns}
        self._name_to_id = {c.name: c.col_id for c in schema.value_columns}
        self._key_col_names = {c.name for c in schema.key_columns}
        # Structural gather-plan cache; invalidated whenever the run set
        # changes (flush/compact). Holds strong refs to its TpuRuns, so
        # id(trun) keys can't be reused while cached.
        self._plan_cache: dict = {}
        # Delta-overlay cache for multi-source scans: (source runs,
        # memtable ref, memtable version count, state | None). Validity
        # is judged by identity + the monotone version counter, and the
        # tuple holds strong refs so nothing it names can be collected
        # and identity-reused underneath it.
        self._overlay_cache = None
        self._read_plane_cache: dict = {}
        self._wire_dtype_cache: dict = {}
        from yugabyte_db_tpu.storage.run_io import RunPersistence

        # Device-plane accounting: the runs' resident plane bytes, a
        # sibling subtree of memstore so /memz shows both residencies.
        # Charged and released per cache entry by the residency manager.
        from yugabyte_db_tpu.utils.memtracker import root_tracker

        self.device_tracker = root_tracker().child("device").child(
            self.mem_tracker.name)
        # Overlay pin bookkeeping: the cached delta-overlay state keeps
        # its primary run pinned (its masked arrays alias the primary's
        # planes) and its masked valid plane accounted as an external
        # residency entry until the cache is dropped.
        self._overlay_pinned: TpuRun | None = None
        self._overlay_ext_key: int | None = None
        # Fault domain: the breaker quarantines the device dispatch path
        # after repeated device faults; while open (and for one probe's
        # worth of half-open) every scan re-serves byte-identically from
        # the authoritative host structures (_serve_host_batch).
        from yugabyte_db_tpu.utils.flags import FLAGS

        self.breaker = CircuitBreaker(
            f"tpu_engine:{self.mem_tracker.name}",
            failure_threshold=int(self.options.get(
                "breaker_failure_threshold",
                FLAGS.get("tpu_breaker_failure_threshold"))),
            cooldown_s=float(self.options.get(
                "breaker_cooldown_s",
                FLAGS.get("tpu_breaker_cooldown_s"))))
        self.persist = RunPersistence(self.options.get("data_dir"))
        for entries in self.persist.load_all():
            crun = ColumnarRun.build(self.schema, entries, self.rows_per_block)
            self.runs.append(TpuRun(crun, self.device_tracker))
            self.flushed_frontier_ht = max(self.flushed_frontier_ht, crun.max_ht)
        # Plane-encoding observability: yb_plane_bytes{encoding} /
        # yb_plane_encoded_ratio sample plane_stats() at scrape time
        # (weakly held — a dropped engine falls out of the series).
        from yugabyte_db_tpu.utils.metrics import register_plane_stats

        register_plane_stats(self)

    # -- writes ------------------------------------------------------------
    def apply(self, rows: list[RowVersion]) -> None:
        self.memtable.apply(rows)
        self._after_apply()

    def apply_block(self, block: bytes) -> None:
        self.memtable.apply_block(block)
        self._after_apply()

    def _after_apply(self) -> None:
        from yugabyte_db_tpu.utils.flags import FLAGS

        limit = self.options.get("memtable_flush_versions",
                                 FLAGS.get("memtable_flush_versions"))
        if self.memtable.num_versions >= limit:
            self.flush()
            self.maybe_compact()
        self._track_memstore()

    # -- plane-encoding introspection --------------------------------------
    def plane_stats(self) -> dict:
        """Per-tablet plane-encoding byte accounting for the
        yb_plane_bytes{encoding} gauges and /memz: stored bytes per
        encoding kind vs the logical (plain-format) bytes they replace,
        over this engine's current run set. A run reports its encoded
        stats only once something has actually built its encoded tree
        (first device access under --tpu_plane_encoding=auto); until
        then — and always with the flag off — it counts as plain, so
        the ratio reflects bytes as stored, not a hypothetical."""
        by: dict[str, int] = {}
        logical = 0
        for t in list(self.runs):
            st = t.crun.enc_stats
            if st is not None:
                for k, v in st["by_encoding"].items():
                    by[k] = by.get(k, 0) + int(v)
                logical += int(st["logical_bytes"])
            else:
                nb = self._plain_run_nbytes(t.crun)
                by["plain"] = by.get("plain", 0) + nb
                logical += nb
        return {"tablet": self.mem_tracker.name, "by_encoding": by,
                "encoded_bytes": sum(by.values()),
                "logical_bytes": logical}

    @staticmethod
    def _plain_run_nbytes(crun: ColumnarRun) -> int:
        total = sum(a.nbytes for a in (
            crun.valid, crun.group_start, crun.tomb, crun.live,
            crun.ht_hi, crun.ht_lo, crun.exp_hi, crun.exp_lo))
        for col in crun.cols.values():
            total += col.set_.nbytes + col.isnull.nbytes
            total += col.cmp_planes.nbytes
            if col.arith is not None:
                total += col.arith.nbytes
        return total

    # -- lifecycle ---------------------------------------------------------
    def alter_schema(self, new_schema: Schema) -> None:
        """Adopt an evolved schema. Existing columnar runs were built
        against the old schema, so each gets zero planes for any ADDED
        column (all rows unset -> NULL) and a fresh device upload;
        dropped columns keep their (now unreachable) planes. The memtable
        flushes first so no old-schema rows build runs after the switch."""
        self.flush()
        super().alter_schema(new_schema)
        self.mat = RowMaterializer(new_schema)
        self._kinds = {c.col_id: dtype_kind(c.dtype)
                       for c in new_schema.value_columns}
        self._dtypes = {c.col_id: c.dtype for c in new_schema.value_columns}
        self._name_to_id = {c.name: c.col_id
                            for c in new_schema.value_columns}
        self._key_col_names = {c.name for c in new_schema.key_columns}
        self._plan_cache.clear()
        from yugabyte_db_tpu.storage.columnar import ColumnData

        for trun in self.runs:
            crun = trun.crun
            changed = False
            for c in new_schema.value_columns:
                if c.col_id in crun.cols:
                    continue
                B, R = crun.key_planes.shape[0], crun.R
                planes = 2 if c.dtype.device_planes == 2 else 1
                crun.cols[c.col_id] = ColumnData(
                    dtype=c.dtype,
                    set_=np.zeros((B, R), dtype=bool),
                    isnull=np.zeros((B, R), dtype=bool),
                    cmp_planes=np.zeros((B, R, planes), dtype=np.int32),
                    arith=(np.zeros((B, R), dtype=np.float32)
                           if c.dtype.is_numeric else None),
                    varlen=([[None] * R for _ in range(B)]
                            if not c.dtype.is_fixed_width else None),
                )
                changed = True
            crun.schema = new_schema
            trun.host_index = None  # column planes changed shape/set
            if changed:
                # Host planes grew: drop any resident upload (the next
                # access re-uploads the evolved planes) and recompute
                # the residency byte hint.
                trun.invalidate_device()
                trun._dev_nbytes_hint = None
        self._drop_overlay_cache()

    def flush(self) -> None:
        from yugabyte_db_tpu.utils.sync_point import sync_point

        sync_point("tpu_engine:flush:start")
        if self.memtable.is_empty:
            return
        if self.memtable.max_ht is not None:
            self.flushed_frontier_ht = max(self.flushed_frontier_ht,
                                           self.memtable.max_ht)
        # Device flush first: replay the memtable op log into sorted run
        # planes in one device scatter, leaving the run HBM-resident
        # with no separate upload (--tpu_device_flush). Host build when
        # ineligible or over the residency budget: the native one-C-pass
        # path, generic drain+build behind it.
        seeded = self._device_flush()
        if seeded is not None:
            crun, trun = seeded
            if self.persist.enabled:
                self.persist.save_new(list(crun.iter_entries()))
        else:
            count_flush_path("host")
            crun = ColumnarRun.build_from_memtable(
                self.schema, self.memtable, self.rows_per_block)
            if crun is None:
                entries = self.memtable.drain_sorted()
                self.persist.save_new(entries)
                crun = ColumnarRun.build(self.schema, entries,
                                         self.rows_per_block)
            elif self.persist.enabled:
                self.persist.save_new(list(crun.iter_entries()))
            trun = TpuRun(crun, self.device_tracker)
        self.runs.append(trun)
        self.memtable = make_memtable()
        self._plan_cache.clear()
        self._drop_overlay_cache()
        self._track_memstore()
        if len(self.runs) > 1:
            self._warm_overlay_scatter()
        sync_point("tpu_engine:flush:done")

    def _device_flush(self):
        """The device flush path: stage the memtable's apply-order op
        log through the columnar encoders, compute the flush sort
        (key asc, ht desc, write_id desc — drain_sorted()'s order) and
        block packing host-side with one stable argsort over memcmp
        keys, then materialize the sorted padded run planes in a single
        device scatter (ops.flush.replay_flush). The outputs seed the
        residency cache directly AND round-trip back as the host planes,
        so device and host content are byte-identical by construction.

        Returns (crun, trun) on success, None when ineligible — flag
        off, no op log (capped), keys beyond the exact 32-byte prefix,
        run over the HBM residency budget, breaker open, or a device
        fault mid-flush (recorded on the breaker) — sending the caller
        to the host build."""
        from yugabyte_db_tpu.ops import flush as dflush
        from yugabyte_db_tpu.utils.flags import FLAGS

        try:
            if not FLAGS.get("tpu_device_flush"):
                return None
        except KeyError:
            return None
        rows = self.memtable.versions_since(0)
        if not rows:
            return None
        n = len(rows)
        keys = [r.key for r in rows]
        max_key_len = max(map(len, keys))
        if max_key_len > 32:
            # Sorted-order group boundaries come from prefix-plane
            # equality — exact only when every key fits the 32-byte
            # device prefix (the same eligibility device compaction
            # enforces).
            return None
        R = self.rows_per_block
        # Stage apply-order planes through the columnar encoders: one
        # block whose row capacity is the bucketed op count (pad rows
        # are never gathered, and bucketing keeps the device program
        # count bounded).
        m = 1 << max(10, (n - 1).bit_length())
        try:
            staged = ColumnarRun(self.schema, rows_per_block=m)
            staged.B = 1
            staged._alloc(1)
            staged._fill_block(0, [(r.key, [r]) for r in rows])
        except (OverflowError, ValueError, TypeError):
            return None  # value shape the encoders reject: host path
        wid = np.fromiter((r.write_id for r in rows), np.int64, n)
        sk = self._flush_sortkey(staged.key_planes[0, :n],
                                 staged.ht_hi[0, :n],
                                 staged.ht_lo[0, :n], wid)
        perm = np.argsort(sk, kind="stable").astype(np.int32)
        kw_s = staged.key_planes[0][perm]
        new_group = np.empty(n, dtype=bool)
        new_group[0] = True
        new_group[1:] = (kw_s[1:] != kw_s[:-1]).any(axis=1)
        gstarts = np.flatnonzero(new_group)
        sizes = np.diff(np.append(gstarts, n))
        try:
            ranges = ColumnarRun.pack_group_ranges(sizes.tolist(), R)
        except ValueError:
            return None  # an over-block key group: the host build's call
        B = len(ranges)
        Bp = padded_blocks(B, PAD_BLOCKS)
        budget = hbm_cache().budget()
        if budget and dflush.flush_plane_nbytes(Bp, R,
                                                self.schema) > budget:
            return None  # run exceeds the residency budget: host build
        if not self.breaker.allow():
            return None
        try:
            return self._device_flush_dispatch(
                rows, keys, staged, perm, kw_s, new_group, gstarts,
                sizes, ranges, Bp, max_key_len)
        except DEVICE_FAULT_TYPES as e:
            self.breaker.record_failure(e)
            return None
        except BaseException as e:
            # Any other raise still retires the half-open probe admitted by
            # allow() above — leaking it wedges the breaker's probe slot so
            # it could never close again. The error itself propagates.
            self.breaker.record_failure(e)
            raise

    def _device_flush_dispatch(self, rows, keys, staged, perm, kw_s,
                               new_group, gstarts, sizes, ranges, Bp,
                               max_key_len):
        from yugabyte_db_tpu.ops import flush as dflush
        from yugabyte_db_tpu.storage.columnar import BlockMeta

        self._device_fault_point()
        n = len(rows)
        m = staged.R
        R = self.rows_per_block
        B = len(ranges)
        rows_per = np.array([nr for _g0, _gn, nr in ranges], np.int64)
        block_of = np.repeat(np.arange(B, dtype=np.int64), rows_per)
        offs = np.cumsum(rows_per) - rows_per
        dst = (block_of * R
               + (np.arange(n, dtype=np.int64)
                  - np.repeat(offs, rows_per))).astype(np.int32)
        pad = m - n
        # Pad rows: gather staged row 0, scatter out of range (dropped).
        perm_p = (np.concatenate([perm, np.zeros(pad, np.int32)])
                  if pad else perm)
        dst_p = (np.concatenate([dst, np.full(pad, Bp * R, np.int32)])
                 if pad else dst)
        gs_p = (np.concatenate([new_group, np.zeros(pad, bool)])
                if pad else new_group)
        staged_tree = {
            "ht_hi": staged.ht_hi[0], "ht_lo": staged.ht_lo[0],
            "exp_hi": staged.exp_hi[0], "exp_lo": staged.exp_lo[0],
            "tomb": staged.tomb[0], "live": staged.live[0],
            "cols": {},
        }
        dict_cols = self._flush_dict_cols(staged, n)
        for cid, col in staged.cols.items():
            entry = {"set": col.set_[0], "isnull": col.isnull[0]}
            if cid in dict_cols:
                codes, dhi, dlo, _uniq = dict_cols[cid]
                entry["codes"] = codes
                entry["dhi"] = dhi
                entry["dlo"] = dlo
            else:
                entry["cmp"] = col.cmp_planes[0]
            if col.arith is not None:
                entry["arith"] = col.arith[0]
            staged_tree["cols"][cid] = entry
        is_real = np.zeros(Bp, dtype=bool)
        is_real[:B] = True
        ehi, elo = P.scalar_ht_planes(MAX_HT)
        out = dflush.replay_flush(staged_tree, perm_p, dst_p, gs_p,
                                  is_real, ehi, elo, R=R)
        # The device planes round-trip back as the run's HOST planes
        # (one copy per plane; np.array so they're owned and writable —
        # never a read-only view of a device buffer).
        host = jax.tree_util.tree_map(np.array, out)

        run = ColumnarRun(self.schema, R)
        run.B = B
        run._alloc(B)
        run.valid = host["valid"][:B]
        run.group_start = host["group_start"][:B]
        run.tomb = host["tomb"][:B]
        run.live = host["live"][:B]
        run.ht_hi = host["ht_hi"][:B]
        run.ht_lo = host["ht_lo"][:B]
        run.exp_hi = host["exp_hi"][:B]
        run.exp_lo = host["exp_lo"][:B]
        for cid, col in run.cols.items():
            h = host["cols"][cid]
            col.set_ = h["set"][:B]
            col.isnull = h["isnull"][:B]
            hc = h["cmp"]
            if isinstance(hc, dict):
                # Dict-encoded on device; the authoritative host planes
                # decode the codes through the dictionary (numpy gather
                # — byte-identical to what the device kernels decode).
                e = hc["dict"]
                codes = e["codes"][:B].astype(np.int64)
                col.cmp_planes = np.ascontiguousarray(np.stack(
                    [e["dhi"][codes], e["dlo"][codes]],
                    axis=-1).astype(np.int32))
            else:
                col.cmp_planes = hc[:B]
            if col.arith is not None:
                col.arith = h["arith"][:B]

        # Keys and row payloads stay host-side (no key planes on
        # device): the same flat scatter, in numpy.
        def scatter(dest, vals):
            dest.reshape((B * R,) + dest.shape[2:])[dst] = vals

        scatter(run.key_planes, kw_s)
        keys_arr = np.empty(n, dtype=object)
        keys_arr[:] = keys
        keys_s = keys_arr[perm]
        scatter(run.row_keys, keys_s)
        vers_arr = np.empty(n, dtype=object)
        vers_arr[:] = rows
        scatter(run.row_versions, vers_arr[perm])
        bpos = dst // R
        rpos = dst % R
        for cid, col in run.cols.items():
            if col.varlen is None:
                continue
            src = staged.cols[cid].varlen[0]
            for j in range(n):
                v = src[perm[j]]
                if v is not None:
                    col.varlen[bpos[j]][rpos[j]] = v

        group_keys = keys_s[gstarts]
        for b, (g0, gn, nrows) in enumerate(ranges):
            run.blocks[b] = BlockMeta(group_keys[g0],
                                      group_keys[g0 + gn - 1], nrows)
        run.min_key = group_keys[0]
        run.max_key = run.blocks[B - 1].max_key
        run.num_versions = n
        run.max_ht = staged.max_ht
        run.max_group_versions = int(sizes.max())
        run.max_key_len = max_key_len
        run.varlen_max_len = dict(staged.varlen_max_len)

        trun = TpuRun(run, self.device_tracker)
        trun.seed_device(DeviceRun.from_arrays(run, PAD_BLOCKS, out))
        self.breaker.record_success()
        count_flush_path("device")
        return run, trun

    def _flush_dict_cols(self, staged, n: int):
        """Per-column flush dictionaries (--tpu_plane_encoding): sorted
        unique set non-null raw values of the staged op-log rows ->
        {cid: (codes[m] u16, dhi, dlo, uniq)}. Built the same way
        ColumnarRun._encode_dict_col builds them from run planes, so a
        demand re-upload after eviction produces the SAME dictionary
        (same codes) as the flush-seeded device form."""
        from yugabyte_db_tpu.storage.columnar import _varlen_raw
        from yugabyte_db_tpu.utils.flags import FLAGS

        try:
            if FLAGS.get("tpu_plane_encoding") == "off":
                return {}
        except KeyError:
            return {}
        out = {}
        m = staged.R
        for cid, col in staged.cols.items():
            if col.varlen is None:
                continue
            nn = col.set_[0, :n] & ~col.isnull[0, :n]
            idxs = np.nonzero(nn)[0]
            if idxs.size == 0:
                continue
            src = col.varlen[0]
            raws = [_varlen_raw(src[i]) for i in idxs.tolist()]
            uniq = sorted(set(raws))
            if len(uniq) > encodings.DICT_MAX_VALUES:
                continue  # overflow: prefix planes, like the host encoder
            cap = encodings.pow2_bucket(len(uniq) + 1)
            code_of = {v: i for i, v in enumerate(uniq)}
            codes = np.full(m, cap - 1, np.int64)
            codes[idxs] = [code_of[v] for v in raws]
            hi, lo = P.varlen_prefix_planes(uniq)
            dhi = np.zeros(cap, np.int32)
            dlo = np.zeros(cap, np.int32)
            dhi[:len(uniq)] = hi
            dlo[:len(uniq)] = lo
            out[cid] = (codes.astype(np.uint16), dhi, dlo, uniq)
        return out

    @staticmethod
    def _flush_sortkey(kw_part, ht_hi_part, ht_lo_part, wid):
        """_sortkey_bytes plus a trailing inverted write_id: the FLUSH
        order (key asc, ht desc, write_id desc) — exactly
        drain_sorted()'s version order — as ONE memcmp key per row."""
        n, W = kw_part.shape
        buf = np.empty((n, W + 4), dtype=np.uint32)
        buf[:, :W] = (kw_part.view(np.uint32)
                      ^ np.uint32(0x80000000)).byteswap()
        buf[:, W] = (~(ht_hi_part.view(np.uint32)
                       ^ np.uint32(0x80000000))).byteswap()
        buf[:, W + 1] = (~(ht_lo_part.view(np.uint32)
                           ^ np.uint32(0x80000000))).byteswap()
        w = wid.view(np.uint64)
        buf[:, W + 2] = (~(w >> np.uint64(32))
                         .astype(np.uint32)).byteswap()
        buf[:, W + 3] = (~(w & np.uint64(0xFFFFFFFF))
                         .astype(np.uint32)).byteswap()
        return np.ascontiguousarray(buf).view(
            f"S{4 * (W + 4)}").reshape(n)

    _scatter_warmed: set = set()
    _scatter_warm_lock = __import__("threading").Lock()

    def _warm_overlay_scatter(self) -> None:
        """Compile the overlay's valid-plane scatter programs off the
        critical path: a second run means the next scan likely builds a
        delta overlay, and its first dispatch would otherwise pay the
        XLA compile inside the measured scan. One background compile per
        (plane shape, index bucket), process-wide. The shape is computed
        host-side (padded_blocks x R) so warmup neither forces the
        primary's planes resident nor depends on cache state — the keys
        stay identical to what _overlay dispatches, full or incremental."""
        primary = max(self.runs, key=lambda t: t.crun.total_rows())
        shape = (padded_blocks(primary.crun.B, PAD_BLOCKS),
                 primary.crun.R)
        size = shape[0] * shape[1]
        todo = [b for b in self._MASK_BUCKETS if b <= 65536
                and (shape, b) not in TpuStorageEngine._scatter_warmed]
        if not todo:
            return

        def warm():
            try:
                valid = jnp.zeros(shape, dtype=bool)
            except Exception as e:  # noqa: BLE001 — warmup best-effort
                count_swallowed("tpu_engine.scatter_warmup", e)
                return
            for b in todo:
                key = (shape, b)
                with TpuStorageEngine._scatter_warm_lock:
                    if key in TpuStorageEngine._scatter_warmed:
                        continue
                    TpuStorageEngine._scatter_warmed.add(key)
                try:
                    idx = jnp.full((b,), size, dtype=jnp.int32)
                    TpuStorageEngine._scatter_invalid(valid, idx)
                except Exception as e:  # noqa: BLE001 — warmup best-effort
                    count_swallowed("tpu_engine.scatter_warmup", e)

        import threading

        threading.Thread(target=warm, daemon=True).start()

    def compact(self, history_cutoff_ht: int = 0) -> None:
        """Merge all runs into one, GCing history at the cutoff. The
        k-way merge ORDER and the GC decisions run as one device dispatch
        (ops.compact: lexsort by key planes + vectorized retention mask)
        whenever every key fits the exact 32-byte device prefix; the host
        then materializes the merged run with a single linear pass. Falls
        back to the host heap merge otherwise (BASELINE config 4;
        reference hot loop: CompactionJob::Run,
        src/yb/rocksdb/db/compaction_job.cc:622)."""
        if len(self.runs) <= 1 and history_cutoff_ht == 0:
            return
        # Bulk object churn (hundreds of thousands of row objects moving
        # between containers) makes the cyclic GC fire on allocation and
        # rescan the whole heap repeatedly — measured 27x slowdown on
        # plain object-array fills. Nothing here creates cycles; pause
        # collection for the duration (the reference's arena-allocated
        # compaction has no analogous cost).
        import gc

        gc_was = gc.isenabled()
        gc.disable()
        try:
            self._compact_locked(history_cutoff_ht)
        finally:
            if gc_was:
                gc.enable()

    def _compact_locked(self, history_cutoff_ht: int) -> None:
        result = None
        if self.runs and all(t.crun.max_key_len <= 32 for t in self.runs) \
                and sum(t.crun.num_versions for t in self.runs) > 0:
            result = self._device_compact_entries(history_cutoff_ht)
        if result is None:
            from yugabyte_db_tpu.storage.cpu_engine import CpuStorageEngine
            from yugabyte_db_tpu.storage.merge import merge_entry_streams

            merged = []
            for key, versions in merge_entry_streams(
                    [t.crun.iter_entries() for t in self.runs]):
                kept = CpuStorageEngine._gc_versions(key, versions,
                                                     history_cutoff_ht)
                if kept:
                    merged.append((key, kept))
            crun = (ColumnarRun.build(self.schema, merged,
                                      self.rows_per_block)
                    if merged else None)
            self.persist.replace_all(merged)
        else:
            make_entries, crun = result
            # The (key, versions) entry list exists only for durability;
            # materialize it lazily — an in-memory engine (data_dir=None)
            # skips the 1-tuple-per-group Python walk entirely.
            self.persist.replace_all(make_entries()
                                     if self.persist.enabled else [])
        old_runs = [t for t in self.runs]
        self.runs = ([TpuRun(crun, self.device_tracker)]
                     if crun is not None else [])
        self._plan_cache.clear()
        self._drop_overlay_cache()
        for t in old_runs:
            t.retire()

    def _drop_overlay_cache(self) -> None:
        """Forget the cached delta-overlay state, releasing its pin on
        the primary run and its masked-valid residency accounting. Must
        run whenever the run set changes (flush/compact/restore/alter) —
        validity checks alone would leak the pin."""
        self._overlay_cache = None
        if self._overlay_pinned is not None:
            self._overlay_pinned.unpin()
            self._overlay_pinned = None
        if self._overlay_ext_key is not None:
            hbm_cache().invalidate(self._overlay_ext_key)
            self._overlay_ext_key = None

    def close(self) -> None:
        self._drop_overlay_cache()
        for t in self.runs:
            t.retire()
        self.device_tracker.detach()
        super().close()

    def _device_gc_fits_budget(self) -> bool:
        """Compaction's resident mask needs every run pinned at once;
        under a budget smaller than the union's plane bytes that would
        force pinned overflow, so the caller falls back to the
        host-vectorized mask instead."""
        b = hbm_cache().budget()
        if not b:
            return True
        return sum(t._nbytes_hint() for t in self.runs) <= b

    def _device_compact_entries(self, cutoff: int):
        """Device merge+GC -> (entries, merged ColumnarRun), or None when
        the union is empty. The merged run is assembled by GATHERING the
        surviving rows' existing planes (numpy) instead of re-encoding
        every version through ColumnarRun.build — the whole pipeline is
        vectorized except one linear grouping pass."""
        from yugabyte_db_tpu.ops import compact as dcompact

        crs = [t.crun for t in self.runs]
        parts_kw, parts = [], {k: [] for k in
                               ("ht_hi", "ht_lo", "exp_hi", "exp_lo",
                                "tomb", "live")}
        col_ids = [c.col_id for c in self.schema.value_columns]
        set_parts = {cid: [] for cid in col_ids}
        null_parts = {cid: [] for cid in col_ids}
        cmp_parts = {cid: [] for cid in col_ids}
        arith_parts = {cid: [] for cid in col_ids}
        varlen_all = {cid: [] for cid in col_ids}
        run_row_counts = []
        for cr in crs:
            nrun = 0
            for b in range(cr.B):
                nv = cr.blocks[b].num_valid
                if nv == 0:
                    continue
                nrun += nv
                parts_kw.append(cr.key_planes[b, :nv])
                parts["ht_hi"].append(cr.ht_hi[b, :nv])
                parts["ht_lo"].append(cr.ht_lo[b, :nv])
                parts["exp_hi"].append(cr.exp_hi[b, :nv])
                parts["exp_lo"].append(cr.exp_lo[b, :nv])
                parts["tomb"].append(cr.tomb[b, :nv])
                parts["live"].append(cr.live[b, :nv])
                for cid in col_ids:
                    col = cr.cols[cid]
                    set_parts[cid].append(col.set_[b, :nv])
                    null_parts[cid].append(col.isnull[b, :nv])
                    cmp_parts[cid].append(col.cmp_planes[b, :nv])
                    if col.arith is not None:
                        arith_parts[cid].append(col.arith[b, :nv])
                    if col.varlen is not None:
                        varlen_all[cid].extend(col.varlen[b][:nv])
            run_row_counts.append(nrun)
        if not parts_kw:
            return None
        N = sum(run_row_counts)
        # Pad to a size bucket so the compiled program is reused; pad rows
        # carry max key planes (sort last) and the plane encoding of
        # hybrid time 0 (visible, never a contributor), and are dropped by
        # the perm < N filter regardless.
        Np = 1 << max(10, (N - 1).bit_length())
        pad = Np - N
        ZLO = -(1 << 31)  # low plane of value 0 (bias-flipped)

        def cat(lst, fill):
            arr = np.concatenate(lst)
            if pad:
                shape = (pad,) + arr.shape[1:]
                arr = np.concatenate(
                    [arr, np.full(shape, fill, dtype=arr.dtype)])
            return arr

        kw = cat(parts_kw, np.iinfo(np.int32).max)
        ht_hi = cat(parts["ht_hi"], 0)
        ht_lo = cat(parts["ht_lo"], ZLO)

        # Merge ORDER host-side, as a k-way merge of the PRESORTED runs
        # (each run is (key asc, ht desc) by construction) over memcmp
        # sort keys — vectorized C, ~6x cheaper than np.lexsort of the
        # union, which XLA's variadic sort can't replace either (its
        # 10-key lexsort compiles catastrophically slowly, measured).
        # The retention decisions run on device (ops.compact docstring).
        run_items = []
        off = 0
        for t, nrows in zip(self.runs, run_row_counts):
            if nrows == 0:
                continue
            sk = self._sortkey_bytes(kw[off:off + nrows],
                                     ht_hi[off:off + nrows],
                                     ht_lo[off:off + nrows])
            run_items.append((np.arange(off, off + nrows,
                                        dtype=np.int64), sk))
            off += nrows
        perm = self._merge_sorted(run_items)
        if pad:
            perm = np.concatenate(
                [perm, np.arange(N, Np, dtype=np.int64)])
        skw = kw[perm]
        s_ht_hi = ht_hi[perm]
        s_ht_lo = ht_lo[perm]
        new_group = np.empty(Np, dtype=bool)
        new_group[0] = True
        new_group[1:] = (skw[1:] != skw[:-1]).any(axis=1)

        exp_hi = cat(parts["exp_hi"], 0)
        exp_lo = cat(parts["exp_lo"], ZLO)
        tomb = cat(parts["tomb"], False)
        live = cat(parts["live"], False)
        cat_set = {cid: cat(set_parts[cid], False) for cid in col_ids}

        c_hi, c_lo = P.scalar_ht_planes(max(cutoff, 0))
        keep_dev = None
        gc_pins: list[TpuRun] = []
        try:
            if N > HOST_GC_MASK_MAX and self._device_gc_fits_budget():
                # Device retention mask over RESIDENT planes: upload only
                # the sorted flat-index vector (union position -> row in
                # the concatenation of the runs' flattened device planes)
                # and the group bits — the planes never re-cross the link.
                # Every run is pinned for the dispatch window so eviction
                # can't drop planes the mask program still references.
                for t in self.runs:
                    t.pin("low")
                    gc_pins.append(t)
                R = self.rows_per_block
                offsets = np.cumsum(
                    [0] + [t.dev.B * R for t in self.runs])[:-1]
                src_parts = []
                for t, off in zip(self.runs, offsets):
                    cr = t.crun
                    for b in range(cr.B):
                        nv = cr.blocks[b].num_valid
                        if nv:
                            src_parts.append(np.arange(
                                off + b * R, off + b * R + nv,
                                dtype=np.int32))
                if pad:
                    src_parts.append(np.full(pad, -1, np.int32))
                src = np.concatenate(src_parts)
                idx = src[perm]
                runs_planes = tuple(
                    {"ht_hi": t.dev.arrays["ht_hi"],
                     "ht_lo": t.dev.arrays["ht_lo"],
                     "exp_hi": t.dev.arrays["exp_hi"],
                     "exp_lo": t.dev.arrays["exp_lo"],
                     "tomb": t.dev.arrays["tomb"],
                     "live": t.dev.arrays["live"],
                     "sets": tuple(t.dev.arrays["cols"][cid]["set"]
                                   for cid in col_ids)}
                    for t in self.runs)
                cutoff_planes = (jnp.int32(c_hi), jnp.int32(c_lo),
                                 jnp.int32(c_hi), jnp.int32(c_lo))
                keep_dev = dcompact.resident_gc_mask(
                    runs_planes, jnp.asarray(idx),
                    jnp.asarray(new_group), cutoff_planes)
                keep_dev.copy_to_host_async()
            else:
                # Small unions (or budgets too tight to pin the whole
                # union): the host-vectorized twin beats the link's
                # fixed per-dispatch fence + index upload.
                keep = dcompact.gc_mask_host(
                    len(col_ids),
                    {"new_group": new_group, "ht_hi": s_ht_hi,
                     "ht_lo": s_ht_lo, "exp_hi": exp_hi[perm],
                     "exp_lo": exp_lo[perm], "tomb": tomb[perm],
                     "live": live[perm],
                     "set_": [cat_set[cid][perm] for cid in col_ids]},
                    (c_hi, c_lo, c_hi, c_lo))

            # While any device mask computes/streams back, do the host
            # work that doesn't need it: collect the row-level Python
            # payloads (block VIEWS of the runs' object ndarrays, one
            # pointer-copying concatenate per payload).
            valid_blocks = [(cr, b, cr.blocks[b].num_valid)
                            for cr in crs for b in range(cr.B)
                            if cr.blocks[b].num_valid]
            all_keys = np.concatenate(
                [cr.row_keys[b, :nv] for cr, b, nv in valid_blocks])
            all_vers = np.concatenate(
                [cr.row_versions[b, :nv] for cr, b, nv in valid_blocks])
            all_kvs = np.concatenate(
                [cr.row_key_vals[b, :nv] for cr, b, nv in valid_blocks])
            if keep_dev is not None:
                keep = jax.device_get(keep_dev)
        finally:
            for t in gc_pins:
                t.unpin()

        kept_pos = np.nonzero(keep[:].astype(bool) & (perm < N))[0]
        kept_src = perm[kept_pos]
        if kept_src.size == 0:
            return (lambda: []), None
        # Group boundaries among KEPT rows (still key-sorted).
        gid_sorted = np.cumsum(new_group.astype(np.int64)) - 1
        kept_gids = gid_sorted[kept_pos]
        kept_new_group = np.empty(kept_src.size, dtype=bool)
        kept_new_group[0] = True
        kept_new_group[1:] = kept_gids[1:] != kept_gids[:-1]

        # Survivor (key, versions) groups via one fancy index + per-group
        # slices (C-speed object-array copies; the per-row append loop
        # was the second compaction hot spot). Deferred: only the
        # durability path needs the entry-list form.
        kept_keys = all_keys[kept_src]
        kept_vers = all_vers[kept_src]

        def make_entries() -> list[tuple[bytes, list]]:
            group_starts = np.nonzero(kept_new_group)[0].tolist()
            group_ends = group_starts[1:] + [kept_src.size]
            return [(kept_keys[g0], kept_vers[g0:g1].tolist())
                    for g0, g1 in zip(group_starts, group_ends)]

        planes = {
            "ht_hi": ht_hi, "ht_lo": ht_lo, "exp_hi": exp_hi,
            "exp_lo": exp_lo, "tomb": tomb, "live": live,
            "set": cat_set,
        }
        crun = self._gather_run(kept_src, kept_new_group, all_keys,
                                all_vers, all_kvs, kw, planes, col_ids,
                                null_parts, cmp_parts, arith_parts,
                                varlen_all)
        return make_entries, crun

    def _gather_run(self, kept_src, kept_new_group, all_keys, all_vers,
                    all_kvs, kw, planes, col_ids, null_parts, cmp_parts,
                    arith_parts, varlen_all):
        """Assemble the merged ColumnarRun by numpy-gathering surviving
        rows' planes (no per-version re-encoding)."""
        R = self.rows_per_block
        nk = kept_src.size
        bounds = np.nonzero(kept_new_group)[0].tolist() + [nk]
        sizes = [bounds[gi + 1] - bounds[gi]
                 for gi in range(len(bounds) - 1)]
        max_group = max(sizes) if sizes else 0
        # (kept start row, row count) per block via the SHARED packing.
        ranges = [(bounds[g0], rows)
                  for g0, _gn, rows in ColumnarRun.pack_group_ranges(
                      sizes, R)]

        run = ColumnarRun(self.schema, R)
        B = len(ranges)
        run.B = B
        run._alloc(B)
        from yugabyte_db_tpu.storage.columnar import BlockMeta

        cat_null = {cid: np.concatenate(null_parts[cid])
                    for cid in col_ids}
        cat_cmp = {cid: np.concatenate(cmp_parts[cid]) for cid in col_ids}
        cat_set = planes["set"]
        cat_arith = {cid: (np.concatenate(arith_parts[cid])
                           if arith_parts[cid] else None)
                     for cid in col_ids}
        ht_hi_u = planes["ht_hi"]
        ht_lo_u = planes["ht_lo"]
        exp_hi_u = planes["exp_hi"]
        exp_lo_u = planes["exp_lo"]
        tomb_u = planes["tomb"]
        live_u = planes["live"]

        # One flat scatter per plane: kept row j lands at (block_of[j],
        # pos[j]) — the per-block slice loop was the remaining gather
        # hot spot.
        starts = np.array([s0 for s0, _n in ranges], dtype=np.int64)
        ns = np.array([n for _s0, n in ranges], dtype=np.int64)
        block_of = np.repeat(np.arange(B, dtype=np.int64), ns)
        dst = block_of * R + (np.arange(nk, dtype=np.int64)
                              - np.repeat(starts, ns))

        def scatter(dest, vals):
            dest.reshape((B * R,) + dest.shape[2:])[dst] = vals

        scatter(run.key_planes, kw[kept_src])
        scatter(run.ht_hi, ht_hi_u[kept_src])
        scatter(run.ht_lo, ht_lo_u[kept_src])
        scatter(run.exp_hi, exp_hi_u[kept_src])
        scatter(run.exp_lo, exp_lo_u[kept_src])
        scatter(run.tomb, tomb_u[kept_src])
        scatter(run.live, live_u[kept_src])
        run.valid.reshape(-1)[dst] = True
        scatter(run.group_start, kept_new_group)
        for cid in col_ids:
            col = run.cols[cid]
            scatter(col.set_, cat_set[cid][kept_src])
            scatter(col.isnull, cat_null[cid][kept_src])
            scatter(col.cmp_planes, cat_cmp[cid][kept_src])
            if col.arith is not None and cat_arith[cid] is not None:
                scatter(col.arith, cat_arith[cid][kept_src])
        scatter(run.row_keys, all_keys[kept_src])
        scatter(run.row_versions, all_vers[kept_src])
        scatter(run.row_key_vals, all_kvs[kept_src])
        has_varlen = any(run.cols[cid].varlen is not None
                         for cid in col_ids)
        for b, (s0, n) in enumerate(ranges):
            if has_varlen:
                sel_list = kept_src[s0:s0 + n].tolist()
                for cid in col_ids:
                    col = run.cols[cid]
                    if col.varlen is not None:
                        vl = varlen_all[cid]
                        col.varlen[b][:n] = [vl[i] for i in sel_list]
            run.blocks[b] = BlockMeta(run.row_keys[b][0],
                                      run.row_keys[b][n - 1], n)
        run.min_key = run.row_keys[0][0]
        run.max_key = run.blocks[B - 1].max_key
        run.num_versions = nk
        run.max_ht = int(P.planes_to_u64(ht_hi_u[kept_src],
                                         ht_lo_u[kept_src]).max())
        run.max_group_versions = max_group
        # Exact (not inherited) maxima over SURVIVING rows, so GC'd long
        # values/keys don't disable device-exact paths forever.
        kept_keys_flat = all_keys[kept_src]
        run.max_key_len = max(run.max_key_len, int(np.fromiter(
            map(len, kept_keys_flat), np.int64,
            kept_keys_flat.size).max()))
        for b in range(run.B):
            n = run.blocks[b].num_valid
            for cid in col_ids:
                vl = run.cols[cid].varlen
                if vl is None:
                    continue
                # ASCII-dominant workloads: len(str) == encoded length; only
                # re-measure the (rare) non-ASCII cells byte-exactly.
                from yugabyte_db_tpu.storage.columnar import _varlen_raw
                lens = [len(v) if (isinstance(v, str) and v.isascii())
                        else len(v) if isinstance(v, (bytes, bytearray))
                        else len(_varlen_raw(v))
                        for v in vl[b][:n] if v is not None]
                if lens:
                    run.varlen_max_len[cid] = max(
                        run.varlen_max_len.get(cid, 0), max(lens))
        return run

    def restore_entries(self, entries) -> None:
        self.memtable = make_memtable()
        self.persist.replace_all(entries)
        old_runs = list(self.runs)
        if entries:
            crun = ColumnarRun.build(self.schema, entries,
                                     self.rows_per_block)
            self.runs = [TpuRun(crun, self.device_tracker)]
            self.flushed_frontier_ht = max(self.flushed_frontier_ht,
                                           crun.max_ht)
        else:
            self.runs = []
        self._plan_cache.clear()
        self._drop_overlay_cache()
        for t in old_runs:
            t.retire()

    def dump_entries(self):
        """All flushed (key, versions ht-desc) pairs, key-merged across
        runs — the storage payload of a remote-bootstrap session."""
        from yugabyte_db_tpu.storage.merge import merge_entry_streams

        return list(merge_entry_streams(
            [t.crun.iter_entries() for t in self.runs]))

    def stats(self) -> dict:
        return {
            "num_runs": len(self.runs),
            "memtable_versions": self.memtable.num_versions,
            "run_versions": sum(t.crun.num_versions for t in self.runs),
            "flushed_frontier_ht": self.flushed_frontier_ht,
            # True residency: what the cache currently holds for this
            # engine (demand uploads minus evictions), not the run total.
            "device_bytes": self.device_tracker.consumption,
        }

    # -- scan plumbing ------------------------------------------------------
    @staticmethod
    def _prune_prefix(spec: ScanSpec) -> bytes | None:
        """The hashed-components prefix shared by EVERY key in the scan
        range, or None. Present for point gets and single-primary-key
        range scans — the shapes the per-run bloom prunes."""
        if not spec.lower or not spec.upper:
            return None
        from yugabyte_db_tpu.models.encoding import (hashed_prefix,
                                                     prefix_successor)

        hp = hashed_prefix(spec.lower)
        if not hp:
            return None
        ps = prefix_successor(hp)
        if ps and spec.upper > ps:
            return None  # range crosses out of the hash section
        return hp

    def _overlapping_runs(self, spec: ScanSpec) -> list[TpuRun]:
        out = []
        hp = self._prune_prefix(spec)
        for t in self.runs:
            if t.crun.num_versions == 0:
                continue
            if spec.upper and t.crun.min_key >= spec.upper:
                continue
            if t.crun.max_key < spec.lower:
                continue
            if hp is not None and not t.crun.may_contain_hashed(hp):
                continue
            out.append(t)
        return out

    def _memtable_in_range(self, spec: ScanSpec) -> bool:
        if self.memtable.is_empty:
            return False
        return self.memtable.has_keys(spec.lower, spec.upper)

    def _split_predicates(self, spec: ScanSpec):
        """(device-exact preds, device-superset preds, host-only preds).

        'str' prefixes and 'f32' rounded values give superset masks only
        (ties are maybe-matches the host verifies); key-column and IN
        predicates are host-only."""
        exact, superset, host_only = [], [], []
        for p in spec.predicates:
            if p.column in self._key_col_names or p.op == "IN":
                host_only.append(p)
                continue
            cid = self._name_to_id[p.column]
            dt = self._dtypes[cid]
            if not dt.is_fixed_width and dt not in (DataType.STRING,
                                                    DataType.BINARY):
                # opaque payloads (collections, jsonb): the device prefix
                # is repr-ordered, not value-ordered — host only
                host_only.append(p)
                continue
            kind = self._kinds[cid]
            if kind in ("str", "f32"):
                superset.append(p)
            else:
                exact.append(p)
        return exact, superset, host_only

    def _promote_code_preds(self, trun: TpuRun, preds):
        """Translate superset string predicates into device-EXACT
        dictionary-code predicates (_CodePred) against ``trun``'s
        per-run sorted dictionaries, or None when any predicate can't
        promote (encoding off, column not dictionary-encoded on this
        run — overflow fallback — or a non-range operator).

        The dictionary is the sorted unique set non-null values, so
        order-preserving code translation is a bisect:
        '<' v  -> code <  bisect_left,  '<=' v -> code <  bisect_right,
        '>' v  -> code >= bisect_right, '>=' v -> code >= bisect_left;
        '='/'!=' use the exact code, or -1 (matches/misses nothing set:
        every eval site ANDs with the column's notnull mask). Promotion
        requires the RESIDENT device form to be the encoded tree — a
        device-flush-seeded run stays plain in HBM until evicted."""
        dicts = getattr(trun.crun, "enc_dicts", None)
        if not dicts or trun.crun.encoded_arrays() is None:
            return None
        out = []
        for p in preds:
            cid = self._name_to_id[p.column]
            d = dicts.get(cid)
            if d is None or p.op not in ("=", "!=", "<", "<=", ">", ">="):
                return None
            raw = (p.value.encode("utf-8", "surrogateescape")
                   if isinstance(p.value, str) else bytes(p.value))
            if p.op in ("=", "!="):
                i = bisect.bisect_left(d, raw)
                code = i if i < len(d) and d[i] == raw else -1
                out.append(_CodePred(p.column, p.op, code))
            elif p.op == "<":
                out.append(_CodePred(p.column, "<",
                                     bisect.bisect_left(d, raw)))
            elif p.op == "<=":
                out.append(_CodePred(p.column, "<",
                                     bisect.bisect_right(d, raw)))
            elif p.op == ">":
                out.append(_CodePred(p.column, ">=",
                                     bisect.bisect_right(d, raw)))
            else:  # >=
                out.append(_CodePred(p.column, ">=",
                                     bisect.bisect_left(d, raw)))
        if not trun.dev.encoded:
            return None  # resident planes are the plain (seeded) form
        return out

    def _aggs_device_eligible(self, spec: ScanSpec) -> bool:
        """Device aggregates need every aggregate column to be a numeric
        VALUE column (key columns live in the encoded key, not in planes;
        string min/max needs full bytes the device doesn't have)."""
        for a in spec.aggregates:
            if a.column is None:
                continue
            cid = self._name_to_id.get(a.column)
            if cid is None:
                return False  # key column (or unknown): host path
            if self._kinds[cid] == "str" and a.fn != "count":
                return False
        return True

    def _pred_kind(self, p) -> str:
        """Device plane kind a predicate compares against; promoted
        dictionary-code predicates compare the int32 code plane."""
        if isinstance(p, _CodePred):
            return "code"
        return self._kinds[self._name_to_id[p.column]]

    def _pred_sig_and_literals(self, preds, literal_fn=None):
        lit = _literal if literal_fn is None else literal_fn
        sigs, lits = [], []
        for p in preds:
            cid = self._name_to_id[p.column]
            kind = self._pred_kind(p)
            sigs.append(dscan.PredSig(cid, kind, p.op))
            lits.append(lit(kind, p.value))
        return tuple(sigs), tuple(lits)

    def _pred_sigs_only(self, preds):
        """PredSigs without materializing device literals (the gather path
        ships literals inside the params vector; creating jnp scalars here
        would queue one tiny host->device transfer per predicate ahead of
        the batched dispatch)."""
        return tuple(
            dscan.PredSig(self._name_to_id[p.column],
                          self._pred_kind(p), p.op)
            for p in preds)

    def _col_sigs(self):
        return tuple(dscan.ColSig(c.col_id, self._kinds[c.col_id])
                     for c in self.schema.value_columns)

    def _read_planes(self, spec: ScanSpec):
        return tuple(jnp.int32(v) for v in self._read_plane_ints(spec))

    @staticmethod
    def _scan_priority(spec: ScanSpec) -> str:
        """Residency-pool priority of a scan: unbounded full-table
        traffic is admitted low-pri (scan-resistant), bounded ranges and
        point shapes protect their runs in the high-pri pool."""
        return "low" if (not spec.lower and not spec.upper) else "high"

    def _device_candidates(self, trun: TpuRun, spec: ScanSpec,
                           pred_sigs, pred_lits, apply_preds: bool):
        """Run the device row-scan over the block windows covering the range;
        yield candidate keys (host-materialized, in key order)."""
        self._device_fault_point()
        crun = trun.crun
        row_lo = crun.lower_row(spec.lower)
        row_hi = crun.upper_row(spec.upper)
        if row_lo >= row_hi:
            return
        R = crun.R
        K = WINDOW_BLOCKS
        b_first = (row_lo // R) // K * K
        b_last = ((row_hi - 1) // R) // K * K
        sig = dscan.ScanSig(B=trun.dev.B, R=R, K=K, cols=self._col_sigs(),
                            preds=pred_sigs, aggs=(), apply_preds=apply_preds,
                            flat=crun.max_group_versions <= 1)
        fn = dscan.compiled_scan(sig)
        r_hi_, r_lo_, e_hi_, e_lo_ = self._read_planes(spec)
        for b0 in range(b_first, b_last + 1, K):
            base = b0 * R
            res = fn(trun.dev.arrays, jnp.int32(b0),
                     jnp.int32(np.clip(row_lo - base, -(1 << 30), 1 << 30)),
                     jnp.int32(np.clip(row_hi - base, -(1 << 30), 1 << 30)),
                     r_hi_, r_lo_, e_hi_, e_lo_, pred_lits)
            # One explicit fetch for all three outputs instead of a
            # blocking transfer per array.
            res = jax.device_get(res)
            mask = res["result"]
            ng = int(res["num_groups"])
            start = res["start_idx"]
            for g in np.nonzero(mask[:ng])[0]:
                yield crun.key_at(base + int(start[g]))

    # -- reads -------------------------------------------------------------
    # The host↔device link pays a full round-trip per *blocking* call,
    # ~ms per transferred array, and pipelines async dispatches (measured:
    # 10 async dispatches complete in ~1 RTT). Every scan therefore splits
    # into a plan step that DESCRIBES device work and a finish step that
    # decodes fetched results; scan_batch() groups all page scans with the
    # same static signature into one vmapped dispatch, issues everything
    # async, and fetches every output in one device_get.
    def scan(self, spec: ScanSpec) -> ScanResult:
        return self.scan_batch([spec])[0]

    # G buckets for the vmapped page-scan dispatch (one compile per bucket).
    _G_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)

    def scan_batch(self, specs: list[ScanSpec],
                   deadline=None) -> list[ScanResult]:
        return self.scan_batch_async(specs, deadline=deadline).finish()

    def _device_fault_point(self) -> None:
        """MAYBE_FAULT marker for the device dispatch path (flag
        ``fault.tpu_dispatch``): fires as the kind of failure the
        breaker quarantines."""
        if maybe_fault("fault.tpu_dispatch"):
            raise FaultInjected("injected device dispatch fault")

    def scan_batch_async(self, specs: list[ScanSpec], deadline=None):
        """Plan every scan, issue all round-1 device work, and start the
        outputs streaming host-ward (copy_to_host_async) WITHOUT waiting.
        The caller finishes the batch later with .finish().

        This is the server shape for the tunnel link: one synchronous
        fetch cycle costs ~1 link RTT regardless of size, but dispatches
        and async copies pipeline — so overlapping batches (issue N+1
        before finishing N) amortizes the RTT across whole batches.

        Fault containment: while the breaker quarantines the device path
        (or a device fault strikes during planning/dispatch) the batch
        is served from the authoritative host structures instead —
        byte-identical results, no device traffic. ``deadline``
        (utils.retry.Deadline) is the propagated RPC budget; an expired
        deadline aborts with Code.TIMED_OUT before any work is issued
        (and between finish()-time rounds), unwinding residency pins."""
        if deadline is not None:
            deadline.check("tpu_engine.scan_batch")
        if not self.breaker.allow():
            return _HostServeBatch(self, specs, deadline)
        try:
            return self._scan_batch_async_device(specs, deadline)
        except DEVICE_FAULT_TYPES as e:
            self.breaker.record_failure(e)
            return _HostServeBatch(self, specs, deadline)
        except BaseException as e:
            # A non-device raise (planning bug, expired deadline between
            # rounds) must still retire the probe allow() admitted, or the
            # breaker's half-open slot stays consumed forever.
            self.breaker.record_failure(e)
            raise

    def _scan_batch_async_device(self, specs: list[ScanSpec],
                                 deadline=None) -> "_AsyncBatch":
        self._device_fault_point()
        agg_sink: list = []
        grouped_sink: list = []
        plans = [self._plan_scan(s, agg_sink=agg_sink,
                                 grouped_sink=grouped_sink)
                 for s in specs]

        results: list = [None] * len(plans)
        issued_outs = []
        host_plans = []
        page_items: list[tuple[int, tuple]] = []
        gathers: list[tuple[int, "_GatherScan"]] = []
        pre_work = []
        deferred: list = []
        gdeferred: list = []
        for pi, plan in enumerate(plans):
            if plan[0] == "host":
                host_plans.append((pi, plan[1]))
            elif plan[0] == "page":
                page_items.append((pi, plan[1]))
            elif plan[0] == "issued":
                issued_outs.append((pi, plan[1], plan[2]))
                if len(plan) > 3:  # host work to overlap with the fetch
                    pre_work.append(plan[3])
            elif plan[0] == "agg_deferred":
                deferred.append(pi)
            elif plan[0] == "grouped_deferred":
                gdeferred.append(pi)
            else:
                gathers.append((pi, plan[1]))
        # Residency pins for the issue→finish window: every run a device
        # plan references stays resident until finish() releases it, so
        # eviction can't drop planes an in-flight dispatch still holds.
        # Unbounded full scans pin at low priority — they stream through
        # the cache's low-pri pool instead of flushing the protected
        # working set (the overlay's masked primary is pinned separately
        # by the engine's overlay cache).
        want_pins: dict[int, tuple[TpuRun, str]] = {}

        def want_pin(trun, priority):
            if isinstance(trun, _MaskedRun):
                return
            prev = want_pins.get(id(trun))
            if prev is None or priority == "high":
                want_pins[id(trun)] = (trun, priority)

        for _pi, st in gathers:
            want_pin(st.trun,
                     "low" if st.mode == "chunks" else "high")
        for trun, spec, _exact in agg_sink:
            want_pin(trun, self._scan_priority(spec))
        for item in grouped_sink:
            want_pin(item[0], self._scan_priority(item[1]))
        # Until the _AsyncBatch below takes ownership (its finish path
        # unpins), any failure while pinning or planning must unwind the
        # pins already taken, or those entries stay unevictable for the
        # process lifetime.
        pins = []
        try:
            for trun, priority in want_pins.values():
                trun.pin(priority)
                pins.append(trun)
            if deferred:
                # Single-source device aggregates dispatch together: one
                # vmapped program per (run, signature) group.
                items = [(pi, trun, spec, exact)
                         for pi, (trun, spec, exact)
                         in zip(deferred, agg_sink)]
                issued_outs.extend(
                    self._plan_device_aggregate_batch(items))
            if gdeferred:
                items = [(pi, trun, spec, exact, payload)
                         for pi, (trun, spec, exact, payload)
                         in zip(gdeferred, grouped_sink)]
                issued_outs.extend(self._plan_grouped_batch(items))
            # Page items defer wholesale to finish() (device work
            # first); host_page.serve_pages runs them through the
            # native page server.
            pages = page_items

            states = dict(gathers)
            pending = {pi: st.pending for pi, st in gathers
                       if st.pending}
            dispatches = (self._issue_round(states, pending)
                          if pending else [])
            for leaf in jax.tree.leaves([[d for _c, d in dispatches],
                                         [o for _pi, o, _f
                                          in issued_outs]]):
                leaf.copy_to_host_async()
            return _AsyncBatch(self, results, host_plans, issued_outs,
                               gathers, states, pending, dispatches,
                               pages, pre_work, pins, specs=specs,
                               deadline=deadline)
        except BaseException:
            for trun in pins:
                trun.unpin()
            raise

    def scan_batch_wire(self, specs: list[ScanSpec], fmt: str = "cql",
                        deadline=None):
        """Wire-serialized pages with the native fast path: LIMIT pages
        on a single flat run with host-exact predicates serialize to
        protocol bytes entirely in C (host_page.serve_pages_wire /
        native serve_page_wire_batch) — no Python value objects on the
        hot path. Point gets (exact-key ranges) keep a dedicated
        bloom-pruned per-key path that stays fast with a live memtable
        and overlapping runs. Everything else (multi-source range
        scans, aggregates, superset predicates) takes the
        scan + Python-serialize fallback, which produces identical
        bytes (models.wirefmt)."""
        fmt_id = host_page.WIRE_CQL if fmt == "cql" else host_page.WIRE_PG
        out = [None] * len(specs)
        mem = self.memtable
        fast_ok = (len(self.runs) == 1 and mem.is_empty
                   and self.runs[0].crun.num_versions > 0
                   and self.runs[0].crun.max_group_versions <= 1)
        slow_idx: list[int] = []
        slow_specs: list[ScanSpec] = []
        if fast_ok:
            trun = self.runs[0]
            items, item_idx = [], []
            for i, spec in enumerate(specs):
                if (spec.limit is not None
                        and spec.limit <= host_page.MAX_PAGE_LIMIT
                        and not spec.is_aggregate and not spec.group_by):
                    pred_items = host_page.encode_pred_items(
                        self, spec.predicates)
                    if pred_items is not None:
                        items.append((trun, spec, pred_items))
                        item_idx.append(i)
                        continue
                slow_idx.append(i)
                slow_specs.append(spec)
            if items:
                served = host_page.serve_pages_wire(self, items, fmt_id)
                for i, pg in zip(item_idx, served):
                    if pg is None:
                        slow_idx.append(i)
                        slow_specs.append(specs[i])
                    else:
                        out[i] = pg
        else:
            # Live memtable: most point reads still miss it (the YCSB
            # mixed steady state — updates touch a small dirty set), so
            # keys ABSENT from the memtable serve from the flat run via
            # the native page server exactly like the fast path; only
            # memtable hits pay the Python merge. The presence probe is
            # the native memtable's has_keys (C, O(log n)).
            run_ok = (len(self.runs) == 1
                      and self.runs[0].crun.num_versions > 0
                      and self.runs[0].crun.max_group_versions <= 1)
            trun = self.runs[0] if run_ok else None
            items, item_idx = [], []
            for i, spec in enumerate(specs):
                pk = self._point_key(spec)
                if pk is None:
                    slow_idx.append(i)
                    slow_specs.append(spec)
                    continue
                if (trun is not None and spec.limit is not None
                        and spec.limit <= host_page.MAX_PAGE_LIMIT
                        and not mem.has_keys(spec.lower, spec.upper)):
                    pred_items = host_page.encode_pred_items(
                        self, spec.predicates)
                    if pred_items is not None:
                        items.append((trun, spec, pred_items))
                        item_idx.append(i)
                        continue
                out[i] = self._point_get_wire(spec, fmt_id, mem, pk)
            if items:
                served = host_page.serve_pages_wire(self, items, fmt_id)
                for i, pg in zip(item_idx, served):
                    if pg is None:
                        out[i] = self._point_get_wire(
                            specs[i], fmt_id, mem,
                            self._point_key(specs[i]))
                    else:
                        out[i] = pg
        if slow_specs:
            for i, pg in zip(slow_idx,
                             super().scan_batch_wire(slow_specs, fmt,
                                                     deadline=deadline)):
                out[i] = pg
        return out

    def _point_key(self, spec: ScanSpec) -> bytes | None:
        from yugabyte_db_tpu.storage.scan_spec import point_key_of

        return point_key_of(spec, self.schema)

    def _point_versions(self, key: bytes, mem) -> list[RowVersion]:
        """Bloom-pruned per-key version lookup across runs + memtable —
        O(log run), no scan machinery (the reference's
        DocRowwiseIterator point-get over the IntentAwareIterator,
        src/yb/docdb/doc_rowwise_iterator.cc)."""
        from yugabyte_db_tpu.models.encoding import hashed_prefix

        versions: list[RowVersion] = []
        hp = hashed_prefix(key)
        # The bloom earns its (lazy, full-run) build only when it can
        # skip several runs per get; with 1-2 runs the per-run binary
        # search is already O(log n), so only probe a bloom that exists.
        many_runs = len(self.runs) > 2
        for t in self.runs:
            crun = t.crun
            if crun.num_versions == 0 or crun.max_key < key \
                    or crun.min_key > key:
                continue
            if hp and (many_runs or crun.bloom_ready) \
                    and not crun.may_contain_hashed(hp):
                continue
            versions.extend(crun.find_versions(key))
        versions.extend(mem.versions(key))
        return versions

    def _point_get_row(self, spec: ScanSpec, mem, key: bytes):
        """-> (projection, rows, resume, scanned) for one exact-key
        read (merge + predicates + materialization, shared by the wire
        and row point paths)."""
        versions = self._point_versions(key, mem)
        projection = spec.projection or [c.name for c in
                                         self.schema.columns]
        rows: list[tuple] = []
        if versions:
            merged = merge_versions(key, versions, spec.read_ht)
            if merged.exists:
                key_vals = self.mat.key_values(key)
                if self.mat.matches(spec, key_vals, merged):
                    rows.append(tuple(
                        self.mat.value(nm, key_vals, merged)
                        for nm in projection))
        resume = (key + b"\x00" if spec.limit is not None
                  and len(rows) >= spec.limit else None)
        return projection, rows, resume, 1 if versions else 0

    def _point_get_wire(self, spec: ScanSpec, fmt_id, mem, key: bytes):
        """Exact-key read serialized by the Python twin (one row)."""
        from yugabyte_db_tpu.models import wirefmt

        projection, rows, resume, scanned = self._point_get_row(
            spec, mem, key)
        dts = self._wire_dtypes(tuple(projection))
        data = wirefmt.serialize_rows(
            "cql" if fmt_id == host_page.WIRE_CQL else "pg", dts, rows)
        return host_page.WirePage(list(projection), data, len(rows),
                                  resume, scanned)

    def _wire_dtypes(self, projection: tuple):
        dts = self._wire_dtype_cache.get(projection)
        if dts is None:
            by_name = {c.name: c.dtype for c in self.schema.columns}
            dts = [by_name[nm] for nm in projection]
            if len(self._wire_dtype_cache) >= 64:
                self._wire_dtype_cache.pop(
                    next(iter(self._wire_dtype_cache)))
            self._wire_dtype_cache[projection] = dts
        return dts

    def _issue_round(self, states, pending):
        """Group every active gather's pending param-rows by (signature,
        run) into vmapped dispatches; returns [(chunk, out_array)]."""
        self._device_fault_point()
        from yugabyte_db_tpu.ops import row_gather

        by_sig: dict = {}
        for pi, rows in pending.items():
            st = states[pi]
            for ri, (ip, fp) in enumerate(rows):
                by_sig.setdefault((st.sig, id(st.trun)),
                                  (st.trun, []))[1].append(
                    (pi, ri, ip, fp))
        dispatches = []
        for (sig, _tid), (trun, members) in by_sig.items():
            for c0 in range(0, len(members), self._G_BUCKETS[-1]):
                chunk = members[c0:c0 + self._G_BUCKETS[-1]]
                G = next(g for g in self._G_BUCKETS if g >= len(chunk))
                ip = np.zeros((G, len(chunk[0][2])), dtype=np.int32)
                fp = np.zeros((G, len(chunk[0][3])), dtype=np.float32)
                ip[:, 1] = -1  # padding: w_last < w_first -> no work
                for j, (_pi, _ri, ipj, fpj) in enumerate(chunk):
                    ip[j] = ipj
                    fp[j] = fpj
                fn = row_gather.compiled_gather_batch(sig, G)
                dispatches.append((chunk, fn(trun.dev.arrays, ip, fp)))
        return dispatches

    def _feed_round(self, states, pending, dispatches, disp_bufs):
        """Feed fetched buffers back to their gathers; returns the next
        round's pending param-rows ({} when every gather completed).

        Lanes that are provably complete after round 1 (paged LIMIT scans
        with no host verification: the while_loop either filled M >= limit
        matches or exhausted the range) are decoded in one vectorized pass
        per plan structure instead of page-by-page — per-page Python cost
        is what caps server throughput once fetches are pipelined."""
        groups: dict = {}
        handled: set[int] = set()
        for (chunk, _out), bufs in zip(dispatches, disp_bufs):
            tails = None
            for j, (pi, ri, _ip, _fp) in enumerate(chunk):
                st = states[pi]
                ctx = st.ctx
                if (ri != 0 or len(pending[pi]) != 1 or st.rows or
                        st.mode != "paged" or ctx["aggregate"] or
                        ctx["verify_preds"] or ctx["limit"] is None or
                        ctx.get("struct_key") is None):
                    continue
                if tails is None:  # one vectorized read per chunk
                    tails = bufs[:, ctx["M"], :2].tolist()
                groups.setdefault(ctx["struct_key"], []).append(
                    (pi, st, bufs[j], tails[j]))
        for members in groups.values():
            self._batch_emit(members)
            handled.update(pi for pi, _st, _b, _t in members)

        plan_bufs: dict[int, dict[int, np.ndarray]] = {}
        for (chunk, _out), bufs in zip(dispatches, disp_bufs):
            for j, (pi, ri, _ip, _fp) in enumerate(chunk):
                if pi in handled:
                    continue
                plan_bufs.setdefault(pi, {})[ri] = bufs[j]
        next_pending = {}
        for pi, rows in pending.items():
            st = states[pi]
            if pi in handled:
                st.pending = []
                continue
            bufs = [plan_bufs[pi][ri] for ri in range(len(rows))]
            more = st.consume(bufs)
            if more:
                next_pending[pi] = more
        return next_pending

    def _batch_emit(self, members):
        """Vectorized decode of many completed LIMIT pages that share one
        plan structure: one concatenate + one decode per column for the
        whole group, then per-page list slices."""
        from yugabyte_db_tpu.ops import row_gather

        st0 = members[0][1]
        ctx = st0.ctx
        M, limit, crun = ctx["M"], ctx["limit"], ctx["crun"]
        projection = ctx["projection"]
        key_col_pos = ctx["key_col_pos"]
        _w, col_offs = row_gather.out_layout(ctx["sig"])
        parts, metas = [], []
        for _pi, st, buf, (count, scanned) in members:
            n_take = min(count, M, limit)
            st.scanned += scanned
            if n_take:
                parts.append(buf[:n_take])
            metas.append((st, n_take))
        if parts:
            flat = np.concatenate(parts) if len(parts) > 1 else parts[0]
            starts = flat[:, 0]
            kv_cols = (crun.key_col_arrays(
                           np.unique(starts // crun.R).tolist())
                       if any(nm in key_col_pos for nm in projection)
                       else None)
            cols_out = []
            for nm in projection:
                if nm in key_col_pos:
                    cols_out.append(
                        kv_cols[key_col_pos[nm]][starts].tolist())
                else:
                    cols_out.append(self._decode_col(
                        self._name_to_id[nm], flat, flat.shape[0], crun,
                        col_offs))
            rows_all = list(zip(*cols_out))
        else:
            rows_all = []
            starts = None
        off = 0
        for st, n_take in metas:
            st.rows = rows_all[off:off + n_take]
            if n_take >= limit:
                st.resume = crun.key_at(
                    int(starts[off + n_take - 1])) + b"\x00"
            off += n_take

    def _plan_scan(self, spec: ScanSpec, agg_sink: list | None = None,
                   grouped_sink: list | None = None):
        """-> ("host", finish()) | ("issued", outs, finish(fetched))
           | ("gather", _GatherScan) | ("agg_deferred",) /
           ("grouped_deferred",) for single-source device (grouped)
           aggregates, which land in the sinks — the caller dispatches
           those together (one vmapped program per signature group;
           _plan_device_aggregate_batch / _plan_grouped_batch)."""
        if agg_sink is None:
            agg_sink = []
        if grouped_sink is None:
            grouped_sink = []
        # Snapshot the memtable BEFORE the run list: flush() appends the
        # new run and THEN swaps in an empty memtable, so (old mem, runs
        # read after) can at worst see a flushed row in both sources
        # (harmless — merge dedups by hybrid time) but never in neither.
        # The snapshot also covers _AsyncBatch.finish()-time execution of
        # host-path closures: flush() never mutates the old MemTable.
        mem = self.memtable
        from yugabyte_db_tpu.utils.sync_point import sync_point

        sync_point("tpu_engine:plan:mem_snapshotted")
        runs = self._overlapping_runs(spec)
        mem_live = (not mem.is_empty) and \
            mem.has_keys(spec.lower, spec.upper)
        exact, superset, host_only = self._split_predicates(spec)
        pred_split = (exact, superset, host_only)
        single_source = len(runs) == 1 and not mem_live

        if spec.is_aggregate:
            has_expr = any(a.expr is not None for a in spec.aggregates)
            if single_source and runs and superset and not host_only:
                # Dictionary-encoded string predicates promote to exact
                # code-range compares: the aggregate stays a pure device
                # fold instead of degrading to the gather+verify path.
                promoted = self._promote_code_preds(runs[0], superset)
                if promoted is not None:
                    exact = exact + promoted
                    superset = []
                    pred_split = (exact, superset, host_only)
            if single_source and runs and not superset and not host_only \
                    and (spec.group_by or has_expr):
                prep = self._grouped_prep(runs[0], spec, exact)
                if prep is not None:
                    kind, payload = prep
                    if kind == "empty":
                        return payload
                    grouped_sink.append((runs[0], spec, exact, payload))
                    return ("grouped_deferred",)
            eligible = (not superset and not host_only
                        and not spec.group_by and not has_expr
                        and self._aggs_device_eligible(spec))
            if eligible and single_source and runs:
                agg_sink.append((runs[0], spec, exact))
                return ("agg_deferred",)
            if eligible and not single_source and (runs or mem_live):
                # Multi-source (overlapping runs / live memtable): the
                # cached delta overlay keeps this a pure device scan —
                # primary run with dirty keys masked out of its valid
                # plane + a mini-run holding the dirty keys' full merged
                # version sets (disjoint partials, combined on host).
                ov = self._overlay(mem)
                if ov is not None:
                    return self._plan_overlay_aggregate(ov, spec, exact)
            if single_source and runs:
                return ("gather", self._plan_gather(
                    runs[0], spec, pred_split, aggregate=True))
            return ("host", lambda: self._row_scan(
                spec, runs, mem_live, pred_split, aggregate=True, mem=mem))
        page_eligible = (single_source and runs
                         and spec.limit is not None
                         and spec.limit <= host_page.MAX_PAGE_LIMIT
                         and runs[0].crun.max_group_versions <= 1
                         and not superset and not host_only)
        page_pred_items = (host_page.encode_pred_items(self, exact)
                           if page_eligible else None)
        pk = self._point_key(spec)
        if pk is not None:
            # Exact-key read: the bloom-pruned per-key lookup beats both
            # the generic source-merge (~10x) and a device dispatch (the
            # link RTT). The native page server keeps flat-run LIMIT
            # point reads (it emits them in C).
            if page_pred_items is None:
                def point():
                    projection, rows, resume, scanned = \
                        self._point_get_row(spec, mem, pk)
                    return ScanResult(list(projection), rows, resume,
                                      scanned)

                return ("host", point)
        if single_source and runs:
            # Result-bound LIMIT pages on a flat run with host-exact
            # predicates: serve from the host mirror (block-cache analog,
            # storage.host_page) — no device round trip for ~100 rows.
            if page_eligible:
                pred_items = page_pred_items
                if pred_items is not None:
                    # Deferred: scan_batch_async batch-plans all pages
                    # (one vectorized searchsorted per shared structure).
                    return ("page", (runs[0], spec, pred_items))
            return ("gather", self._plan_gather(
                runs[0], spec, pred_split, aggregate=False))
        return ("host", lambda: self._row_scan(
            spec, runs, mem_live, pred_split, aggregate=False, mem=mem))

    def _serve_host_batch(self, specs: list[ScanSpec],
                          deadline=None) -> list[ScanResult]:
        """Serve a whole batch WITHOUT touching the device: candidate
        keys come from the authoritative host ColumnarRuns instead of
        device scans, and the shared merge/materialize loop applies the
        full predicate set host-side — so results are byte-identical to
        the device path (and to the CPU oracle). This is the degraded
        mode behind the circuit breaker."""
        out = []
        for spec in specs:
            if deadline is not None:
                deadline.check("tpu_engine.host_serve")
            out.append(self._host_scan(spec))
        return out

    def _host_scan(self, spec: ScanSpec) -> ScanResult:
        mem = self.memtable
        runs = self._overlapping_runs(spec)
        mem_live = (not mem.is_empty) and \
            mem.has_keys(spec.lower, spec.upper)
        pred_split = self._split_predicates(spec)
        if not spec.is_aggregate:
            pk = self._point_key(spec)
            if pk is not None:
                projection, rows, resume, scanned = \
                    self._point_get_row(spec, mem, pk)
                return ScanResult(list(projection), rows, resume, scanned)
        return self._row_scan(spec, runs, mem_live, pred_split,
                              aggregate=spec.is_aggregate, mem=mem,
                              device_ok=False)

    def _host_candidates(self, trun: TpuRun, spec: ScanSpec):
        """Candidate keys for one run straight from the host ColumnarRun
        (every valid key in range, duplicates adjacent — the merge loop
        dedups and applies predicates). The device-free twin of
        _device_candidates for breaker-degraded serving. Pad rows past
        each block's valid prefix hold b"" keys and MUST be skipped:
        they would both break heapq.merge's sorted-stream contract and
        defeat the merge loop's adjacency dedup."""
        crun = trun.crun
        row_lo = crun.lower_row(spec.lower)
        row_hi = crun.upper_row(spec.upper)
        R = crun.R
        for row in range(row_lo, row_hi):
            b, r = divmod(row, R)
            if r >= crun.blocks[b].num_valid:
                continue
            yield crun.row_keys[b][r]

    def _row_scan(self, spec: ScanSpec, runs, mem_live, pred_split,
                  aggregate: bool, mem: MemTable | None = None,
                  device_ok: bool = True):
        exact, superset, host_only = pred_split
        mem = self.memtable if mem is None else mem
        single_source = len(runs) == 1 and not mem_live
        apply_preds = single_source and device_ok
        pred_sigs, pred_lits = (
            self._pred_sig_and_literals(exact + superset) if apply_preds
            else ((), ()))

        key_streams = [
            self._device_candidates(t, spec, pred_sigs, pred_lits,
                                    apply_preds)
            if device_ok else self._host_candidates(t, spec)
            for t in runs
        ]
        if mem_live or not mem.is_empty:
            key_streams.append(mem.scan_keys(spec.lower, spec.upper))

        import heapq

        candidates = heapq.merge(*key_streams)
        projection = spec.projection or [c.name for c in self.schema.columns]
        agg = Aggregator(spec.aggregates or [], spec.group_by or []) \
            if aggregate else None
        rows: list[tuple] = []
        scanned = 0
        resume = None
        last = None
        for key in candidates:
            if key == last:
                continue
            last = key
            scanned += 1
            versions: list[RowVersion] = []
            for t in runs:
                versions.extend(t.crun.find_versions(key))
            versions.extend(mem.versions(key))
            merged = merge_versions(key, versions, spec.read_ht)
            if not merged.exists:
                continue
            key_vals = self.mat.key_values(key)
            if not self.mat.matches(spec, key_vals, merged):
                continue
            if aggregate:
                agg.add(lambda name: self.mat.value(name, key_vals, merged))
                continue
            rows.append(tuple(
                self.mat.value(name, key_vals, merged) for name in projection))
            if spec.limit is not None and len(rows) >= spec.limit:
                resume = key + b"\x00"
                break
        if aggregate:
            return ScanResult(agg.column_names(), agg.results(), None, scanned)
        return ScanResult(projection, rows, resume, scanned)

    # -- device row-materialization path -------------------------------------
    def _gather_out_cols(self, names):
        from yugabyte_db_tpu.ops import row_gather

        seen = {}
        for name in names:
            cid = self._name_to_id.get(name)
            if cid is None or cid in seen:
                continue  # key column (decoded from the key) or duplicate
            kind = self._kinds[cid]
            planes = 2 if kind in ("i64", "f64", "str") else 1
            # FLOAT round-trips through f32 planes lossily vs the stored
            # python value; STRING/BINARY payloads live host-side — both
            # fetch the original value via the setter row index instead.
            seen[cid] = row_gather.OutCol(cid, planes, kind in ("str", "f32"))
        return tuple(seen.values())

    def _decode_col(self, cid, buf, n, crun, col_offs):
        """Packed buffer columns -> python value list (None for NULL)."""
        kind = self._kinds[cid]
        cmp_off, null_off, idx_off = col_offs[cid]
        null = buf[:n, null_off] != 0
        if kind in ("str", "f32"):
            idxs = buf[:n, idx_off]
            R = crun.R
            out = []
            for i in range(n):
                gi = int(idxs[i])
                if null[i] or gi < 0:
                    out.append(None)
                else:
                    b, r = divmod(gi, R)
                    out.append(crun.row_versions[b][r].columns[cid])
            return out
        if kind == "i32":
            raw = buf[:n, cmp_off].tolist()
        elif kind == "i64":
            raw = P.ordered_planes_to_i64(
                buf[:n, cmp_off], buf[:n, cmp_off + 1]).tolist()
        else:  # f64
            raw = P.ordered_planes_to_f64(
                buf[:n, cmp_off], buf[:n, cmp_off + 1]).tolist()
        dt = self._dtypes[cid]
        if dt == DataType.BOOL:
            return [None if null[i] else bool(raw[i]) for i in range(n)]
        if not null.any():
            return raw
        for i in np.nonzero(null)[0].tolist():
            raw[i] = None
        return raw

    def _pred_host_literals(self, preds):
        """Predicate literals -> (int32 plane list, f32 list), host values."""
        int_lits, f32_lits = [], []
        for p in preds:
            kind = self._pred_kind(p)
            if kind == "code":
                int_lits.append(int(p.value))
            elif kind == "f32":
                f32_lits.append(float(p.value))
            elif kind == "i32":
                int_lits.append(int(p.value))
            elif kind == "i64":
                hi, lo = P.i64_to_ordered_planes(
                    np.array([int(p.value)], dtype=np.int64))
                int_lits += [int(hi[0]), int(lo[0])]
            elif kind == "f64":
                hi, lo = P.f64_to_ordered_planes(
                    np.array([p.value], dtype=np.float64))
                int_lits += [int(hi[0]), int(lo[0])]
            else:
                raw = (p.value.encode("utf-8", "surrogateescape")
                       if isinstance(p.value, str)
                       else bytes(p.value))
                hi, lo = P.varlen_prefix_planes([raw])
                int_lits += [int(hi[0]), int(lo[0])]
        return int_lits, f32_lits

    def _plan_gather(self, trun: TpuRun, spec: ScanSpec, pred_split,
                     aggregate: bool):
        """Single-source scan fully resolved on device: gather dispatches
        pack matched rows' value planes into one int32 matrix; the host
        bulk-decodes. Superset (str/f32) and host-only (key-column, IN)
        predicates are verified on the decoded values — still
        result-proportional work.

        Dispatch shape: a LIMIT page is ONE param-row whose while_loop
        early-exits once the buffer fills; an unbounded scan is one
        param-row per window with the buffer sized to the window (no
        overflow possible). scan_batch() coalesces same-signature rows
        into vmapped dispatches, so whole batches cost one round-trip."""
        from yugabyte_db_tpu.ops import row_gather

        exact, superset, host_only = pred_split
        crun = trun.crun
        # Structural plan cache: a server runs thousands of pages with
        # the same shape (projection/predicates/limit) per batch; the
        # per-spec parts (row bounds, read point, params) are cheap, the
        # structure (out cols, sigs, literal encodings) is not.
        cache_key = None
        if not aggregate:
            try:
                cache_key = (id(trun), spec.limit,
                             tuple(spec.projection or ()),
                             tuple((p.column, p.op, p.value)
                                   for p in spec.predicates))
                cached = self._plan_cache.get(cache_key)
            except TypeError:
                cache_key = cached = None  # unhashable literal: no cache
            if cached is not None:
                ctx = dict(cached)
                return self._finish_plan_gather(trun, spec, ctx)
        projection = spec.projection or [c.name for c in self.schema.columns]
        verify_preds = superset + host_only
        if aggregate:
            from yugabyte_db_tpu.storage.expr import columns_of

            agg = Aggregator(spec.aggregates or [], spec.group_by or [])
            out_names = ([a.column for a in (spec.aggregates or [])
                          if a.column is not None]
                         + [c for a in (spec.aggregates or [])
                            if a.expr is not None
                            for c in columns_of(a.expr)]
                         + list(spec.group_by or []))
        else:
            agg = None
            out_names = list(projection)
        out_names += [p.column for p in verify_preds]
        out_cols = self._gather_out_cols(out_names)
        decode_ids = {self._name_to_id[n] for n in out_names
                      if n in self._name_to_id}
        device_preds = exact + superset
        pred_sigs = self._pred_sigs_only(device_preds)
        int_lits, f32_lits = self._pred_host_literals(device_preds)
        limit = None if aggregate else spec.limit
        K = WINDOW_BLOCKS
        R = crun.R

        ctx = {
            "crun": crun, "trun": trun, "agg": agg,
            "aggregate": aggregate, "projection": projection,
            "verify_preds": verify_preds, "decode_ids": decode_ids,
            "limit": limit, "out_cols": out_cols, "pred_sigs": pred_sigs,
            "int_lits": int_lits, "f32_lits": f32_lits,
            "key_col_pos": {c.name: i
                            for i, c in enumerate(self.schema.key_columns)},
        }
        if limit is None and not device_preds and not verify_preds:
            # Unbounded, unpredicated: one param-row per window, emitted
            # in place (every row is a result row; the host compacts).
            ctx["mode"] = "chunks"
            ctx["M"] = M = K * R
            ctx["sig"] = self._gather_sig(ctx, M, packed=False, K=K)
        else:
            # One definitive round, LIMIT page or selective scan: the
            # while_loop walks windows to the range end, early-exiting
            # once the buffer holds M matches. A LIMIT page (M > limit)
            # never needs a second dispatch — every synchronous fetch
            # cycle costs ~1 link round trip (~100ms on the tunnel), so
            # round count, not device compute, is the price that matters.
            ctx["mode"] = "paged"
            # The tunnel link moves ~30MB/s device->host: the output
            # buffer M is the page's wire cost, so use the smallest
            # bucket that guarantees one-round completion (M >= limit).
            M = 4096
            if limit is not None and not verify_preds:
                M = next((m for m in (104, 256, 1024, 4096) if m >= limit),
                         -(-limit // 8) * 8)
            ctx["M"] = M
            ctx["sig"] = self._gather_sig(ctx, M, K=K)
        if cache_key is not None:
            if len(self._plan_cache) >= 1024:  # distinct literals bound it
                self._plan_cache.pop(next(iter(self._plan_cache)))
            ctx["struct_key"] = cache_key
            self._plan_cache[cache_key] = dict(ctx)
        return self._finish_plan_gather(trun, spec, ctx)

    def _finish_plan_gather(self, trun: TpuRun, spec: ScanSpec, ctx):
        """Per-spec completion of a (possibly cached) gather plan:
        row bounds, read point, param rows."""
        from yugabyte_db_tpu.ops import row_gather

        crun = trun.crun
        read_planes = self._read_plane_ints(spec)
        ctx["read_planes"] = read_planes
        row_lo = crun.lower_row(spec.lower)
        row_hi = crun.upper_row(spec.upper)
        if row_lo >= row_hi:
            return _GatherScan(self, ctx, "paged", [], 0, 0)
        K = ctx["sig"].K
        R = crun.R
        w_first = row_lo // (K * R)
        w_last = (row_hi - 1) // (K * R)
        if ctx["mode"] == "chunks":
            param_rows = [
                row_gather.pack_params(w, w, row_lo, row_hi, read_planes,
                                       ctx["int_lits"], ctx["f32_lits"])
                for w in range(w_first, w_last + 1)
            ]
            return _GatherScan(self, ctx, "chunks", param_rows,
                               w_last, row_hi)
        ip, fp = row_gather.pack_params(
            w_first, w_last, row_lo, row_hi, read_planes,
            ctx["int_lits"], ctx["f32_lits"])
        return _GatherScan(self, ctx, "paged", [(ip, fp)],
                           w_last, row_hi)

    def _read_plane_ints(self, spec: ScanSpec):
        # Tiny keyed cache: servers issue thousands of pages at the same
        # read point and the plane math costs ~µs/page at wire rates.
        cached = self._read_plane_cache.get(spec.read_ht)
        if cached is not None:
            return cached
        r_hi, r_lo = P.scalar_ht_planes(min(spec.read_ht, MAX_HT))
        e_hi, e_lo = P.scalar_ht_planes(min(spec.read_ht, MAX_HT - 1))
        planes = (r_hi, r_lo, e_hi, e_lo)
        if len(self._read_plane_cache) >= 64:
            self._read_plane_cache.pop(next(iter(self._read_plane_cache)))
        self._read_plane_cache[spec.read_ht] = planes
        return planes

    def _gather_sig(self, ctx, M, packed=True, K=WINDOW_BLOCKS):
        from yugabyte_db_tpu.ops import row_gather

        return row_gather.GatherSig(
            B=ctx["trun"].dev.B, R=ctx["crun"].R, K=K, M=M,
            cols=self._col_sigs(), preds=ctx["pred_sigs"], apply_preds=True,
            out_cols=ctx["out_cols"],
            flat=ctx["crun"].max_group_versions <= 1, packed=packed)

    def _emit_fetched(self, ctx, buf, rows):
        """Decode one fetched packed buffer into ctx's sinks.

        Returns (count, emitted_n, hit_limit, last_start). ``last_start``
        is the global row index of the last *consumed* packed row (for
        resume / continuation bounds)."""
        from yugabyte_db_tpu.ops import row_gather

        crun = ctx["crun"]
        M = ctx["M"]
        limit = ctx["limit"]
        verify_preds = ctx["verify_preds"]
        aggregate = ctx["aggregate"]
        agg = ctx["agg"]
        projection = ctx["projection"]
        key_col_pos = ctx["key_col_pos"]
        count = int(buf[M, 0])
        if not ctx["sig"].packed:
            # In-place window: compact matched rows with numpy.
            body = buf[:M]
            buf = body[body[:, 0] >= 0]
            n = buf.shape[0]
        else:
            n = min(count, M)
            buf = buf[:n]
        if n == 0:
            return 0, 0, False, None
        _w, col_offs = row_gather.out_layout(ctx["sig"])
        starts = buf[:n, 0]

        hit_limit = False
        if not verify_preds and not aggregate:
            # Columnar fast path: decode only the rows the page will
            # emit; key columns come from the run's per-column object
            # arrays via one fancy-index (no per-row Python decode).
            n_take = n if limit is None else min(n, limit - len(rows))
            sel = starts[:n_take]
            kv_cols = (crun.key_col_arrays(
                           np.unique(sel // crun.R).tolist())
                       if any(nm in key_col_pos for nm in projection)
                       else None)
            cols_out = []
            for nm in projection:
                if nm in key_col_pos:
                    cols_out.append(kv_cols[key_col_pos[nm]][sel].tolist())
                else:
                    cols_out.append(self._decode_col(
                        self._name_to_id[nm], buf, n_take, crun, col_offs))
            rows.extend(zip(*cols_out))
            hit_limit = limit is not None and len(rows) >= limit
            return count, n, hit_limit, int(starts[n_take - 1])

        colvals = {cid: self._decode_col(cid, buf, n, crun, col_offs)
                   for cid in ctx["decode_ids"]}

        def getter(name, i, _s=starts, _cv=colvals, _kp=key_col_pos):
            if name in _kp:
                return crun.key_vals_at(int(_s[i]))[_kp[name]]
            return _cv[self._name_to_id[name]][i]
        if verify_preds and n:
            # Every fetched row crosses back for host re-verification
            # when the device mask is a superset (string predicates) —
            # yb_scan_host_verify_rows makes that cliff measurable.
            count_host_verify_rows(int(n))
        taken_i = -1
        for i in range(n):
            if verify_preds and not all(
                    p.matches(getter(p.column, i)) for p in verify_preds):
                taken_i = i
                continue
            if aggregate:
                agg.add(lambda nm, _i=i: getter(nm, _i))
                taken_i = i
                continue
            rows.append(tuple(getter(nm, i) for nm in projection))
            taken_i = i
            if limit is not None and len(rows) >= limit:
                hit_limit = True
                break
        last = int(starts[taken_i]) if taken_i >= 0 else None
        return count, n, hit_limit, last

    def _gather_result(self, ctx, rows, scanned, resume):
        if ctx["aggregate"]:
            return ScanResult(ctx["agg"].column_names(), ctx["agg"].results(),
                              None, scanned)
        return ScanResult(ctx["projection"], rows, resume, scanned)

    # (gather round execution lives in _GatherScan below)

    # -- device grouped/expression aggregates --------------------------------
    def _dtype_of(self, name: str):
        cid = self._name_to_id.get(name)
        if cid is None:
            raise ValueError(f"{name} is not a value column")
        return self._dtypes[cid]

    def _encode_factor(self, node):
        """storage.expr tree -> the kernel's static factor tuples."""
        from yugabyte_db_tpu.storage import expr as X

        if isinstance(node, X.Col):
            return ("c", self._name_to_id[node.name])
        if isinstance(node, X.Const):
            return ("k", int(node.value))
        return (node.op, self._encode_factor(node.left),
                self._encode_factor(node.right))

    def _grouped_prep(self, trun: TpuRun, spec: ScanSpec, exact_preds):
        """Device GROUP BY / expression aggregates (ops.group_agg) — the
        TPC-H Q1/Q6 path. Host-side planning only: returns None when the
        spec isn't device-lowerable (caller falls back), ("empty", plan)
        for empty ranges, or ("params", (sig, ip, fp)) ready for a
        single or vmapped-batch dispatch."""
        from yugabyte_db_tpu.ops import group_agg, row_gather
        from yugabyte_db_tpu.storage import expr as X

        crun = trun.crun
        group_cols = []
        for name in (spec.group_by or []):
            cid = self._name_to_id.get(name)
            if cid is None:
                return None  # key column: host path
            kind = self._kinds[cid]
            if kind == "str":
                if crun.varlen_max_len.get(cid, 0) > 8:
                    return None  # prefix equality not exact
                planes = 2
            elif kind in ("i64", "f64"):
                planes = 2
            elif kind == "f32":
                return None  # raw-bit equality conflates -0.0/0.0
            else:
                planes = 1
            group_cols.append((cid, planes))

        gaggs = []
        for a in spec.aggregates:
            if a.fn == "count" and a.expr is None:
                cid = self._name_to_id.get(a.column) if a.column else None
                if a.column and cid is None:
                    return None
                gaggs.append(group_agg.GAgg(
                    "count", cid,
                    need_cols=(cid,) if cid is not None else ()))
            elif a.fn == "sum":
                if a.expr is None:
                    cid = self._name_to_id.get(a.column)
                    if cid is None or self._kinds[cid] not in ("i32", "i64"):
                        return None
                    gaggs.append(group_agg.GAgg(
                        "sum_prod", cid,
                        planes=1 if self._kinds[cid] == "i32" else 2,
                        factors=(), need_cols=(cid,)))
                else:
                    lowered = X.lower_product(a.expr, self._dtype_of)
                    if lowered is None:
                        return None
                    base, factors = lowered
                    # (negative factor VALUES are caught at runtime by the
                    # kernel's negs counter -> host fallback)
                    base_cid = self._name_to_id[base]
                    need = [base_cid]
                    for f in factors:
                        for cname in X.columns_of(f):
                            need.append(self._name_to_id[cname])
                    gaggs.append(group_agg.GAgg(
                        "sum_prod", base_cid,
                        planes=1 if self._kinds[base_cid] == "i32" else 2,
                        factors=tuple(self._encode_factor(f)
                                      for f in factors),
                        need_cols=tuple(dict.fromkeys(need))))
            else:
                return None  # min/max/avg: lowered by callers or host

        pred_sigs = self._pred_sigs_only(exact_preds)
        int_lits, f32_lits = self._pred_host_literals(exact_preds)
        row_lo = crun.lower_row(spec.lower)
        row_hi = crun.upper_row(spec.upper)
        sig = group_agg.GroupAggSig(
            B=trun.dev.B, R=crun.R, K=WINDOW_BLOCKS,
            NB=group_agg.NUM_BUCKETS, cols=self._col_sigs(),
            preds=pred_sigs, apply_preds=True,
            flat=crun.max_group_versions <= 1,
            group_cols=tuple(group_cols), aggs=tuple(gaggs))

        if row_lo >= row_hi:
            agg = Aggregator(spec.aggregates, spec.group_by or [])
            empty = ScanResult(agg.column_names(), agg.results(), None, 0)
            return ("empty", ("issued", [], lambda _f: empty))
        K = WINDOW_BLOCKS
        R = crun.R
        w_first = row_lo // (K * R)
        w_last = (row_hi - 1) // (K * R)
        ip, fp = row_gather.pack_params(
            w_first, w_last, row_lo, row_hi, self._read_plane_ints(spec),
            int_lits, f32_lits)
        return ("params", (sig, ip, fp))

    def _grouped_finish(self, trun: TpuRun, spec: ScanSpec, exact_preds,
                        sig):
        def fallback():
            return self._row_scan(spec, [trun], False,
                                  (exact_preds, [], []), aggregate=True)

        return lambda f: self._finish_grouped(trun.crun, spec, sig, f,
                                              fallback)

    def _dispatch_grouped(self, trun: TpuRun, spec: ScanSpec,
                          exact_preds, prep):
        from yugabyte_db_tpu.ops import group_agg

        sig, ip, fp = prep
        fn = group_agg.compiled_grouped(sig)
        out = fn(trun.dev.arrays, ip, fp)
        return ("issued", out,
                self._grouped_finish(trun, spec, exact_preds, sig))

    @staticmethod
    @functools.lru_cache(maxsize=64)
    @compile_contract("batched_grouped", max_compiles=256)
    def _batched_grouped_fn(sig):
        """jit(vmap) of the grouped-aggregate program: N same-signature
        GROUP BY scans (distinct bounds/read points/literals packed in
        the param vectors) in one dispatch."""
        from yugabyte_db_tpu.ops import group_agg

        base = group_agg.compiled_grouped(sig)
        return jax.jit(jax.vmap(base, in_axes=(None, 0, 0)))

    def _plan_grouped_batch(self, items):
        """Batched grouped aggregates (the concurrent TPC-H Q1 shape):
        group prepped specs by (run, signature), stack their packed
        param vectors (padded to the next power of two), one vmapped
        dispatch per group; per-lane finishes slice the stacked
        outputs. items = [(pi, trun, spec, exact, (sig, ip, fp))];
        returns [(pi, outs, finish)]."""
        groups: dict = {}
        out = []
        for pi, trun, spec, exact, (sig, ip, fp) in items:
            groups.setdefault((id(trun), sig), []).append(
                (pi, trun, spec, exact, sig, ip, fp))
        for grp in groups.values():
            if len(grp) == 1:
                pi, trun, spec, exact, sig, ip, fp = grp[0]
                _tag, outs, fin = self._dispatch_grouped(
                    trun, spec, exact, (sig, ip, fp))
                out.append((pi, outs, fin))
                continue
            _pi0, trun, _s0, _e0, sig, ip0, fp0 = grp[0]
            n = len(grp)
            m = 1 << (n - 1).bit_length()
            ip0 = np.asarray(ip0)
            fp0 = np.asarray(fp0)
            ip_b = np.zeros((m,) + ip0.shape, ip0.dtype)
            fp_b = np.zeros((m,) + fp0.shape, fp0.dtype)
            for i, (_pi, _t, _s, _e, _sig, ip, fp) in enumerate(grp):
                ip_b[i] = np.asarray(ip)
                fp_b[i] = np.asarray(fp)
            fn = self._batched_grouped_fn(sig)
            res = fn(trun.dev.arrays, ip_b, fp_b)
            for i, (pi, trun_i, spec, exact, sig_i, _ip, _fp) in \
                    enumerate(grp):
                fin1 = self._grouped_finish(trun_i, spec, exact, sig_i)
                out.append((pi, res,
                            lambda f, i=i, fin1=fin1:
                            fin1({k: v[i] for k, v in f.items()})))
        return out


    def _finish_grouped(self, crun, spec, sig, res, fallback):
        NB = sig.NB
        count = np.asarray(res["count"])[:NB]
        live = np.nonzero(count > 0)[0]
        if int(res["negs"]) > 0:
            return fallback()  # negative base values: digits invalid
        km = np.asarray(res["keymin"])[:NB]
        kM = np.asarray(res["keymax"])[:NB]
        if live.size and sig.group_cols and \
                not (km[live] == kM[live]).all():
            return fallback()  # bucket collision: rehash on host

        group_names = list(spec.group_by or [])
        rows = []
        reps = np.asarray(res["rep"])[:NB]
        for b in live:
            gvals = self._decode_group(crun, spec, sig, km[b], int(reps[b]))
            if gvals is None:
                return fallback()
            aggs = []
            for i, (a, ga) in enumerate(zip(spec.aggregates, sig.aggs)):
                if ga.kind == "count":
                    aggs.append(int(np.asarray(res[f"a{i}"])[b]))
                else:
                    digits = np.asarray(res[f"a{i}"])[b]
                    v = sum(int(d) << (16 * k)
                            for k, d in enumerate(digits))
                    # SQL sum over zero non-null inputs is NULL.
                    n_in = int(np.asarray(res[f"n{i}"])[b])
                    aggs.append(v if n_in else None)
            rows.append(tuple(gvals) + tuple(aggs))
        if not rows and not spec.group_by:
            agg = Aggregator(spec.aggregates, [])
            return ScanResult(agg.column_names(), agg.results(), None,
                              int(res["scanned"]))
        rows.sort(key=lambda r: tuple(
            (v is None, v) for v in r[:len(group_names)]))
        names = group_names + [a.output_name for a in spec.aggregates]
        return ScanResult(names, rows, None, int(res["scanned"]))

    def _decode_group(self, crun, spec, sig, key_planes, rep):
        """Bucket key planes (verified min==max) -> python group values.
        Strings decode from the representative row's merged state."""
        from yugabyte_db_tpu.storage.merge import merge_versions

        out = []
        off = 0
        for (cid, planes), name in zip(sig.group_cols,
                                       spec.group_by or []):
            vals = key_planes[off:off + planes]
            null = key_planes[off + planes]
            off += planes + 1
            if null:
                out.append(None)
                continue
            kind = self._kinds[cid]
            dt = self._dtypes[cid]
            if kind == "i32":
                v = int(vals[0])
                out.append(bool(v) if dt == DataType.BOOL else v)
            elif kind == "i64":
                v = int(P.ordered_planes_to_i64(
                    np.array([vals[0]], np.int32),
                    np.array([vals[1]], np.int32))[0])
                out.append(v)
            elif kind == "f64":
                out.append(float(P.ordered_planes_to_f64(
                    np.array([vals[0]], np.int32),
                    np.array([vals[1]], np.int32))[0]))
            else:  # str: exact via the representative row's merged value
                if rep >= crun.total_rows():
                    return None
                b_, r_ = divmod(rep, crun.R)
                key, versions = crun.group_versions(b_, r_)
                merged = merge_versions(key, versions, spec.read_ht)
                out.append(merged.get(cid))
        return out

    @staticmethod
    def _sortkey_bytes(kw_part, ht_hi_part, ht_lo_part):
        """[n, W] i32 key planes + ht planes -> fixed-width big-endian
        byte strings whose memcmp order is (key asc, ht desc) — the
        merge order, as ONE comparison per row."""
        n, W = kw_part.shape
        buf = np.empty((n, W + 2), dtype=np.uint32)
        buf[:, :W] = (kw_part.view(np.uint32)
                      ^ np.uint32(0x80000000)).byteswap()
        buf[:, W] = (~(ht_hi_part.view(np.uint32)
                       ^ np.uint32(0x80000000))).byteswap()
        buf[:, W + 1] = (~(ht_lo_part.view(np.uint32)
                           ^ np.uint32(0x80000000))).byteswap()
        return np.ascontiguousarray(buf).view(
            f"S{4 * (W + 2)}").reshape(n)

    @staticmethod
    def _merge_sorted(items):
        """Stable k-way merge of presorted (indices, sortkeys) pairs via
        a pairwise searchsorted tournament — O(N log K) comparisons, all
        vectorized, replacing a full np.lexsort of the union (measured
        ~6x cheaper at 500K rows; each run is already sorted)."""
        while len(items) > 1:
            nxt = []
            for i in range(0, len(items) - 1, 2):
                a_idx, a_keys = items[i]
                b_idx, b_keys = items[i + 1]
                # Stability: ties keep earlier-run rows first.
                a_dst = np.arange(a_keys.size, dtype=np.int64) + \
                    np.searchsorted(b_keys, a_keys, side="left")
                b_dst = np.arange(b_keys.size, dtype=np.int64) + \
                    np.searchsorted(a_keys, b_keys, side="right")
                out_n = a_keys.size + b_keys.size
                out_keys = np.empty(out_n, dtype=a_keys.dtype)
                out_idx = np.empty(out_n, dtype=np.int64)
                out_keys[a_dst] = a_keys
                out_keys[b_dst] = b_keys
                out_idx[a_dst] = a_idx
                out_idx[b_dst] = b_idx
                nxt.append((out_idx, out_keys))
            if len(items) % 2:
                nxt.append(items[-1])
            items = nxt
        return items[0][0]

    # -- delta overlay (masked primary + host-folded dirty set) -------------
    # Dirty-index buckets: the scatter that clears dirty rows from the
    # primary's valid plane pads its index vector to one of these sizes
    # so at most a handful of programs ever compile.
    _MASK_BUCKETS = (256, 1024, 4096, 16384, 65536, 262144)

    @staticmethod
    @compile_contract("scatter_invalid", max_compiles=64)
    @jax.jit
    def _scatter_invalid(valid, idx):
        flat = valid.reshape(-1)
        return flat.at[idx].set(False, mode="drop").reshape(valid.shape)

    @staticmethod
    @compile_contract("scatter_invalid_bits", max_compiles=64)
    @jax.jit
    def _scatter_invalid_bits(bw, idx):
        """Bit-packed valid plane (--tpu_plane_encoding): decode the
        words and scatter-clear in ONE fused program — the masked
        overlay substitutes a plain bool plane, which every kernel
        accepts because decode dispatch is per-leaf."""
        B, W = bw.shape
        bits = (bw[:, :, None] >> jnp.arange(32, dtype=jnp.int32)) \
            & jnp.int32(1)
        flat = bits.astype(jnp.bool_).reshape(B * W * 32)
        return flat.at[idx].set(False, mode="drop").reshape(B, W * 32)

    def _overlay(self, mem):
        """The cached delta-overlay state for the current engine content:
        (masked_primary, dirty rows, per-read-point partial cache).

        Multi-source reads (overlapping runs and/or a live memtable)
        previously merged EVERY key on host — correct, but ~100x slower
        than a device scan. The overlay keeps the DEVICE scanning only
        the primary run, with dirty keys' rows cleared from its valid
        plane, and folds the (small) dirty set on host:

        - dirty keys = every key present in any non-primary source, with
          their FULL version sets merged across all sources (primary
          included) and their key values pre-decoded;
        - masked primary = the primary run's device arrays with dirty
          rows scatter-cleared from ``valid`` — the scatter ships a
          bucketed index vector (KBs), never a full mask plane;
        - scans = one already-compiled flat dispatch over the masked
          primary + a cached host fold of the dirty rows (exact MVCC
          merge + predicates at the spec's read point).

        Nothing here builds a device run or compiles a multi-version
        kernel, so the first post-write scan pays only the dirty-set
        collection (the VERDICT-flagged 3s rebuild was the overlay
        mini-run's upload + lookback compile + a 26MB mask upload).
        Rebuilds amortize two ways: (run-set identity, memtable version
        counter) keying makes the steady-state scan a pure cache hit,
        and when only the version counter moved the state is advanced
        INCREMENTALLY (_overlay_apply_delta) by the memtable's
        versions_since() log instead of re-collecting every dirty key.
        Reference contract: IntentAwareIterator's multi-source merge
        (src/yb/docdb/intent_aware_iterator.h:81) and the
        immutable-memtable flush handoff (rocksdb/db/flush_job.cc:
        reads never stall on flush). Returns None (host fallback) when
        the dirty set approaches the primary's size — at that shape a
        compaction is the real answer."""
        runs = list(self.runs)
        if not runs:
            return None
        cache = self._overlay_cache
        if cache is not None:
            c_runs, c_mem, c_ver, state = cache
            if c_runs == runs and c_mem is mem:
                if c_ver == mem.num_versions:
                    return state
                if state is not None and mem.num_versions > c_ver:
                    inc = self._overlay_apply_delta(state, mem, c_ver)
                    if inc is not _OVERLAY_REBUILD:
                        ver = (inc.mem_count if inc is not None
                               else mem.num_versions)
                        self._cache_overlay(runs, mem, inc, ver)
                        return inc
        primary = max(runs, key=lambda t: t.crun.total_rows())
        deltas = [t for t in runs if t is not primary]

        # Snapshot the counter BEFORE collecting: rows racing in during
        # collection are re-applied by the next delta (idempotent — the
        # incremental path dedups versions by (ht, write_id)).
        ver0 = mem.num_versions
        dirty: dict[bytes, list] = {}
        for t in deltas:
            for key, versions in t.crun.iter_entries():
                dirty.setdefault(key, []).extend(versions)
        for key in mem.scan_keys(b"", b""):
            dirty.setdefault(key, []).extend(mem.versions(key))
        state = None
        if dirty and len(dirty) * 2 <= max(primary.crun.total_rows(), 64):
            primary.pin("high")
            try:
                rows_out = []
                idx_parts = []
                crun = primary.crun
                R = crun.R
                total = crun.total_rows()
                for key in sorted(dirty):
                    versions = list(dirty[key])
                    # Locate the key's primary versions with ONE bisect
                    # and read forward (find_versions would bisect again).
                    start = crun.lower_row(key)
                    n = 0
                    if start < total:
                        b, r = divmod(start, R)
                        meta = crun.blocks[b]
                        rk = crun.row_keys[b]
                        rv = crun.row_versions[b]
                        while r + n < meta.num_valid and rk[r + n] == key:
                            versions.append(rv[r + n])
                            n += 1
                    if n:
                        idx_parts.append(
                            np.arange(start, start + n, dtype=np.int32))
                    if len(versions) > 1:
                        versions.sort(key=lambda x: (x.ht, x.write_id),
                                      reverse=True)
                    # Key values decode lazily at first host fold.
                    rows_out.append([key, versions, None])
                idx = (np.concatenate(idx_parts) if idx_parts
                       else np.zeros(0, np.int32))
                masked_primary = self._masked_primary(primary, idx)
                state = _OverlayState(
                    masked_primary, rows_out,
                    [e[0] for e in rows_out],
                    {e[0]: e for e in rows_out}, idx, ver0)
                self._cache_overlay(runs, mem, state, ver0)
            finally:
                primary.unpin()
        else:
            self._cache_overlay(runs, mem, None, mem.num_versions)
        return state

    def _masked_primary(self, primary: TpuRun, idx) -> _MaskedRun:
        """The primary's device arrays with ``idx`` rows scatter-cleared
        from the valid plane; the index vector pads to a _MASK_BUCKETS
        size so at most a handful of scatter programs ever compile."""
        vleaf = primary.dev.arrays["valid"]
        packed = encodings.leaf_kind(vleaf) == "bits"
        size = (vleaf["bits"]["bw"].size * 32 if packed else vleaf.size)
        bucket = next((b for b in self._MASK_BUCKETS
                       if b >= idx.size), idx.size)
        # Pad with an out-of-range index; mode="drop" discards it.
        pidx = np.full(bucket, size, dtype=np.int32)
        pidx[:idx.size] = idx
        masked_valid = (
            TpuStorageEngine._scatter_invalid_bits(
                vleaf["bits"]["bw"], jnp.asarray(pidx)) if packed
            else TpuStorageEngine._scatter_invalid(
                vleaf, jnp.asarray(pidx)))
        masked_arrays = dict(primary.dev.arrays, valid=masked_valid)
        return _MaskedRun(primary, masked_arrays)

    def _cache_overlay(self, runs, mem, state, ver) -> None:
        """Publish an overlay cache entry, moving the primary-run pin
        and the masked-valid residency accounting with it."""
        new_primary = state.masked.source if state is not None else None
        old = self._overlay_pinned
        if old is not new_primary:
            if new_primary is not None:
                new_primary.pin("high")
            if old is not None:
                old.unpin()
            self._overlay_pinned = new_primary
            if self._overlay_ext_key is not None:
                hbm_cache().invalidate(self._overlay_ext_key)
                self._overlay_ext_key = None
            if state is not None:
                self._overlay_ext_key = hbm_cache().add_external(
                    None,
                    device_nbytes(state.masked.dev.arrays["valid"]),
                    self.device_tracker, "overlay_mask")
        self._overlay_cache = (runs, mem, ver, state)

    def _overlay_apply_delta(self, state: _OverlayState, mem,
                             since: int):
        """Advance the cached overlay by the memtable versions applied
        after index ``since`` (copy-on-write: shared row entries are
        replaced, never mutated, so in-flight readers of the old state
        stay consistent). Returns the new state, None when the dirty
        set outgrew the overlay shape (host fallback, as in the full
        build), or _OVERLAY_REBUILD when the memtable has no delta log.

        Steady-state cost is O(delta): one bisect per touched key plus
        one re-scatter only when new primary rows need clearing — this
        is what turns the 899ms per-wave overlay rebuild into a
        sub-50ms update (BENCH_r05 postwrite_scan)."""
        delta = getattr(mem, "versions_since", lambda _n: None)(since)
        if delta is None:
            return _OVERLAY_REBUILD
        if not delta:
            return state
        changed: dict[bytes, list] = {}
        for r in delta:
            changed.setdefault(r.key, []).append(r)
        primary = state.masked.source
        crun = primary.crun
        n_new = sum(1 for k in changed if k not in state.by_key)
        if (len(state.rows) + n_new) * 2 > max(crun.total_rows(), 64):
            return None
        rows = list(state.rows)
        by_key = dict(state.by_key)
        idx_parts = [state.idx]
        added: list = []
        R = crun.R
        total = crun.total_rows()
        for key in sorted(changed):
            add = changed[key]
            old_entry = by_key.get(key)
            if old_entry is not None:
                # Re-applied versions (a build racing a write) dedup by
                # the version identity the merge sorts on.
                seen = {(v.ht, v.write_id) for v in old_entry[1]}
                versions = old_entry[1] + [
                    v for v in add if (v.ht, v.write_id) not in seen]
                if len(versions) > 1:
                    versions.sort(key=lambda x: (x.ht, x.write_id),
                                  reverse=True)
                entry = [key, versions, old_entry[2]]
                rows[bisect.bisect_left(state.keys, key)] = entry
                by_key[key] = entry
                continue
            versions = list(add)
            start = crun.lower_row(key)
            n = 0
            if start < total:
                b, r = divmod(start, R)
                meta = crun.blocks[b]
                rk = crun.row_keys[b]
                rv = crun.row_versions[b]
                while r + n < meta.num_valid and rk[r + n] == key:
                    versions.append(rv[r + n])
                    n += 1
            if n:
                idx_parts.append(
                    np.arange(start, start + n, dtype=np.int32))
            if len(versions) > 1:
                versions.sort(key=lambda x: (x.ht, x.write_id),
                              reverse=True)
            entry = [key, versions, None]
            by_key[key] = entry
            added.append(entry)  # sorted: changed iterates in key order
        if added:
            # One linear merge of the two sorted lists (inserting one at
            # a time would memmove the tail per new key).
            merged_rows = []
            i = j = 0
            while i < len(rows) and j < len(added):
                if rows[i][0] <= added[j][0]:
                    merged_rows.append(rows[i])
                    i += 1
                else:
                    merged_rows.append(added[j])
                    j += 1
            merged_rows.extend(rows[i:])
            merged_rows.extend(added[j:])
            rows = merged_rows
        keys = [e[0] for e in rows] if added else state.keys
        if len(idx_parts) > 1:
            idx = np.concatenate(idx_parts)
            masked = self._masked_primary(primary, idx)
        else:
            idx = state.idx
            masked = state.masked
        return _OverlayState(masked, rows, keys, by_key, idx,
                             since + len(delta))

    def _overlay_host_partial(self, ov, spec: ScanSpec):
        """Exact host fold of the dirty rows at spec's read point:
        -> (scanned, [per-agg (n, value)]) where value is the finalized
        partial (sum / min / max; count rides n). Cached per (read
        point, predicates, aggregates) on the overlay state — the
        steady-state scan shape reuses it for free."""
        rows_out = ov.rows
        cache = ov.partial
        try:
            key = (self._read_plane_ints(spec), spec.lower, spec.upper,
                   tuple((p.column, p.op, p.value)
                         for p in spec.predicates),
                   tuple((a.fn, a.column) for a in spec.aggregates))
        except TypeError:
            key = None
        if key is not None:
            hit = cache.get(key)
            if hit is not None:
                return hit
        scanned = 0
        parts = [[0, None] for _ in spec.aggregates]
        # Key columns decode only when something references one (the
        # usual aggregate shape touches value columns only).
        needs_keys = any(
            p.column in self._key_col_names for p in spec.predicates
        ) or any(a.column in self._key_col_names
                 for a in spec.aggregates if a.column)
        for entry in rows_out:
            rkey, versions, key_vals = entry
            if rkey < spec.lower or (spec.upper and rkey >= spec.upper):
                continue
            merged = merge_versions(rkey, versions, spec.read_ht)
            if not merged.exists:
                continue
            scanned += 1
            if needs_keys and key_vals is None:
                key_vals = entry[2] = self.mat.key_values(rkey)
            if not self.mat.matches(spec, key_vals, merged):
                continue
            for pi, a in enumerate(spec.aggregates):
                if a.column is None:
                    parts[pi][0] += 1
                    continue
                v = self.mat.value(a.column, key_vals, merged)
                if v is None:
                    continue
                p = parts[pi]
                p[0] += 1
                if a.fn in ("sum", "avg"):
                    p[1] = v if p[1] is None else p[1] + v
                elif a.fn == "min":
                    p[1] = v if p[1] is None else min(p[1], v)
                elif a.fn == "max":
                    p[1] = v if p[1] is None else max(p[1], v)
        result = (scanned, [tuple(p) for p in parts])
        if key is not None:
            if len(cache) >= 8:
                cache.pop(next(iter(cache)))
            cache[key] = result
        return result

    def _plan_overlay_aggregate(self, ov, spec: ScanSpec, exact_preds):
        """One device aggregate over the masked primary (flat,
        already-compiled program) + the cached host fold of the dirty
        rows, combined exactly at the finalized level (disjoint key
        sets)."""
        masked_primary = ov.masked
        dev_aggs, lowering = agg_fold.lower_aggs(
            spec.aggregates, self._name_to_id, self._kinds)
        o1, f1 = self._plan_device_aggregate(masked_primary, spec,
                                             exact_preds, raw=True)

        def pre_fetch():
            # Runs while the device outputs stream host-ward: the host
            # fold overlaps the link fetch instead of following it.
            self._overlay_host_partial(ov, spec)

        def finish(fetched):
            acc, s1 = f1(fetched)
            h_scanned, h_parts = self._overlay_host_partial(ov, spec)
            out_row, names = [], []
            for pi, (a, (fn_name, di)) in enumerate(
                    zip(spec.aggregates, lowering)):
                names.append(f"{a.fn}({a.column or '*'})")
                ag = dev_aggs[di]
                h_n, h_v = h_parts[pi]
                if a.fn == "count":
                    dv = agg_fold.finalize(ag, acc[di], "count")
                    out_row.append(int(dv) + h_n)
                    continue
                dev_n = int(acc[di].get("n", 0))
                if a.fn in ("sum", "avg"):
                    ds = agg_fold.finalize(ag, acc[di], "sum")
                    total = None
                    if ds is not None or h_v is not None:
                        total = (ds or 0) + (h_v or 0)
                    if a.fn == "sum":
                        out_row.append(total)
                    else:
                        n = dev_n + h_n
                        out_row.append(total / n if n else None)
                    continue
                dv = agg_fold.finalize(ag, acc[di], a.fn)
                vals = [v for v in (dv, h_v) if v is not None]
                if not vals:
                    out_row.append(None)
                elif a.fn == "min":
                    out_row.append(min(vals))
                else:
                    out_row.append(max(vals))
            return ScanResult(names, [tuple(out_row)], None,
                              s1 + h_scanned)

        return ("issued", o1, finish, pre_fetch)

    # -- device aggregate path ---------------------------------------------
    def _device_agg_prep(self, trun: TpuRun, spec: ScanSpec, exact_preds):
        """Host-side planning shared by the single-spec and batched
        device-aggregate paths: compile signature, fold route, scan
        bounds, read planes (host ints), and HOST predicate literals
        (so batched dispatch stacks them into one transfer)."""
        from yugabyte_db_tpu.ops import flat_fold, lookback_fold, seg_fold

        crun = trun.crun
        row_lo = crun.lower_row(spec.lower)
        row_hi = crun.upper_row(spec.upper)
        sigs, lits = self._pred_sig_and_literals(
            exact_preds, literal_fn=agg_fold.pred_literal_host)
        dev_aggs, lowering = agg_fold.lower_aggs(
            spec.aggregates, self._name_to_id, self._kinds)
        R = crun.R
        K = agg_fold.safe_window_blocks(R, agg_fold.FULL_WINDOW_BLOCKS)
        flat = crun.max_group_versions <= 1
        # lookback rides in the compile signature: set it ONLY when the
        # lookback route can serve this run (otherwise every distinct
        # version count would recompile the byte-identical fallbacks),
        # and round up to a power of two so drifting counts share at
        # most 5 compiled variants.
        lb = 0
        if not flat and \
                crun.max_group_versions <= lookback_fold.MAX_LOOKBACK:
            lb = 1 << (crun.max_group_versions - 1).bit_length()
        sig = dscan.ScanSig(B=trun.dev.B, R=R, K=K, cols=self._col_sigs(),
                            preds=tuple(sigs), aggs=dev_aggs,
                            apply_preds=True, flat=flat, lookback=lb)
        if flat_fold.supports(sig):
            route = "flat"
        elif lookback_fold.supports(sig):
            route = "lookback"
        elif seg_fold.supports(sig):
            route = "seg"
        else:
            route = "full"
        planes = self._read_plane_ints(spec)
        return (sig, route, row_lo, row_hi, planes, tuple(lits),
                dev_aggs, lowering)

    @staticmethod
    def _agg_route_fn(route: str, sig):
        from yugabyte_db_tpu.ops import flat_fold, lookback_fold, seg_fold

        if route == "flat":
            # Flat run: one fused full-array program (bandwidth-roofline;
            # ops.flat_fold) instead of the serialized window fold.
            return flat_fold.compiled_flat_aggregate(sig)
        if route == "lookback":
            # Bounded version counts: shifted-mask resolve at the flat
            # path's memory roofline (ops.lookback_fold).
            return lookback_fold.compiled_lookback_aggregate(sig)
        if route == "seg":
            # Multi-version run: fused segmented-scan resolve
            # (ops.seg_fold) — same results as the windowed fold.
            return seg_fold.compiled_seg_aggregate(sig)
        return agg_fold.compiled_full_aggregate(sig)

    @staticmethod
    def _agg_finish(spec: ScanSpec, dev_aggs, lowering, raw: bool):
        def finish(f):
            iv, fv = f
            acc, scanned = agg_fold.unpack(dev_aggs, iv, fv)
            if raw:
                return acc, scanned
            out_row, names = [], []
            for a, (fn_name, di) in zip(spec.aggregates, lowering):
                names.append(f"{a.fn}({a.column or '*'})")
                out_row.append(agg_fold.finalize(dev_aggs[di], acc[di],
                                                 fn_name))
            return ScanResult(names, [tuple(out_row)], None, scanned)

        return finish

    def _plan_device_aggregate(self, trun: TpuRun, spec: ScanSpec,
                               exact_preds, raw: bool = False):
        """Single-dispatch full-run aggregate: the device fori_loops every
        window and returns two packed vectors (ops.agg_fold) — one dispatch
        plus two small transfers per scan, because the host link pays
        per-transfer latency (see ops/agg_fold.py docstring)."""
        prep = self._device_agg_prep(trun, spec, exact_preds)
        return self._dispatch_prepped(trun, spec, prep, raw=raw)

    @staticmethod
    @functools.lru_cache(maxsize=64)
    @compile_contract("batched_agg", max_compiles=256)
    def _batched_agg_fn(route: str, sig):
        """jit(vmap) of the per-spec aggregate program: N same-signature
        scans (distinct bounds / read points / predicate literals) in
        ONE dispatch. The run planes broadcast; everything else maps.
        Distinct batch sizes retrace inside the jit cache."""
        base = TpuStorageEngine._agg_route_fn(route, sig)
        return jax.jit(jax.vmap(base,
                                in_axes=(None, 0, 0, 0, 0, 0, 0, 0)))

    def _plan_device_aggregate_batch(self, items):
        """Batched device aggregates: group deferred specs by
        (run, signature, literal shapes) and dispatch each group as one
        vmapped program — the per-dispatch host cost and the per-scan
        transfer latency amortize across the whole group (the tserver
        shape: many concurrent aggregate queries differing only in
        bounds/literals). Returns [(pi, outs, finish)] entries for the
        async batch. Reference capability analog: doc-op batching in
        src/yb/docdb/doc_operation.cc (one RocksDB pass serving many
        ops) — here one DEVICE pass serving many scans."""
        preps = []
        for pi, trun, spec, exact in items:
            preps.append((pi, trun, spec,
                          self._device_agg_prep(trun, spec, exact)))
        groups: dict = {}
        for p in preps:
            (pi, trun, spec,
             (sig, route, row_lo, row_hi, planes, lits, da, lo)) = p
            lit_shapes = tuple(l.shape for l in lits)
            if route == "full":
                # The windowed fold's traced fori bounds don't vmap
                # cheaply; keep it per-spec.
                key = ("solo", pi)
            else:
                key = (id(trun), sig, route, lit_shapes)
            groups.setdefault(key, []).append(p)
        out = []
        for key, grp in groups.items():
            if key[0] == "solo" or len(grp) == 1:
                for pi, trun, spec, prep in grp:
                    outs, fin = self._dispatch_prepped(trun, spec, prep)
                    out.append((pi, outs, fin))
                continue
            _pi0, trun, _s0, (sig, route, *_rest) = grp[0]
            n = len(grp)
            # Pad lanes to the next power of two so drifting batch sizes
            # share at most log2(max) compiled variants (the same trick
            # the lookback signature uses). Pad lanes scan nothing
            # (row_lo == row_hi == 0) and their outputs are ignored.
            m = 1 << (n - 1).bit_length()
            row_lo_b = np.zeros(m, np.int32)
            row_hi_b = np.zeros(m, np.int32)
            planes_b = np.zeros((4, m), np.int32)
            lits0 = grp[0][3][5]
            lits_b = [np.zeros((m,) + l.shape, l.dtype) for l in lits0]
            for i, (_pi, _t, _s, (_sig, _r, rlo, rhi, pl, lits,
                                  _da, _lo)) in enumerate(grp):
                row_lo_b[i] = rlo
                row_hi_b[i] = rhi
                planes_b[:, i] = pl
                for k, l in enumerate(lits):
                    lits_b[k][i] = l
            fn = self._batched_agg_fn(route, sig)
            ivec, fvec = fn(trun.dev.arrays, row_lo_b, row_hi_b,
                            planes_b[0], planes_b[1], planes_b[2],
                            planes_b[3], tuple(lits_b))
            for i, (pi, _t, spec, (_sig, _r, _rlo, _rhi, _pl, _lits,
                                   dev_aggs, lowering)) in enumerate(grp):
                fin1 = self._agg_finish(spec, dev_aggs, lowering,
                                        raw=False)
                out.append((pi, [ivec, fvec],
                            lambda f, i=i, fin1=fin1:
                            fin1((f[0][i], f[1][i]))))
        return out

    def _dispatch_prepped(self, trun: TpuRun, spec: ScanSpec, prep,
                          raw: bool = False):
        """Dispatch one prepped aggregate (the per-spec path and the
        solo leg of the batched planner) without re-running host
        planning."""
        (sig, route, row_lo, row_hi, planes, lits,
         dev_aggs, lowering) = prep
        r_hi_, r_lo_, e_hi_, e_lo_ = (jnp.int32(v) for v in planes)
        pred_lits = tuple(jnp.asarray(l) for l in lits)
        fn = self._agg_route_fn(route, sig)
        if route == "full":
            W = trun.dev.B // sig.K
            w_first, w_last = agg_fold.window_bounds(row_lo, row_hi,
                                                     sig.R, sig.K, W)
            ivec, fvec = fn(trun.dev.arrays, jnp.int32(row_lo),
                            jnp.int32(row_hi),
                            jnp.int32(w_first), jnp.int32(w_last),
                            r_hi_, r_lo_, e_hi_, e_lo_, pred_lits)
        else:
            ivec, fvec = fn(trun.dev.arrays, jnp.int32(row_lo),
                            jnp.int32(row_hi), r_hi_, r_lo_, e_hi_, e_lo_,
                            pred_lits)
        return [ivec, fvec], self._agg_finish(spec, dev_aggs, lowering,
                                              raw=raw)


class _AsyncBatch:
    """An in-flight scan_batch: round-1 device work is issued and its
    outputs are streaming host-ward; .finish() consumes them (one fetch
    cycle worst case, free when the copies already landed), runs any host
    fallback scans, and drives the (rare) continuation rounds."""

    def __init__(self, eng, results, host_plans, issued_outs, gathers,
                 states, pending, dispatches, pages=(), pre_work=(),
                 pins=(), specs=(), deadline=None):
        self.eng = eng
        self.results = results
        self.host_plans = host_plans
        self.issued_outs = issued_outs
        self.gathers = gathers
        self.states = states
        self.pending = pending
        self.dispatches = dispatches
        self.pages = list(pages)
        self.pre_work = list(pre_work)
        self.pins = list(pins)
        self.specs = list(specs)
        self.deadline = deadline
        self._done = False

    def _release_pins(self) -> None:
        pins, self.pins = self.pins, []
        for trun in pins:
            trun.unpin()

    def __del__(self):
        # An abandoned batch (never finished) must still release its
        # residency pins, or the cache leaks protected bytes.
        try:
            self._release_pins()
        except Exception as e:  # noqa: BLE001 — interpreter teardown
            count_swallowed("tpu_engine.async_batch_del", e)

    def finish(self) -> list[ScanResult]:
        if self._done:
            return self.results
        try:
            out = self._finish()
        except DEVICE_FAULT_TYPES as e:
            # Mid-flight device fault: release the pins, report to the
            # breaker, and re-serve the WHOLE batch from the host — the
            # specs' pinned read points make the re-serve byte-identical
            # (MVCC: later writes are invisible at spec.read_ht).
            self._release_pins()
            self.eng.breaker.record_failure(e)
            self.results = self.eng._serve_host_batch(self.specs,
                                                      self.deadline)
            self._done = True
            return self.results
        except BaseException:
            self._release_pins()
            raise
        self._release_pins()
        self.eng.breaker.record_success()
        return out

    def _check_deadline(self) -> None:
        if self.deadline is not None:
            self.deadline.check("tpu_engine.scan_batch.finish")

    def _finish(self) -> list[ScanResult]:
        eng = self.eng
        results = self.results
        self._check_deadline()
        # Host work that overlaps the in-flight fetch (e.g. the delta
        # overlay's dirty-row fold), then host-path scans.
        for pre in self.pre_work:
            pre()
        for pi, fin in self.host_plans:
            results[pi] = fin()
        # Host page-cache scans through the native page server (numpy
        # plan/decode fallback inside serve_pages).
        if self.pages:
            served = host_page.serve_pages(
                eng, [it for _pi, it in self.pages])
            for (pi, _it), res in zip(self.pages, served):
                results[pi] = res
        # One fetch for everything issued in round 1 (device_get reuses
        # buffers the async copies already landed).
        disp_bufs, issued_np = jax.device_get(
            [[d for _c, d in self.dispatches],
             [o for _pi, o, _f in self.issued_outs]])
        for (pi, _outs, fin), f in zip(self.issued_outs, issued_np):
            results[pi] = fin(f)
        pending = eng._feed_round(self.states, self.pending,
                                  self.dispatches, disp_bufs)
        # Continuation rounds (overflow/verification shortfalls): plain
        # synchronous cycles. Each round re-checks the propagated
        # deadline: a budget that expired mid-scan aborts here and
        # finish() unwinds the residency pins on the way out.
        while pending:
            self._check_deadline()
            dispatches = eng._issue_round(self.states, pending)
            disp_bufs = jax.device_get([d for _c, d in dispatches])
            pending = eng._feed_round(self.states, pending, dispatches,
                                      disp_bufs)
        for pi, st in self.gathers:
            results[pi] = st.result()
        self._done = True
        return self.results


class _HostServeBatch:
    """The degraded-mode stand-in for _AsyncBatch: produced while the
    circuit breaker quarantines the device path (or after a fault struck
    during planning). Nothing was issued to the device and no residency
    pins are held; finish() serves the whole batch from the host."""

    def __init__(self, eng, specs, deadline=None):
        self.eng = eng
        self.specs = list(specs)
        self.deadline = deadline
        self.results: list | None = None
        self._done = False

    def finish(self) -> list[ScanResult]:
        if self._done:
            return self.results
        self.results = self.eng._serve_host_batch(self.specs,
                                                  self.deadline)
        self._done = True
        return self.results


class _GatherScan:
    """State of one in-flight device scan across scan_batch rounds.

    ``pending`` holds the param-rows to dispatch this round; ``consume``
    decodes the fetched buffers and returns the next round's param-rows
    ([] when the scan is complete). Continuations advance by global row
    index only — no host key lookups on the continuation path."""

    def __init__(self, eng: TpuStorageEngine, ctx, mode: str, pending,
                 w_last: int, row_hi: int):
        self.eng = eng
        self.ctx = ctx
        self.mode = mode          # "paged" | "chunks"
        self.pending = pending
        self.sig = ctx["sig"]
        self.trun = ctx["trun"]
        self.w_last = w_last
        self.row_hi = row_hi
        self.rows: list[tuple] = []
        self.scanned = 0
        self.resume: bytes | None = None

    def consume(self, bufs) -> list:
        eng, ctx = self.eng, self.ctx
        M = ctx["M"]
        if self.mode == "chunks":
            for buf in bufs:
                self.scanned += int(buf[M, 1])
                eng._emit_fetched(ctx, buf, self.rows)
            return []

        from yugabyte_db_tpu.ops import row_gather

        buf = bufs[0]
        (prev_ip, _prev_fp) = self.pending[0]
        w_cap = int(prev_ip[1])
        count = int(buf[M, 0])
        self.scanned += int(buf[M, 1])
        w_end = int(buf[M, 2])
        n = min(count, M)
        last_start = int(buf[n - 1, 0]) if n else None
        _c, _n, hit_limit, last = eng._emit_fetched(ctx, buf, self.rows)
        if hit_limit:
            self.resume = ctx["crun"].key_at(last) + b"\x00"
            self.pending = []
            return []
        # Complete iff no match was dropped (count > M: overflow) AND the
        # loop consumed every window up to the range end.
        if count <= M and w_end > w_cap and w_cap >= self.w_last:
            self.pending = []
            return []
        K, R = self.sig.K, self.sig.R
        if count > M:
            row_lo2 = last_start + 1
        else:
            row_lo2 = max(int(prev_ip[2]), w_end * K * R)
        if row_lo2 >= self.row_hi:
            self.pending = []
            return []
        w_first2 = row_lo2 // (K * R)
        # Windows up to w_end were already counted toward rows_scanned;
        # a mid-window resume must not re-count them.
        scan_from = max(row_lo2, w_end * K * R)
        ip, fp = row_gather.pack_params(
            w_first2, self.w_last, row_lo2, self.row_hi, ctx["read_planes"],
            ctx["int_lits"], ctx["f32_lits"], scan_from=scan_from)
        self.pending = [(ip, fp)]
        return self.pending

    def result(self) -> ScanResult:
        return self.eng._gather_result(self.ctx, self.rows, self.scanned,
                                       self.resume)


_literal = agg_fold.pred_literal


register_engine("tpu", TpuStorageEngine)
