"""TPU storage engine: the ``tablet_storage_engine=tpu`` data plane.

The north-star component (BASELINE.json): scans, MVCC merge-on-read,
predicate filtering and aggregate pushdown execute as device programs over
HBM-resident columnar runs (ops.scan over storage.columnar), while writes,
the memtable, and exact tie/varlen handling stay host-side. Query results
are required to be identical to CpuStorageEngine (the oracle) — the
engine-diff tests enforce it.

Read-path policy (correctness first, device fast path where it's sound):

- single-source scans (one run covers the range, memtable empty there):
  device evaluates visibility + range + numeric predicates exactly; varlen
  (string) predicates produce a candidate SUPERSET that the host verifies
  during materialization.
- multi-source scans (several overlapping runs and/or a live memtable):
  each run reports candidate keys from the device without predicate
  filtering (a column's latest value may live in another source, so
  per-source predicate evaluation is unsound — see ops/scan.py); the host
  merges versions across sources per candidate key (storage.merge) and
  applies predicates. Memtable keys in range are always candidates.
- aggregates push down to the device (per-block partials, exact integer
  limb sums) only when the scan is single-source and every predicate is
  device-exact; otherwise they fall back to the row path + host Aggregator.

Reference analog of the seam/merge behavior: DocRowwiseIterator over an
IntentAwareIterator merging regular/provisional sources
(src/yb/docdb/doc_rowwise_iterator.cc, intent_aware_iterator.h:81).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.schema import Schema
from yugabyte_db_tpu.ops import agg_fold
from yugabyte_db_tpu.ops import scan as dscan
from yugabyte_db_tpu.ops.device_run import DeviceRun, dtype_kind
from yugabyte_db_tpu.storage.columnar import ColumnarRun
from yugabyte_db_tpu.storage.cpu_engine import Aggregator, RowMaterializer
from yugabyte_db_tpu.storage.engine import StorageEngine, register_engine
from yugabyte_db_tpu.storage.memtable import MemTable
from yugabyte_db_tpu.storage.merge import merge_versions
from yugabyte_db_tpu.storage.row_version import MAX_HT, RowVersion
from yugabyte_db_tpu.storage.scan_spec import ScanResult, ScanSpec
from yugabyte_db_tpu.utils import planes as P

WINDOW_BLOCKS = 8          # blocks per device dispatch on the row path
PAD_BLOCKS = 64            # run block-axis padding (multiple of every window)


class TpuRun:
    def __init__(self, crun: ColumnarRun):
        self.crun = crun
        self.dev = DeviceRun(crun, PAD_BLOCKS)


class TpuStorageEngine(StorageEngine):
    def __init__(self, schema: Schema, options: dict | None = None):
        super().__init__(schema, options)
        self.memtable = MemTable()
        self.runs: list[TpuRun] = []
        self.mat = RowMaterializer(schema)
        self.flushed_frontier_ht = 0
        self.rows_per_block = self.options.get("rows_per_block", 2048)
        self._kinds = {c.col_id: dtype_kind(c.dtype)
                       for c in schema.value_columns}
        self._name_to_id = {c.name: c.col_id for c in schema.value_columns}
        self._key_col_names = {c.name for c in schema.key_columns}
        from yugabyte_db_tpu.storage.run_io import RunPersistence

        self.persist = RunPersistence(self.options.get("data_dir"))
        for entries in self.persist.load_all():
            crun = ColumnarRun.build(self.schema, entries, self.rows_per_block)
            self.runs.append(TpuRun(crun))
            self.flushed_frontier_ht = max(self.flushed_frontier_ht, crun.max_ht)

    # -- writes ------------------------------------------------------------
    def apply(self, rows: list[RowVersion]) -> None:
        self.memtable.apply(rows)
        limit = self.options.get("memtable_flush_versions", 1 << 60)
        if self.memtable.num_versions >= limit:
            self.flush()
            self.maybe_compact()

    # -- lifecycle ---------------------------------------------------------
    def flush(self) -> None:
        if self.memtable.is_empty:
            return
        if self.memtable.max_ht is not None:
            self.flushed_frontier_ht = max(self.flushed_frontier_ht,
                                           self.memtable.max_ht)
        entries = self.memtable.drain_sorted()
        self.persist.save_new(entries)
        crun = ColumnarRun.build(self.schema, entries, self.rows_per_block)
        self.runs.append(TpuRun(crun))
        self.memtable = MemTable()

    def compact(self, history_cutoff_ht: int = 0) -> None:
        """Merge all runs into one. Host-side k-way merge + shared GC for
        now; the device sort-merge path (ops.merge) takes over for large
        runs once wired in."""
        import heapq

        from yugabyte_db_tpu.storage.cpu_engine import CpuStorageEngine

        if len(self.runs) <= 1 and history_cutoff_ht == 0:
            return

        def run_iter(trun):
            return ((k, vs) for k, vs in trun.crun.iter_entries())

        merged = []
        current, bucket = None, []
        for key, versions in heapq.merge(*[run_iter(t) for t in self.runs],
                                         key=lambda p: p[0]):
            if key != current:
                if current is not None:
                    self._emit_group(merged, current, bucket, history_cutoff_ht,
                                     CpuStorageEngine)
                current, bucket = key, []
            bucket.extend(versions)
        if current is not None:
            self._emit_group(merged, current, bucket, history_cutoff_ht,
                             CpuStorageEngine)
        self.persist.replace_all(merged)
        crun = ColumnarRun.build(self.schema, merged, self.rows_per_block)
        self.runs = [TpuRun(crun)] if merged else []

    @staticmethod
    def _emit_group(out, key, versions, cutoff, cpu_cls):
        versions = sorted(versions, key=lambda r: -r.ht)
        kept = cpu_cls._gc_versions(key, versions, cutoff)
        if kept:
            out.append((key, kept))

    def stats(self) -> dict:
        return {
            "num_runs": len(self.runs),
            "memtable_versions": self.memtable.num_versions,
            "run_versions": sum(t.crun.num_versions for t in self.runs),
            "flushed_frontier_ht": self.flushed_frontier_ht,
        }

    # -- scan plumbing ------------------------------------------------------
    def _overlapping_runs(self, spec: ScanSpec) -> list[TpuRun]:
        out = []
        for t in self.runs:
            if t.crun.num_versions == 0:
                continue
            if spec.upper and t.crun.min_key >= spec.upper:
                continue
            if t.crun.max_key < spec.lower:
                continue
            out.append(t)
        return out

    def _memtable_in_range(self, spec: ScanSpec) -> bool:
        return next(self.memtable.scan_keys(spec.lower, spec.upper), None) is not None

    def _split_predicates(self, spec: ScanSpec):
        """(device-exact preds, device-superset preds, host-only preds).

        'str' prefixes and 'f32' rounded values give superset masks only
        (ties are maybe-matches the host verifies); key-column and IN
        predicates are host-only."""
        exact, superset, host_only = [], [], []
        for p in spec.predicates:
            if p.column in self._key_col_names or p.op == "IN":
                host_only.append(p)
                continue
            kind = self._kinds[self._name_to_id[p.column]]
            if kind in ("str", "f32"):
                superset.append(p)
            else:
                exact.append(p)
        return exact, superset, host_only

    def _aggs_device_eligible(self, spec: ScanSpec) -> bool:
        """Device aggregates need every aggregate column to be a numeric
        VALUE column (key columns live in the encoded key, not in planes;
        string min/max needs full bytes the device doesn't have)."""
        for a in spec.aggregates:
            if a.column is None:
                continue
            cid = self._name_to_id.get(a.column)
            if cid is None:
                return False  # key column (or unknown): host path
            if self._kinds[cid] == "str" and a.fn != "count":
                return False
        return True

    def _pred_sig_and_literals(self, preds):
        sigs, lits = [], []
        for p in preds:
            cid = self._name_to_id[p.column]
            kind = self._kinds[cid]
            sigs.append(dscan.PredSig(cid, kind, p.op))
            lits.append(_literal(kind, p.value))
        return tuple(sigs), tuple(lits)

    def _col_sigs(self):
        return tuple(dscan.ColSig(c.col_id, self._kinds[c.col_id])
                     for c in self.schema.value_columns)

    def _read_planes(self, spec: ScanSpec):
        r_hi, r_lo = P.scalar_ht_planes(min(spec.read_ht, MAX_HT))
        e_hi, e_lo = P.scalar_ht_planes(min(spec.read_ht, MAX_HT - 1))
        return (jnp.int32(r_hi), jnp.int32(r_lo),
                jnp.int32(e_hi), jnp.int32(e_lo))

    def _device_candidates(self, trun: TpuRun, spec: ScanSpec,
                           pred_sigs, pred_lits, apply_preds: bool):
        """Run the device row-scan over the block windows covering the range;
        yield candidate keys (host-materialized, in key order)."""
        crun = trun.crun
        row_lo = crun.lower_row(spec.lower)
        row_hi = crun.upper_row(spec.upper)
        if row_lo >= row_hi:
            return
        R = crun.R
        K = WINDOW_BLOCKS
        b_first = (row_lo // R) // K * K
        b_last = ((row_hi - 1) // R) // K * K
        sig = dscan.ScanSig(B=trun.dev.B, R=R, K=K, cols=self._col_sigs(),
                            preds=pred_sigs, aggs=(), apply_preds=apply_preds)
        fn = dscan.compiled_scan(sig)
        r_hi_, r_lo_, e_hi_, e_lo_ = self._read_planes(spec)
        for b0 in range(b_first, b_last + 1, K):
            base = b0 * R
            res = fn(trun.dev.arrays, jnp.int32(b0),
                     jnp.int32(np.clip(row_lo - base, -(1 << 30), 1 << 30)),
                     jnp.int32(np.clip(row_hi - base, -(1 << 30), 1 << 30)),
                     r_hi_, r_lo_, e_hi_, e_lo_, pred_lits)
            mask = np.asarray(res["result"])
            ng = int(res["num_groups"])
            start = np.asarray(res["start_idx"])
            for g in np.nonzero(mask[:ng])[0]:
                yield crun.key_at(base + int(start[g]))

    # -- reads -------------------------------------------------------------
    def scan(self, spec: ScanSpec) -> ScanResult:
        runs = self._overlapping_runs(spec)
        mem_live = self._memtable_in_range(spec)
        exact, superset, host_only = self._split_predicates(spec)
        single_source = len(runs) == 1 and not mem_live

        if spec.is_aggregate:
            eligible = (single_source and not superset and not host_only
                        and not spec.group_by
                        and self._aggs_device_eligible(spec))
            if eligible and runs:
                return self._device_aggregate(runs[0], spec, exact)
            return self._row_scan(spec, runs, mem_live,
                                  (exact, superset, host_only), aggregate=True)
        return self._row_scan(spec, runs, mem_live,
                              (exact, superset, host_only), aggregate=False)

    def _row_scan(self, spec: ScanSpec, runs, mem_live, pred_split,
                  aggregate: bool):
        exact, superset, host_only = pred_split
        single_source = len(runs) == 1 and not mem_live
        apply_preds = single_source
        pred_sigs, pred_lits = (
            self._pred_sig_and_literals(exact + superset) if apply_preds
            else ((), ()))

        key_streams = [
            self._device_candidates(t, spec, pred_sigs, pred_lits, apply_preds)
            for t in runs
        ]
        if mem_live or not self.memtable.is_empty:
            key_streams.append(self.memtable.scan_keys(spec.lower, spec.upper))

        import heapq

        candidates = heapq.merge(*key_streams)
        projection = spec.projection or [c.name for c in self.schema.columns]
        agg = Aggregator(spec.aggregates or [], spec.group_by or []) \
            if aggregate else None
        rows: list[tuple] = []
        scanned = 0
        resume = None
        last = None
        for key in candidates:
            if key == last:
                continue
            last = key
            scanned += 1
            versions: list[RowVersion] = []
            for t in runs:
                versions.extend(t.crun.find_versions(key))
            versions.extend(self.memtable.versions(key))
            merged = merge_versions(key, versions, spec.read_ht)
            if not merged.exists:
                continue
            key_vals = self.mat.key_values(key)
            if not self.mat.matches(spec, key_vals, merged):
                continue
            if aggregate:
                agg.add(lambda name: self.mat.value(name, key_vals, merged))
                continue
            rows.append(tuple(
                self.mat.value(name, key_vals, merged) for name in projection))
            if spec.limit is not None and len(rows) >= spec.limit:
                resume = key + b"\x00"
                break
        if aggregate:
            return ScanResult(agg.column_names(), agg.results(), None, scanned)
        return ScanResult(projection, rows, resume, scanned)

    # -- device aggregate path ---------------------------------------------
    def _device_aggregate(self, trun: TpuRun, spec: ScanSpec, exact_preds):
        """Single-dispatch full-run aggregate: the device fori_loops every
        window and returns two packed vectors (ops.agg_fold) — one dispatch
        plus two small transfers per scan, because the host link pays
        per-transfer latency (see ops/agg_fold.py docstring)."""
        crun = trun.crun
        row_lo = crun.lower_row(spec.lower)
        row_hi = crun.upper_row(spec.upper)
        pred_sigs, pred_lits = self._pred_sig_and_literals(exact_preds)
        dev_aggs, lowering = agg_fold.lower_aggs(
            spec.aggregates, self._name_to_id, self._kinds)

        R = crun.R
        K = agg_fold.safe_window_blocks(R, agg_fold.FULL_WINDOW_BLOCKS)
        sig = dscan.ScanSig(B=trun.dev.B, R=R, K=K, cols=self._col_sigs(),
                            preds=pred_sigs, aggs=dev_aggs, apply_preds=True)
        W = trun.dev.B // K
        w_first, w_last = agg_fold.window_bounds(row_lo, row_hi, R, K, W)
        fn = agg_fold.compiled_full_aggregate(sig)
        r_hi_, r_lo_, e_hi_, e_lo_ = self._read_planes(spec)
        ivec, fvec = fn(trun.dev.arrays, jnp.int32(row_lo), jnp.int32(row_hi),
                        jnp.int32(w_first), jnp.int32(w_last),
                        r_hi_, r_lo_, e_hi_, e_lo_, pred_lits)
        iv, fv = jax.device_get([ivec, fvec])
        acc, scanned = agg_fold.unpack(dev_aggs, iv, fv)

        out_row, names = [], []
        for a, (fn_name, di) in zip(spec.aggregates, lowering):
            names.append(f"{a.fn}({a.column or '*'})")
            out_row.append(agg_fold.finalize(dev_aggs[di], acc[di], fn_name))
        return ScanResult(names, [tuple(out_row)], None, scanned)


_literal = agg_fold.pred_literal


register_engine("tpu", TpuStorageEngine)
