"""MVCC merge-on-read semantics, shared by every engine.

This module is the single source of truth for how a set of RowVersions of
one key collapses to the visible row at a read hybrid time. The CPU engine
executes it directly per key; the TPU kernels implement the same function
vectorized over plane arrays (ops/scan.py), and the randomized engine-diff
tests hold the two to identical results.

Reference analog: docdb::GetSubDocument's version/tombstone/TTL resolution
(src/yb/docdb/docdb.cc:849) and the IntentAwareIterator read-point filtering
(src/yb/docdb/intent_aware_iterator.h:81).

Rules (versions sorted ht desc; "visible" = ht <= read_ht):
1. tomb_ht = max ht of visible row tombstones (0 if none). Versions with
   ht <= tomb_ht are shadowed.
2. Per column: the value is the newest visible unshadowed version that sets
   the column; if that value is TTL-expired at read_ht it reads as NULL but
   still shadows older versions (expiry == tombstone at the value's ht).
3. Row liveness: the newest visible unshadowed non-expired liveness marker.
4. The row exists iff it has liveness or any non-null column value.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from yugabyte_db_tpu.storage.row_version import RowVersion


@dataclass
class MergedRow:
    """Per-source merge result for one key; combinable across sources."""

    key: bytes
    tomb_ht: int = 0                      # max visible row-tombstone ht
    live_ht: int = 0                      # max visible liveness ht (0 = none)
    values: dict = field(default_factory=dict)   # col_id -> value (None = null)
    value_hts: dict = field(default_factory=dict)  # col_id -> ht of that value

    @property
    def exists(self) -> bool:
        if self.live_ht > self.tomb_ht:
            return True
        return any(
            v is not None and self.value_hts[c] > self.tomb_ht
            for c, v in self.values.items()
        )

    def get(self, col_id: int):
        if col_id in self.values and self.value_hts[col_id] > self.tomb_ht:
            return self.values[col_id]
        return None


def merge_versions(key: bytes, versions: list[RowVersion], read_ht: int) -> MergedRow:
    """Collapse one key's versions (any order) to its MergedRow at read_ht."""
    out = MergedRow(key)
    for v in versions:
        if v.ht > read_ht:
            continue
        if v.tombstone and v.ht > out.tomb_ht:
            out.tomb_ht = v.ht
    for v in sorted(versions, key=lambda r: (-r.ht, -r.write_id)):
        if v.ht > read_ht or v.ht <= out.tomb_ht or v.tombstone:
            continue
        expired = v.has_ttl and read_ht >= v.expire_ht
        if v.liveness and not expired and v.ht > out.live_ht:
            out.live_ht = v.ht
        for cid, val in v.columns.items():
            if cid not in out.values:
                out.values[cid] = None if expired else val
                out.value_hts[cid] = v.ht
    return out


def combine_merged(a: MergedRow, b: MergedRow) -> MergedRow:
    """Combine two per-source MergedRows of the SAME key (e.g. memtable
    overlay + device-scanned runs, or overlapping sorted runs).

    Associative and commutative: the newest tombstone wins globally, then
    per column the newest value wins, then shadowing is re-applied via
    tomb_ht at read time (MergedRow.get / .exists).
    """
    if a.key != b.key:
        raise ValueError("combine_merged requires identical keys")
    out = MergedRow(a.key)
    out.tomb_ht = max(a.tomb_ht, b.tomb_ht)
    out.live_ht = max(a.live_ht, b.live_ht)
    for src in (a, b):
        for cid, val in src.values.items():
            ht = src.value_hts[cid]
            if cid not in out.values or ht > out.value_hts[cid]:
                out.values[cid] = val
                out.value_hts[cid] = ht
    return out


def merge_entry_streams(streams):
    """K-way merge of (key, versions ht-desc) streams into grouped
    (key, versions ht-desc) pairs in key order — the shared inner loop of
    compaction and remote-bootstrap dumps (reference: the MergingIterator
    under CompactionJob::Run, src/yb/rocksdb/db/compaction_job.cc:622)."""
    import heapq

    current, bucket = None, []
    for key, versions in heapq.merge(*streams, key=lambda p: p[0]):
        if key != current:
            if current is not None:
                yield current, sorted(bucket, key=lambda r: (-r.ht, -r.write_id))
            current, bucket = key, []
        bucket.extend(versions)
    if current is not None:
        yield current, sorted(bucket, key=lambda r: (-r.ht, -r.write_id))
