"""Hashed-prefix bloom filter for sorted-run pruning.

Reference analog: DocDbAwareFilterPolicy — the RocksDB fork blooms on
the DocKey *hashed-components prefix* only (src/yb/docdb/doc_key.h:
551-575, boundary extraction in doc_boundary_values_extractor.cc), so a
point get (or any scan bounded within one primary key's hash section)
can skip SSTables that cannot contain the key. Here the filter is a
plain numpy bit array per ColumnarRun, rebuilt from host-resident keys
on load (no persistence needed — construction is one hash per distinct
key group).

Double hashing (Kirsch–Mitzenmacher): two 64-bit halves of one
blake2b digest generate all k probe positions.
"""

from __future__ import annotations

import hashlib

import numpy as np

BITS_PER_KEY = 10   # ~1% false-positive rate at k=7
NUM_PROBES = 7
_MASK64 = (1 << 64) - 1


class BloomFilter:
    __slots__ = ("m", "bits")

    def __init__(self, n_items: int):
        self.m = max(64, n_items * BITS_PER_KEY)
        self.bits = np.zeros((self.m + 63) // 64, dtype=np.uint64)

    def _probes(self, data: bytes):
        d = hashlib.blake2b(data, digest_size=16).digest()
        h1 = int.from_bytes(d[:8], "little")
        h2 = int.from_bytes(d[8:], "little") | 1
        m = self.m
        # 64-bit wrap before the mod, matching add_many's uint64 math.
        return [(((h1 + i * h2) & _MASK64) % m)
                for i in range(NUM_PROBES)]

    def add(self, data: bytes) -> None:
        for p in self._probes(data):
            self.bits[p >> 6] |= np.uint64(1 << (p & 63))

    def add_many(self, items) -> None:
        """Bulk insert: per-item blake2 stays in Python (fast C call),
        the k probe positions and bit sets vectorize in numpy — ~5x the
        one-at-a-time loop on full-run builds."""
        n = len(items)
        if not n:
            return
        h1 = np.empty(n, np.uint64)
        h2 = np.empty(n, np.uint64)
        for i, data in enumerate(items):
            d = hashlib.blake2b(data, digest_size=16).digest()
            h1[i] = int.from_bytes(d[:8], "little")
            h2[i] = int.from_bytes(d[8:], "little") | 1
        m = np.uint64(self.m)
        one = np.uint64(1)
        six = np.uint64(6)
        mask = np.uint64(63)
        with np.errstate(over="ignore"):
            for i in range(NUM_PROBES):
                p = (h1 + np.uint64(i) * h2) % m
                np.bitwise_or.at(self.bits,
                                 (p >> six).astype(np.int64),
                                 one << (p & mask))

    def may_contain(self, data: bytes) -> bool:
        for p in self._probes(data):
            if not (int(self.bits[p >> 6]) >> (p & 63)) & 1:
                return False
        return True
