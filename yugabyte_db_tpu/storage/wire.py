"""Wire codecs for storage types crossing RPC/WAL boundaries.

Reference analog: the request/response protos carrying row operations and
scan state (src/yb/common/ql_protocol.proto, wire_protocol.proto). One
canonical encoding serves the WAL body (tablet.py) and the client/tserver
RPCs, so a WAL entry can be shipped verbatim during catchup.
"""

from __future__ import annotations

from yugabyte_db_tpu.storage.row_version import MAX_HT, RowVersion
from yugabyte_db_tpu.storage.scan_spec import (AggSpec, Predicate, ScanResult,
                                               ScanSpec)


# -- rows -------------------------------------------------------------------

def encode_rows(rows: list[RowVersion]) -> list:
    # Column ids ride as INT map keys (the codec supports any scalar
    # key); decode_rows accepts the legacy str-keyed form from older WAL
    # segments.
    return [
        [r.key, r.ht, r.tombstone, r.liveness, r.columns, r.expire_ht,
         r.ttl_us, r.write_id, r.increments or None]
        for r in rows
    ]


def _int_keys(d: dict) -> dict:
    if not d:
        return {}
    for k in d:  # all-int fast path: no per-entry rebuild
        if not isinstance(k, int):
            return {int(c): v for c, v in d.items()}
        break
    return d


def decode_rows(body: list) -> list[RowVersion]:
    return [
        RowVersion(rec[0], ht=rec[1], tombstone=rec[2], liveness=rec[3],
                   columns=_int_keys(rec[4]),
                   expire_ht=rec[5],
                   ttl_us=rec[6] if len(rec) > 6 else None,
                   write_id=rec[7] if len(rec) > 7 else 0,
                   increments=_int_keys(rec[8])
                   if len(rec) > 8 and rec[8] else {})
        for rec in body
    ]


# -- scan specs -------------------------------------------------------------

def encode_spec(spec: ScanSpec) -> dict:
    return {
        "lower": spec.lower,
        "upper": spec.upper,
        "read_ht": spec.read_ht,
        "predicates": [[p.column, p.op,
                        list(p.value) if p.op == "IN" else p.value]
                       for p in spec.predicates],
        "projection": spec.projection,
        "limit": spec.limit,
        "aggregates": ([[a.fn, a.column, _encode_expr(a.expr), a.label]
                        for a in spec.aggregates]
                       if spec.aggregates else None),
        "group_by": spec.group_by,
    }


def _encode_expr(e):
    from yugabyte_db_tpu.storage import expr as X

    if e is None:
        return None
    if isinstance(e, X.Col):
        return ["c", e.name]
    if isinstance(e, X.Const):
        return ["k", e.value]
    return ["b", e.op, _encode_expr(e.left), _encode_expr(e.right)]


def _decode_expr(d):
    from yugabyte_db_tpu.storage import expr as X

    if d is None:
        return None
    if d[0] == "c":
        return X.Col(d[1])
    if d[0] == "k":
        return X.Const(d[1])
    return X.BinOp(d[1], _decode_expr(d[2]), _decode_expr(d[3]))


def decode_spec(d: dict) -> ScanSpec:
    return ScanSpec(
        lower=d.get("lower", b""),
        upper=d.get("upper", b""),
        read_ht=d.get("read_ht", MAX_HT),
        predicates=[
            Predicate(c, op, tuple(v) if op == "IN" else v)
            for c, op, v in d.get("predicates", [])
        ],
        projection=d.get("projection"),
        limit=d.get("limit"),
        aggregates=([AggSpec(a[0], a[1],
                             expr=_decode_expr(a[2]) if len(a) > 2 else None,
                             label=a[3] if len(a) > 3 else None)
                     for a in d["aggregates"]]
                    if d.get("aggregates") else None),
        group_by=d.get("group_by"),
    )


# -- scan results -----------------------------------------------------------

def encode_result(res: ScanResult) -> dict:
    return {
        "columns": res.columns,
        "rows": [list(r) for r in res.rows],
        "resume_key": res.resume_key,
        "rows_scanned": res.rows_scanned,
    }


def decode_result(d: dict) -> ScanResult:
    return ScanResult(
        columns=d["columns"],
        rows=[tuple(r) for r in d["rows"]],
        resume_key=d.get("resume_key"),
        rows_scanned=d.get("rows_scanned", 0),
    )
