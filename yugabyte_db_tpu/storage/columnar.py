"""Columnar sorted runs: the TPU-native SSTable.

This is the storage-format heart of the framework (SURVEY.md §7): where the
reference stores row-wise prefix-delta-compressed byte blocks
(src/yb/rocksdb/table/block_builder.cc:29-46), a ColumnarRun stores
fixed-shape SoA planes sized for HBM tiling:

- rows are MVCC versions sorted (encoded key asc, commit ht desc), grouped
  by key; a key's versions never span a block boundary (so device kernels
  can treat each block window as segment-complete);
- keys are represented device-side by a fixed-width big-endian word prefix
  as int32 "planes" (signed compare == byte order, utils.planes); full key
  bytes stay host-side for ties/materialization;
- every 64-bit ordered quantity (hybrid times, int64/double values) is two
  int32 planes; varlen values keep an 8-byte order-preserving prefix on
  device and their payload host-side;
- per-block metadata (min/max key, max commit ht) plays the role of the
  reference's index blocks + UserFrontiers (src/yb/rocksdb/metadata.h:103)
  and drives host-side block pruning.

The numpy arrays here are the host mirror; ops.device_run uploads them.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.schema import Schema
from yugabyte_db_tpu.storage.row_version import MAX_HT, RowVersion
from yugabyte_db_tpu.utils import planes as P

DEFAULT_ROWS_PER_BLOCK = 2048
KEY_WORDS = 8  # 32-byte key prefix on device


def _varlen_raw(v) -> bytes:
    """Bytes for a varlen value's device prefix planes. Strings/bytes are
    their contents (order-preserving compares); opaque containers
    (collections, jsonb) serialize deterministically — their prefix is
    only an equality heuristic, predicates on them stay host-side."""
    if isinstance(v, str):
        return v.encode("utf-8", "surrogateescape")
    if isinstance(v, (bytes, bytearray)):
        return bytes(v)
    return repr(v).encode("utf-8", "surrogateescape")


@dataclass
class ColumnData:
    """Host planes for one value column across all blocks: [B, R, ...]."""

    dtype: DataType
    set_: np.ndarray          # bool: version sets this column
    isnull: np.ndarray        # bool: set and value is NULL
    cmp_planes: np.ndarray    # [B, R, P] int32: order planes (compare/minmax)
    arith: np.ndarray | None  # [B, R] float32 arithmetic plane (numeric only)
    varlen: list | None       # per-block list of python payloads (varlen only)


@dataclass
class BlockMeta:
    min_key: bytes
    max_key: bytes
    num_valid: int


class ColumnarRun:
    """One immutable sorted run in blocked columnar layout."""

    def __init__(self, schema: Schema, rows_per_block: int = DEFAULT_ROWS_PER_BLOCK):
        self.schema = schema
        self.R = rows_per_block
        self.B = 0
        self.num_versions = 0
        self.blocks: list[BlockMeta] = []
        # Filled by build():
        self.key_planes: np.ndarray | None = None   # [B, R, KEY_WORDS] i32
        self.ht_hi = self.ht_lo = None              # [B, R] i32
        self.exp_hi = self.exp_lo = None            # [B, R] i32
        self.tomb = self.live = self.valid = self.group_start = None  # [B, R] bool
        self.cols: dict[int, ColumnData] = {}       # col_id -> ColumnData
        # Host-side exact data for ties/materialization/compaction —
        # [B, R] OBJECT ndarrays (bytes / RowVersion / key-value lists)
        # so compaction slices whole blocks as views:
        self.row_keys: np.ndarray | None = None     # [B, R] object (b"" pad)
        self.row_versions: np.ndarray | None = None  # [B, R] object
        self.min_key = b""
        self.max_key = b""
        self.max_ht = 0
        # Largest key-group version count. 1 means the run is "flat": every
        # row is its own group, so device kernels can skip the segmented
        # MVCC merge machinery entirely (the common post-compaction shape).
        self.max_group_versions = 0
        # Longest varlen value per column (bytes): values <= 8 are fully
        # captured by the device prefix planes, making prefix equality
        # EXACT — the device GROUP BY eligibility check for strings.
        self.varlen_max_len: dict[int, int] = {}
        # Longest encoded key (bytes): keys <= 32 are fully captured by
        # the KEY_WORDS prefix planes, so plane equality/order is EXACT —
        # the device compaction eligibility check.
        self.max_key_len = 0
        # Lazily-built per-key-column object arrays (global row index ->
        # decoded key value) for C-speed fancy-indexed materialization of
        # key columns on the batched scan path; decoded block-by-block
        # under a lock (concurrent scans share one tablet's run).
        import threading

        self._kv_cols: list[np.ndarray] | None = None
        self._kv_blocks_done: set[int] = set()
        # Lazily-encoded compressed device plane tree (ops.encodings):
        # (cache_key, tree) — recomputed when the encoding flag flips or
        # alter_schema grows the column set. ``enc_dicts`` holds each
        # dictionary-encoded column's sorted value list so the engine
        # can translate string predicates to code-range compares.
        self._enc_cache: tuple | None = None
        self.enc_dicts: dict[int, list[bytes]] = {}
        self.enc_stats: dict | None = None
        self.kv_ready = False  # True once every block's keys are decoded
        # Hashed-prefix bloom (storage.bloom): None = not built yet,
        # True = inapplicable (range-partitioned keys present).
        self._hash_bloom = None
        self._kv_lock = threading.Lock()

    # -- construction ------------------------------------------------------
    @staticmethod
    def build(schema: Schema, entries: list[tuple[bytes, list[RowVersion]]],
              rows_per_block: int = DEFAULT_ROWS_PER_BLOCK) -> "ColumnarRun":
        """entries: (key asc, versions ht-desc) — MemTable.drain_sorted() or a
        compaction merge. Packs key groups into blocks without splitting."""
        run = ColumnarRun(schema, rows_per_block)
        R = run.R
        for key, versions in entries:
            n = len(versions)
            if n > run.max_group_versions:
                run.max_group_versions = n
            if len(key) > run.max_key_len:
                run.max_key_len = len(key)
        # Greedy block packing, key groups kept whole (shared with the
        # device-compaction gather path).
        ranges = ColumnarRun.pack_group_ranges(
            [len(v) for _, v in entries], R)
        blocks = [entries[g0:g0 + gn] for g0, gn, _rows in ranges]
        B = max(1, len(blocks))
        run.B = B
        run._alloc(B)
        for b, group_list in enumerate(blocks):
            run._fill_block(b, group_list)
        run.min_key = blocks[0][0][0] if blocks else b""
        run.max_key = blocks[-1][-1][0] if blocks else b""
        run.num_versions = sum(len(v) for _, v in entries)
        return run

    # Value-column kinds the native flush understands (drain_run).
    _NATIVE_KIND = {
        DataType.INT8: 0, DataType.INT16: 0, DataType.INT32: 0,
        DataType.INT64: 0, DataType.TIMESTAMP: 0, DataType.COUNTER: 0,
        DataType.BOOL: 0,
        DataType.DOUBLE: 1, DataType.FLOAT: 2,
        DataType.STRING: 3, DataType.BINARY: 3, DataType.LIST: 3,
        DataType.SET: 3, DataType.MAP: 3, DataType.JSONB: 3,
        DataType.DECIMAL: 3, DataType.VARINT: 3, DataType.UUID: 3,
        DataType.TIMEUUID: 3, DataType.INET: 3, DataType.DATE: 3,
        DataType.TIME: 3, DataType.TUPLE: 3, DataType.FROZEN: 3,
    }

    @staticmethod
    def build_from_memtable(schema: Schema, mt,
                            rows_per_block: int) -> "ColumnarRun | None":
        """The native flush path: one C pass over the sorted memtable
        (yb_wp.Memtable.drain_run) emits flat packed buffers — block
        packing, key prefixes, per-column values, RowVersion payloads —
        and this assembles the [B, R] planes with vectorized numpy only
        (no per-row Python anywhere). Returns None when the memtable
        shape needs the generic path (spilled big-int rows, value kinds
        the C pass doesn't cover) — callers fall back to
        drain_sorted() + build(). Reference analog: the rocksdb flush
        building the SSTable straight off the memtable iterator
        (src/yb/rocksdb/db/flush_job.cc)."""
        native_mt = getattr(mt, "_mt", None)
        if native_mt is None or getattr(mt, "_spill", None):
            return None
        desc = []
        for c in schema.value_columns:
            kind = ColumnarRun._NATIVE_KIND.get(c.dtype)
            if kind is None:
                return None
            desc.append((c.col_id, kind))
        try:
            data = native_mt.drain_run(rows_per_block, KEY_WORDS, desc)
        except (TypeError, ValueError):
            return None  # value shape outside the C pass: generic path
        n = data["n"]
        run = ColumnarRun(schema, rows_per_block)
        R = rows_per_block
        ranges = np.frombuffer(data["ranges"], np.int32).reshape(-1, 3)
        B = max(1, ranges.shape[0])
        run.B = B
        run._alloc(B)
        run.max_key_len = data["max_key_len"]
        run.max_group_versions = max(run.max_group_versions,
                                     data["max_group"])
        run.num_versions = n
        sizes = np.frombuffer(data["group_sizes"], np.int32)
        keys_list = data["keys"]
        if n == 0:
            return run
        # Destination slot of packed row i: blocks keep whole key
        # groups; rows pack densely from each block's start.
        rows_per = ranges[:, 2]
        block_of = np.repeat(np.arange(ranges.shape[0], dtype=np.int64),
                             rows_per)
        offs = np.cumsum(rows_per) - rows_per
        dst = block_of * R + (np.arange(n, dtype=np.int64)
                              - np.repeat(offs, rows_per))

        def scatter(dest, vals):
            dest.reshape((dest.shape[0] * R,) + dest.shape[2:])[dst] = \
                vals

        ht = np.frombuffer(data["ht"], np.uint64)
        hi, lo = P.u64_to_planes(ht)
        scatter(run.ht_hi, hi)
        scatter(run.ht_lo, lo)
        run.max_ht = int(ht.max())
        ehi, elo = P.u64_to_planes(
            np.frombuffer(data["exp"], np.uint64) & np.uint64(MAX_HT))
        scatter(run.exp_hi, ehi)
        scatter(run.exp_lo, elo)
        scatter(run.tomb, np.frombuffer(data["tomb"], np.uint8)
                .astype(bool))
        scatter(run.live, np.frombuffer(data["live"], np.uint8)
                .astype(bool))
        run.valid.reshape(-1)[dst] = True
        gfirst = np.cumsum(sizes) - sizes
        gs = np.zeros(n, dtype=bool)
        gs[gfirst] = True
        scatter(run.group_start, gs)
        kw = np.frombuffer(data["keywords"], ">u4").reshape(
            n, KEY_WORDS).astype(np.uint32)
        scatter(run.key_planes, P.u32_to_plane(kw))
        keys_arr = np.empty(len(keys_list), dtype=object)
        keys_arr[:] = keys_list
        scatter(run.row_keys, np.repeat(keys_arr, sizes))
        vers_arr = np.empty(n, dtype=object)
        vers_arr[:] = data["versions"]
        scatter(run.row_versions, vers_arr)

        for cid, entry in data["cols"].items():
            col = run.cols.get(cid)
            if col is None:
                continue
            rows = np.frombuffer(entry["rows"], np.int32)
            if rows.size == 0:
                continue
            gdst = dst[rows]
            col.set_.reshape(-1)[gdst] = True
            nulls = np.frombuffer(entry["nulls"], np.int32)
            if nulls.size:
                col.isnull.reshape(-1)[dst[nulls]] = True
            nn = rows if not nulls.size else np.setdiff1d(
                rows, nulls, assume_unique=True)
            ndst = dst[nn] if nulls.size else gdst
            kind = entry["kind"]
            cmp_flat = col.cmp_planes.reshape(
                -1, col.cmp_planes.shape[-1])
            if kind == 0:
                arr = np.frombuffer(entry["ivals"], np.int64)
                if cmp_flat.shape[-1] == 2:
                    chi, clo = P.i64_to_ordered_planes(arr)
                    cmp_flat[ndst, 0] = chi
                    cmp_flat[ndst, 1] = clo
                else:
                    cmp_flat[ndst, 0] = arr.astype(np.int32)
                if col.arith is not None:
                    col.arith.reshape(-1)[ndst] = arr.astype(np.float32)
            elif kind in (1, 2):
                arr = np.frombuffer(entry["dvals"], np.float64)
                if kind == 2:
                    f32 = arr.astype(np.float32)
                    cmp_flat[ndst, 0] = f32.view(np.int32)
                    col.arith.reshape(-1)[ndst] = f32
                else:
                    chi, clo = P.f64_to_ordered_planes(arr)
                    cmp_flat[ndst, 0] = chi
                    cmp_flat[ndst, 1] = clo
                    col.arith.reshape(-1)[ndst] = arr.astype(np.float32)
            else:  # varlen: prefixes from C; containers re-prefixed here
                pre = np.frombuffer(entry["prefix"], np.uint64).copy()
                pyvals = entry["pyvals"]
                maxlen = entry["maxlen"]
                for fix_row in entry["pyfix"]:
                    pos = int(np.searchsorted(nn, fix_row))
                    raw = _varlen_raw(pyvals[pos])
                    pre[pos] = int.from_bytes(
                        raw[:8].ljust(8, b"\x00"), "big")
                    maxlen = max(maxlen, len(raw))
                phi = P.u32_to_plane(
                    (pre >> np.uint64(32)).astype(np.uint32))
                plo = P.u32_to_plane(
                    (pre & np.uint64(0xFFFFFFFF)).astype(np.uint32))
                cmp_flat[ndst, 0] = phi
                cmp_flat[ndst, 1] = plo
                if maxlen > run.varlen_max_len.get(cid, 0):
                    run.varlen_max_len[cid] = maxlen
                bpos = (ndst // R).astype(np.int64)
                rpos = (ndst % R).astype(np.int64)
                vl = col.varlen
                for i in range(len(pyvals)):
                    vl[bpos[i]][rpos[i]] = pyvals[i]

        for b in range(ranges.shape[0]):
            g0, gn, nrows = (int(ranges[b, 0]), int(ranges[b, 1]),
                             int(ranges[b, 2]))
            run.blocks[b] = BlockMeta(keys_list[g0],
                                      keys_list[g0 + gn - 1], nrows)
        run.min_key = keys_list[0]
        run.max_key = keys_list[-1]
        return run

    @staticmethod
    def pack_group_ranges(sizes: list[int], R: int):
        """Greedy packing of whole key groups into R-row blocks:
        [(first_group_index, group_count, row_count)] per block. The ONE
        packing implementation — build() and device compaction share it,
        so their block layouts always agree."""
        ranges = []
        g0, gn, fill = 0, 0, 0
        for gi, n in enumerate(sizes):
            if n > R:
                raise ValueError(
                    f"key has {n} versions > rows_per_block={R}; "
                    "GC history (compact with a cutoff) to shrink it")
            if fill + n > R and fill > 0:
                ranges.append((g0, gn, fill))
                g0, gn, fill = gi, 0, 0
            gn += 1
            fill += n
        if fill > 0 or not ranges:
            if gn > 0:
                ranges.append((g0, gn, fill))
        return ranges

    def _alloc(self, B: int) -> None:
        R = self.R
        self.key_planes = np.zeros((B, R, KEY_WORDS), dtype=np.int32)
        self.ht_hi = np.zeros((B, R), dtype=np.int32)
        self.ht_lo = np.zeros((B, R), dtype=np.int32)
        maxhi, maxlo = P.scalar_ht_planes(MAX_HT)
        self.exp_hi = np.full((B, R), maxhi, dtype=np.int32)
        self.exp_lo = np.full((B, R), maxlo, dtype=np.int32)
        self.tomb = np.zeros((B, R), dtype=bool)
        self.live = np.zeros((B, R), dtype=bool)
        self.valid = np.zeros((B, R), dtype=bool)
        # Padding rows are each their own group so they never join a real one.
        self.group_start = np.ones((B, R), dtype=bool)
        for c in self.schema.value_columns:
            P_cmp = 2 if c.dtype.device_planes == 2 else 1
            self.cols[c.col_id] = ColumnData(
                dtype=c.dtype,
                set_=np.zeros((B, R), dtype=bool),
                isnull=np.zeros((B, R), dtype=bool),
                cmp_planes=np.zeros((B, R, P_cmp), dtype=np.int32),
                arith=(np.zeros((B, R), dtype=np.float32)
                       if c.dtype.is_numeric else None),
                varlen=([[None] * R for _ in range(B)]
                        if not c.dtype.is_fixed_width else None),
            )
        # Object NDARRAYS (not lists): compaction slices whole blocks of
        # row payloads as views instead of per-row pointer copies.
        self.row_keys = np.empty((B, R), dtype=object)
        self.row_keys[:] = b""
        self.row_versions = np.empty((B, R), dtype=object)
        self.row_key_vals = np.empty((B, R), dtype=object)
        self.blocks = [BlockMeta(b"", b"", 0) for _ in range(B)]

    def _fill_block(self, b: int, group_list) -> None:
        """Encode one block's rows. One cheap Python pass collects parallel
        per-plane lists; every plane then encodes with a single vectorized
        numpy call (the per-row scalar encode was the write-path
        bottleneck: ~15 tiny numpy ops per version)."""
        keys_flat: list[bytes] = []
        vers_flat: list[RowVersion] = []
        gs: list[bool] = []
        hts: list[int] = []
        tombs: list[bool] = []
        lives: list[bool] = []
        exp_idx: list[int] = []
        exp_hts: list[int] = []
        col_rows: dict[int, list[int]] = {cid: [] for cid in self.cols}
        col_vals: dict[int, list] = {cid: [] for cid in self.cols}
        r = 0
        for key, versions in group_list:
            first = True
            for v in versions:
                gs.append(first)
                first = False
                keys_flat.append(key)
                vers_flat.append(v)
                hts.append(v.ht)
                tombs.append(v.tombstone)
                lives.append(v.liveness)
                if v.has_ttl:
                    exp_idx.append(r)
                    exp_hts.append(v.expire_ht)
                for cid, val in v.columns.items():
                    if cid in col_rows:  # dropped columns: id retired
                        col_rows[cid].append(r)
                        col_vals[cid].append(val)
                r += 1
        n = r
        self.blocks[b] = BlockMeta(
            group_list[0][0] if group_list else b"",
            group_list[-1][0] if group_list else b"",
            n,
        )
        if n == 0:
            return
        self.valid[b, :n] = True
        self.group_start[b, :n] = gs
        self.tomb[b, :n] = tombs
        self.live[b, :n] = lives
        self.row_keys[b][:n] = keys_flat
        self.row_versions[b][:n] = vers_flat
        ht_arr = np.array(hts, dtype=np.int64)
        hi, lo = P.ht_to_planes(ht_arr)
        self.ht_hi[b, :n] = hi
        self.ht_lo[b, :n] = lo
        self.max_ht = max(self.max_ht, int(ht_arr.max()))
        if exp_idx:
            ehi, elo = P.ht_to_planes(np.array(exp_hts, dtype=np.int64))
            self.exp_hi[b, exp_idx] = ehi
            self.exp_lo[b, exp_idx] = elo
        kp = P.key_prefix_planes(keys_flat, KEY_WORDS)
        self.key_planes[b, :n] = kp
        for cid in self.cols:
            if col_rows[cid]:
                self._fill_column(b, cid, col_rows[cid], col_vals[cid])

    def _fill_column(self, b: int, cid: int, rows: list[int],
                     vals: list) -> None:
        """Vectorized encode of one column's set values within a block."""
        col = self.cols[cid]
        col.set_[b, rows] = True
        nn_rows = rows
        nn_vals = vals
        if any(v is None for v in vals):
            null_rows = [r for r, v in zip(rows, vals) if v is None]
            col.isnull[b, null_rows] = True
            nn_rows = [r for r, v in zip(rows, vals) if v is not None]
            nn_vals = [v for v in vals if v is not None]
            if not nn_rows:
                return
        dt = col.dtype
        if dt.is_integer or dt == DataType.BOOL:
            if dt == DataType.BOOL:
                arr = np.array([int(bool(v)) for v in nn_vals],
                               dtype=np.int64)
            else:
                arr = np.array(nn_vals, dtype=np.int64)
            if col.cmp_planes.shape[-1] == 2:
                hi, lo = P.i64_to_ordered_planes(arr)
                col.cmp_planes[b, nn_rows, 0] = hi
                col.cmp_planes[b, nn_rows, 1] = lo
            else:
                col.cmp_planes[b, nn_rows, 0] = arr
            if col.arith is not None:  # BOOL: orderable but not numeric
                col.arith[b, nn_rows] = arr.astype(np.float32)
        elif dt == DataType.FLOAT:
            arr = np.array(nn_vals, dtype=np.float32)
            col.cmp_planes[b, nn_rows, 0] = arr.view(np.int32)
            col.arith[b, nn_rows] = arr
        elif dt == DataType.DOUBLE:
            arr = np.array(nn_vals, dtype=np.float64)
            hi, lo = P.f64_to_ordered_planes(arr)
            col.cmp_planes[b, nn_rows, 0] = hi
            col.cmp_planes[b, nn_rows, 1] = lo
            col.arith[b, nn_rows] = arr.astype(np.float32)
        else:  # STRING / BINARY / opaque (collections, jsonb)
            raws = [_varlen_raw(v) for v in nn_vals]
            hi, lo = P.varlen_prefix_planes(raws)
            col.cmp_planes[b, nn_rows, 0] = hi
            col.cmp_planes[b, nn_rows, 1] = lo
            vl = col.varlen[b]
            for r, v in zip(nn_rows, nn_vals):
                vl[r] = v
            longest = max(map(len, raws))
            if longest > self.varlen_max_len.get(cid, 0):
                self.varlen_max_len[cid] = longest

    # -- compressed device planes (ops.encodings) ---------------------------
    def encoded_arrays(self):
        """The compressed device plane tree for this run, or None when
        --tpu_plane_encoding=off (or the run is empty): upload the plain
        planes instead. Encoded once per run and cached — demand
        re-uploads after eviction reuse the same compressed tree."""
        from yugabyte_db_tpu.utils.flags import FLAGS

        key = (FLAGS.get("tpu_plane_encoding"), len(self.cols))
        if self._enc_cache is not None and self._enc_cache[0] == key:
            return self._enc_cache[1]
        tree = None
        if key[0] != "off" and self.num_versions:
            tree = self._encode_planes()
        self._enc_cache = (key, tree)
        return tree

    def _encode_planes(self):
        """One cheap stats pass per plane picks its encoding; every
        fallback is per plane (a pathological column stays plain while
        its neighbours compress)."""
        from yugabyte_db_tpu.ops import encodings as enc

        tree = {
            "valid": enc.encode_bool_plane(self.valid),
            "group_start": enc.encode_bool_plane(self.group_start),
            "tomb": enc.encode_bool_plane(self.tomb),
            "live": enc.encode_bool_plane(self.live),
            "ht_hi": enc.encode_int_plane(self.ht_hi),
            "ht_lo": enc.encode_int_plane(self.ht_lo),
            "exp_hi": enc.encode_int_plane(self.exp_hi),
            "exp_lo": enc.encode_int_plane(self.exp_lo),
            "cols": {},
        }
        self.enc_dicts = {}
        for cid, col in self.cols.items():
            entry = {"set": enc.encode_bool_plane(col.set_),
                     "isnull": enc.encode_bool_plane(col.isnull)}
            cmp_leaf = None
            if col.dtype in (DataType.STRING, DataType.BINARY):
                cmp_leaf = self._encode_dict_col(cid, col)
            if cmp_leaf is None:
                cmp_leaf = enc.encode_int_plane(col.cmp_planes)
            entry["cmp"] = cmp_leaf
            if col.arith is not None and col.dtype in (
                    DataType.FLOAT, DataType.DOUBLE):
                # Float arith planes are the value itself and must
                # upload; every other numeric kind aggregates exactly
                # from the cmp planes on device, so its arith plane is
                # redundant there and is simply omitted from the tree.
                entry["arith"] = enc.encode_float_plane(col.arith)
            tree["cols"][cid] = entry
        self.enc_stats = enc.tree_stats(tree)
        return tree

    def _encode_dict_col(self, cid: int, col: ColumnData):
        """Per-run sorted dictionary for a string/binary column, or None
        (dict overflow / no set rows) — the caller falls back to the
        prefix-plane int encodings. The dictionary is the sorted unique
        FULL values, so codes order exactly as values do and the last
        (absent) slot decodes the zero prefix planes unset/NULL rows
        hold in the plain format."""
        from yugabyte_db_tpu.ops import encodings as enc

        if col.varlen is None:
            return None
        nn = col.set_ & ~col.isnull
        bi, ri = np.nonzero(nn)
        if bi.size == 0:
            return None
        raws = [_varlen_raw(col.varlen[b][r])
                for b, r in zip(bi.tolist(), ri.tolist())]
        uniq = sorted(set(raws))
        if len(uniq) > enc.DICT_MAX_VALUES:
            return None
        cap = enc.pow2_bucket(len(uniq) + 1)
        hi, lo = P.varlen_prefix_planes(uniq)
        dhi = np.zeros(cap, np.int32)
        dlo = np.zeros(cap, np.int32)
        dhi[:len(uniq)] = hi
        dlo[:len(uniq)] = lo
        code_of = {v: i for i, v in enumerate(uniq)}
        codes = np.full((self.B, self.R), cap - 1, np.int64)
        codes[bi, ri] = [code_of[v] for v in raws]
        self.enc_dicts[cid] = uniq
        return enc.dict_leaf(codes, dhi, dlo)

    # -- host-side access (compaction input, materialization) -------------
    def iter_entries(self):
        """Yield (key, versions ht-desc) in key order — compaction input."""
        for b in range(self.B):
            meta = self.blocks[b]
            r = 0
            while r < meta.num_valid:
                key = self.row_keys[b][r]
                versions = []
                while r < meta.num_valid and self.row_keys[b][r] == key:
                    versions.append(self.row_versions[b][r])
                    r += 1
                yield key, versions

    def group_versions(self, b: int, r: int) -> tuple[bytes, list[RowVersion]]:
        """The key group starting at (block b, row r) — r must be group_start."""
        key = self.row_keys[b][r]
        versions = []
        meta = self.blocks[b]
        while r < meta.num_valid and self.row_keys[b][r] == key:
            versions.append(self.row_versions[b][r])
            r += 1
        return key, versions

    # -- exact host-side key location (bounds, point lookups) --------------
    def lower_row(self, key: bytes) -> int:
        """Global row index (b*R + r) of the first valid row with
        row_key >= key. Exact on full key bytes — this is what turns scan
        bounds into device row-index bounds with no prefix-tie ambiguity."""
        import bisect as _bisect

        if self.B == 0 or not self.blocks[0].num_valid:
            return 0
        maxes = getattr(self, "_block_maxes", None)
        if maxes is None:
            # Runs are immutable once built; cache the per-block max-key
            # list (page scans bisect this on every request).
            maxes = self._block_maxes = [m.max_key for m in self.blocks
                                         if m.num_valid]
        b = _bisect.bisect_left(maxes, key)
        if b >= len(maxes):
            return self.total_rows()
        meta = self.blocks[b]
        r = _bisect.bisect_left(self.row_keys[b], key, 0, meta.num_valid)
        return b * self.R + r

    def upper_row(self, upper: bytes) -> int:
        """Global row index bound for exclusive upper (b'' = unbounded)."""
        if not upper:
            return self.total_rows()
        return self.lower_row(upper)

    def total_rows(self) -> int:
        return self.B * self.R

    def find_versions(self, key: bytes) -> list[RowVersion]:
        """All versions of key in this run (ht-desc), or []."""
        import bisect as _bisect

        row = self.lower_row(key)
        if row >= self.total_rows():
            return []
        b, r = divmod(row, self.R)
        if b >= self.B or r >= self.blocks[b].num_valid or \
                self.row_keys[b][r] != key:
            return []
        out = []
        meta = self.blocks[b]
        while r < meta.num_valid and self.row_keys[b][r] == key:
            out.append(self.row_versions[b][r])
            r += 1
        return out

    def key_at(self, global_row: int) -> bytes:
        b, r = divmod(global_row, self.R)
        return self.row_keys[b][r]

    def key_vals_at(self, global_row: int) -> list:
        """Decoded key-column values (hashed + range) of the row's key,
        memoized per row so repeated scans never re-decode."""
        from yugabyte_db_tpu.models.encoding import decode_doc_key

        b, r = divmod(global_row, self.R)
        kv = self.row_key_vals[b][r]
        if kv is None:
            _, hashed, ranges = decode_doc_key(self.row_keys[b][r])
            kv = self.row_key_vals[b][r] = hashed + ranges
        return kv

    def key_col_arrays(self, blocks=None) -> list[np.ndarray]:
        """One object ndarray per key column, indexed by global row index
        (b*R + r), holding the decoded key value. Decoded lazily PER
        BLOCK (``blocks``: iterable of block indices a scan touched;
        None = all) so a small page never pays an O(run) decode pass;
        batched scans then materialize key columns with one numpy
        fancy-index instead of per-row Python."""
        from yugabyte_db_tpu.models.encoding import decode_doc_key

        nk = len(self.schema.key_columns)
        if self.kv_ready:  # lock-free fast path once fully decoded
            return self._kv_cols
        with self._kv_lock:
            if self._kv_cols is None:
                self._kv_cols = [np.empty(self.B * self.R, dtype=object)
                                 for _ in range(nk)]
            cols = self._kv_cols
            todo = range(self.B) if blocks is None else blocks
            for b in todo:
                if b in self._kv_blocks_done or b >= self.B:
                    continue
                n = self.blocks[b].num_valid
                rk = self.row_keys[b]
                kvs = self.row_key_vals[b]
                base = b * self.R
                for r in range(n):
                    kv = kvs[r]
                    if kv is None:
                        _, hashed, ranges = decode_doc_key(rk[r])
                        kv = kvs[r] = hashed + ranges
                    for p in range(nk):
                        cols[p][base + r] = kv[p]
                # marked done only after the block is fully decoded, so a
                # concurrent reader can never see half-filled rows
                self._kv_blocks_done.add(b)
            if len(self._kv_blocks_done) == self.B:
                self.kv_ready = True
        return cols

    # -- run pruning (hashed-prefix bloom) ----------------------------------
    @property
    def bloom_ready(self) -> bool:
        """True once the lazy hash bloom exists (callers use this to
        avoid paying the build for workloads where a binary search on
        one or two runs is already cheap)."""
        return self._hash_bloom is not None

    def may_contain_hashed(self, prefix: bytes) -> bool:
        """Can this run contain any key with the given hashed-components
        prefix? False lets point gets / single-key scans skip the run
        entirely (reference: DocDbAwareFilterPolicy,
        src/yb/docdb/doc_key.h:551-575). Never a false negative."""
        bl = self._hash_bloom
        if bl is None:
            bl = self._build_hash_bloom()
        if bl is True:
            return True
        return bl.may_contain(prefix)

    def _build_hash_bloom(self):
        from yugabyte_db_tpu.models.encoding import hashed_prefix
        from yugabyte_db_tpu.storage.bloom import BloomFilter

        with self._kv_lock:
            if self._hash_bloom is not None:
                return self._hash_bloom
            bl = BloomFilter(self.num_versions or 1)
            prefixes: list[bytes] = []
            last = None
            for b in range(self.B):
                n = self.blocks[b].num_valid
                rk = self.row_keys[b]
                for r in range(n):
                    key = rk[r]
                    hp = hashed_prefix(key)
                    if not hp:
                        self._hash_bloom = True  # filter inapplicable
                        return True
                    if hp != last:
                        prefixes.append(hp)
                        last = hp
            bl.add_many(prefixes)
            self._hash_bloom = bl
            return bl

    # -- block pruning -----------------------------------------------------
    def block_range(self, lower: bytes, upper: bytes) -> tuple[int, int]:
        """[b0, b1) of blocks that may contain keys in [lower, upper)."""
        if self.B == 0 or not self.blocks[0].num_valid:
            return 0, 0
        b0 = 0
        while b0 < self.B and self.blocks[b0].num_valid and \
                self.blocks[b0].max_key < lower:
            b0 += 1
        b1 = self.B
        if upper:
            while b1 > b0 and (not self.blocks[b1 - 1].num_valid or
                               self.blocks[b1 - 1].min_key >= upper):
                b1 -= 1
        while b1 > b0 and not self.blocks[b1 - 1].num_valid:
            b1 -= 1
        return b0, b1
