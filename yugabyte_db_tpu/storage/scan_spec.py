"""Scan specification and results: what the query layer pushes down.

Reference analog: src/yb/common/ql_scanspec.h (QLScanRange/QLScanSpec — the
key-range bounds), the condition PBs of ql_protocol.proto evaluated by
QLExprExecutor (src/yb/common/ql_expr.h:210), and aggregate pushdown
(PgsqlReadOperation::EvalAggregate, src/yb/docdb/pgsql_operation.cc:473).
Paging mirrors QLPagingStatePB: a scan resumes from an encoded key.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from yugabyte_db_tpu.storage.row_version import MAX_HT

# Predicate operators the engines evaluate. NULL semantics are SQL-ish:
# a comparison with NULL is false (rows with null operands never match).
OPS = ("=", "!=", "<", "<=", ">", ">=", "IN")

AGG_FNS = ("count", "sum", "min", "max", "avg")


@dataclass(frozen=True)
class Predicate:
    column: str
    op: str
    value: object  # literal; for IN, a tuple of literals

    def __post_init__(self):
        if self.op not in OPS:
            raise ValueError(f"bad predicate op {self.op!r}")

    def matches(self, v) -> bool:
        if v is None:
            return False
        if self.op == "=":
            return v == self.value
        if self.op == "!=":
            return v != self.value
        if self.op == "<":
            return v < self.value
        if self.op == "<=":
            return v <= self.value
        if self.op == ">":
            return v > self.value
        if self.op == ">=":
            return v >= self.value
        if self.op == "IN":
            return v in self.value
        raise AssertionError(self.op)


@dataclass(frozen=True)
class AggSpec:
    fn: str          # count | sum | min | max | avg
    column: str | None  # None for count(*) / expression aggregates
    # Optional pushed-down scalar expression (storage.expr tree) the
    # aggregate runs over instead of a bare column — the TPC-H
    # sum(l_extendedprice * (1 - l_discount)) shape
    # (reference: PgsqlExpressionPB trees, pgsql_operation.cc:473).
    expr: object = None
    label: str | None = None  # output column label override

    def __post_init__(self):
        if self.fn not in AGG_FNS:
            raise ValueError(f"bad aggregate {self.fn!r}")
        if self.fn != "count" and self.column is None and self.expr is None:
            raise ValueError(f"{self.fn} needs a column or expression")

    @property
    def output_name(self) -> str:
        if self.label:
            return self.label
        return f"{self.fn}({self.column or ('<expr>' if self.expr else '*')})"


@dataclass
class ScanSpec:
    """A bounded MVCC scan request against one tablet's storage."""

    lower: bytes = b""          # inclusive encoded-key lower bound
    upper: bytes = b""          # exclusive encoded-key upper bound; b"" = unbounded
    read_ht: int = MAX_HT       # MVCC read point (HybridTime.value)
    predicates: list[Predicate] = field(default_factory=list)
    projection: list[str] | None = None   # column names; None = all columns
    limit: int | None = None              # max rows returned (page size)
    aggregates: list[AggSpec] | None = None
    group_by: list[str] | None = None     # grouping columns (with aggregates)

    def in_range(self, key: bytes) -> bool:
        if key < self.lower:
            return False
        return not self.upper or key < self.upper

    @property
    def is_aggregate(self) -> bool:
        return bool(self.aggregates)


@dataclass
class ScanResult:
    columns: list[str]            # names, in output order
    rows: list[tuple]             # materialized rows (or aggregate row(s))
    resume_key: bytes | None = None  # exclusive "scan resumes at" key, None = done
    # Observability: existing rows the engine examined. A work statistic,
    # not a contract — the device engine resolves whole block windows, so
    # a LIMIT page may report more rows examined than a row-at-a-time
    # engine that stops exactly at the limit. Unlimited tombstone-free
    # scans agree across engines (pinned by tests/test_gather.py).
    rows_scanned: int = 0


def point_key_of(spec: ScanSpec, schema=None) -> bytes | None:
    """The single doc key an exact-key-range spec can contain, or None
    when the spec is not a point read. Shapes: [key, key+0xff) (the
    processor's exact-key convention — lower is always a FULL doc key
    there) and, given the schema, [key, prefix_successor(key)) where
    lower binds every hash AND range component (the client GET / CQL
    full-PK shapes; the prefix spelling gets its terminator appended).
    The schema check matters: a hash-prefix scan (WHERE on the hash
    columns only) also has upper == prefix_successor(lower) but spans
    many keys."""
    if not spec.lower or not spec.upper or spec.is_aggregate or \
            spec.group_by:
        return None
    if spec.upper == spec.lower + b"\xff":
        return spec.lower
    if schema is None:
        return None
    from yugabyte_db_tpu.models.encoding import (full_doc_key_of,
                                                 prefix_successor)

    if spec.upper != prefix_successor(spec.lower):
        return None
    return full_doc_key_of(spec.lower, len(schema.hash_columns),
                           len(schema.range_columns))
