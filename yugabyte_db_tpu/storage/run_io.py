"""Sorted-run persistence: save/load runs as codec files.

Reference analog: SSTable files on disk (block_based_table_builder.cc) +
MANIFEST tracking. Both engines persist the same logical content (key ->
MVCC versions); the TPU engine rebuilds its columnar planes from it at load
time. Columnar plane snapshots (zero-rebuild load) come later; this format
is the durable source of truth either way.

File format: codec.encode of
  ["run1", [ [key, [ [ht, tombstone, liveness, {col: val}, expire_ht], ...ht-desc ], ...key-asc ] ]
"""

from __future__ import annotations

import os

from yugabyte_db_tpu.utils import codec
from yugabyte_db_tpu.storage.row_version import RowVersion

_MAGIC = "run1"


def save_run(path: str, entries: list[tuple[bytes, list[RowVersion]]]) -> None:
    payload = [
        [key, [[v.ht, v.tombstone, v.liveness,
                {str(c): val for c, val in v.columns.items()}, v.expire_ht,
                v.write_id]
               for v in versions]]
        for key, versions in entries
    ]
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(codec.encode([_MAGIC, payload]))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class RunPersistence:
    """Tracks a directory of numbered run files for one engine instance.
    ``None`` data_dir = in-memory engine (tests, caches)."""

    def __init__(self, data_dir: str | None):
        self.data_dir = data_dir
        self._seq = 0
        self.files: list[str] = []
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            names = sorted(n for n in os.listdir(data_dir)
                           if n.startswith("run-") and n.endswith(".dat"))
            self.files = [os.path.join(data_dir, n) for n in names]
            if names:
                self._seq = max(int(n[4:-4]) for n in names) + 1

    @property
    def enabled(self) -> bool:
        return self.data_dir is not None

    def load_all(self):
        return [load_run(p) for p in self.files]

    def save_new(self, entries) -> None:
        if not self.enabled:
            return
        path = os.path.join(self.data_dir, f"run-{self._seq:010d}.dat")
        self._seq += 1
        save_run(path, entries)
        self.files.append(path)

    def replace_all(self, entries) -> None:
        """Atomically-ish swap every run file for one merged run (compaction).
        New file is durable before old ones are unlinked, so a crash leaves
        either the old set or a superset — load_all after a crash between
        steps would see duplicated data, which the version-merge semantics
        absorb (identical versions merge idempotently)."""
        if not self.enabled:
            return
        old = list(self.files)
        self.files = []
        if entries:
            self.save_new(entries)
        for p in old:
            try:
                os.unlink(p)
            except FileNotFoundError:
                pass


def load_run(path: str) -> list[tuple[bytes, list[RowVersion]]]:
    with open(path, "rb") as f:
        magic, payload = codec.decode(f.read())
    if magic != _MAGIC:
        raise ValueError(f"{path}: bad run file magic {magic!r}")
    out = []
    for key, versions in payload:
        out.append((key, [
            RowVersion(key, ht=rec[0], tombstone=rec[1], liveness=rec[2],
                       columns={int(c): val for c, val in rec[3].items()},
                       expire_ht=rec[4],
                       write_id=rec[5] if len(rec) > 5 else 0)
            for rec in versions
        ]))
    return out
