"""Row blocks: the write batch as one contiguous binary buffer.

Reference analog: the reference's write path never materializes per-row
language objects — rows live in protobuf arenas (QLWriteRequestPB) and
rocksdb WriteBatch slices end to end (src/yb/tablet/preparer.cc,
src/yb/docdb/doc_write_batch.h). A row block is this framework's
equivalent: the client encodes a batch ONCE (doc keys, partition hash,
per-tablet split), the block travels opaque through the RPC payload, the
WAL entry body, and Raft replication, is stamped with the commit hybrid
time by one native pass on the leader, and lands in the C++ memtable on
every replica.

This module is the pure-Python SPEC of the block layout, used as the
fallback when the native module (native/writeplane.cc -> yb_wp) is
unavailable and as the parity oracle in tests. Layout (little-endian):

    u32 nrows, then per row:
      u16 key_len, key bytes        (byte-comparable DocKey)
      u64 ht                        (commit hybrid time; 0 until stamped)
      u64 expire_ht                 (TTL expiry; MAX_HT = none)
      i64 ttl_us                    (-1 = none; resolved at stamping)
      u32 write_id                  (intra-batch MVCC order)
      u8  flags                     (1 = tombstone, 2 = liveness)
      u16 ncols, then per column: u32 col_id, codec-tagged value
"""

from __future__ import annotations

import struct

from yugabyte_db_tpu.storage.row_version import MAX_HT, RowVersion
from yugabyte_db_tpu.utils import codec as _codec
from yugabyte_db_tpu.utils.hybrid_time import BITS_FOR_LOGICAL

try:
    from yugabyte_db_tpu.native import yb_wp as _native
except Exception:  # noqa: BLE001 — pure-Python fallback
    _native = None

HAVE_NATIVE = _native is not None

_NROWS = struct.Struct("<I")
_KEYLEN = struct.Struct("<H")
_FIXED = struct.Struct("<QQqIBH")  # ht, expire_ht, ttl_us, write_id, flags, ncols
_COLID = struct.Struct("<I")


# -- pure-Python spec ---------------------------------------------------------

def _py_encode_rows(rows: list[RowVersion]) -> bytes:
    out = bytearray(_NROWS.pack(len(rows)))
    for r in rows:
        if r.increments:
            raise ValueError("encode_rows: unresolved counter increments")
        out += _KEYLEN.pack(len(r.key))
        out += r.key
        out += _FIXED.pack(r.ht, r.expire_ht,
                           -1 if r.ttl_us is None else r.ttl_us,
                           r.write_id,
                           (1 if r.tombstone else 0) | (2 if r.liveness else 0),
                           len(r.columns))
        for col_id, v in r.columns.items():
            out += _COLID.pack(col_id)
            out += _codec.encode(v)
    return bytes(out)


def _py_iter_records(block) -> list[tuple]:
    """-> [(key, ht, tombstone, liveness, columns, expire_ht, ttl_us,
    write_id)] — RowVersion's positional field order."""
    buf = bytes(block)
    (nrows,) = _NROWS.unpack_from(buf, 0)
    pos = _NROWS.size
    out = []
    for _ in range(nrows):
        (klen,) = _KEYLEN.unpack_from(buf, pos)
        pos += _KEYLEN.size
        key = buf[pos:pos + klen]
        pos += klen
        ht, expire_ht, ttl_us, write_id, flags, ncols = _FIXED.unpack_from(
            buf, pos)
        pos += _FIXED.size
        columns = {}
        for _c in range(ncols):
            (col_id,) = _COLID.unpack_from(buf, pos)
            pos += _COLID.size
            v, pos = _codec._decode_from(buf, pos)
            columns[col_id] = v
        out.append((key, ht, bool(flags & 1), bool(flags & 2), columns,
                    expire_ht, None if ttl_us < 0 else ttl_us, write_id))
    if pos != len(buf):
        raise ValueError("row block: trailing bytes")
    return out


def _py_block_count(block) -> int:
    (nrows,) = _NROWS.unpack_from(bytes(block), 0)
    return nrows


def _py_block_keys(block) -> list[bytes]:
    return [t[0] for t in _py_iter_records(block)]


def _py_stamp_block(block, ht: int, shift: int = BITS_FOR_LOGICAL) -> bytes:
    rows = [RowVersion(t[0], ht=ht, tombstone=t[2], liveness=t[3],
                       columns=t[4],
                       expire_ht=(ht + (t[6] << shift)) if t[6] is not None
                       else t[5],
                       write_id=i)
            for i, t in enumerate(_py_iter_records(block))]
    return _py_encode_rows(rows)


def _py_block_ht_range(block):
    hts = [t[1] for t in _py_iter_records(block)]
    return (min(hts), max(hts)) if hts else None


# -- dispatch -----------------------------------------------------------------

if HAVE_NATIVE:
    def encode_rows(rows: list[RowVersion]) -> bytes:
        return _native.encode_rows(rows)

    def block_records(block) -> list[tuple]:
        return _native.block_rows(block)

    def block_count(block) -> int:
        return _native.block_count(block)

    def block_keys(block) -> list[bytes]:
        return _native.block_keys(block)

    def stamp_block(block, ht: int, shift: int = BITS_FOR_LOGICAL) -> bytes:
        return _native.stamp_block(block, ht, shift)

    def block_ht_range(block):
        return _native.block_ht_range(block)
else:
    encode_rows = _py_encode_rows
    block_records = _py_iter_records
    block_count = _py_block_count
    block_keys = _py_block_keys
    stamp_block = _py_stamp_block
    block_ht_range = _py_block_ht_range


def rows_from_block(block) -> list[RowVersion]:
    """Materialize a block into RowVersions (fallback/read paths only —
    the hot pipeline never calls this)."""
    return [RowVersion(*t) for t in block_records(block)]
