"""RowVersion: one MVCC version of one row — the storage write record.

Reference analog: in DocDB a logical row version is *shredded* into one
RocksDB KV per column (SubDocKey = DocKey + column_id + DocHybridTime,
src/yb/docdb/doc_key.h) plus a liveness system column written by INSERT.
The columnar TPU layout wants whole-row versions instead: one record per
(DocKey, commit hybrid time) carrying the set of columns that write touched.
The semantics are identical:

- INSERT  -> liveness=True, all provided columns set
- UPDATE  -> liveness=False, only the SET columns present
- DELETE  -> tombstone=True (row tombstone)
- SET col=NULL -> column present with value None (column tombstone)
- TTL     -> expire_ht precomputed at write time; an expired value reads as
  a tombstone at its own hybrid time (shadowing older versions), matching
  DocDBCompactionFilter/GetSubDocument expiry semantics
  (src/yb/docdb/docdb_compaction_filter.cc).
"""

from __future__ import annotations

from dataclasses import dataclass, field

MAX_HT = (1 << 63) - 1


@dataclass
class RowVersion:
    key: bytes                 # encoded DocKey
    ht: int                    # commit hybrid time (HybridTime.value)
    tombstone: bool = False    # row delete marker
    liveness: bool = False     # INSERT liveness marker
    columns: dict = field(default_factory=dict)  # col_id -> value (None = null)
    expire_ht: int = MAX_HT    # TTL expiry as a hybrid time; MAX_HT = no TTL
    # RELATIVE TTL in microseconds: resolved into expire_ht against the
    # write's own stamped hybrid time by the leader (tablet clocks can
    # legitimately run ahead of wall time, so clients must not compute
    # absolute expiry from their wall clock — the reference stores TTLs
    # relative to the value's write time for the same reason).
    ttl_us: int | None = None
    # Sub-hybrid-time ordering of writes within ONE batch (reference:
    # DocHybridTime's write_id component, src/yb/common/doc_hybrid_time.h):
    # every row in a batch shares the batch's hybrid time; write_id is the
    # row's position, so two writes to the SAME key in one batch order by
    # (ht, write_id). A row tombstone at ht T still shadows ALL versions
    # with ht <= T (the same-batch DELETE rule the device kernel applies).
    write_id: int = 0
    # Pending counter deltas (col_id -> signed int). NEVER stored: the
    # tablet LEADER resolves them into absolute column values under its
    # write lock before stamping/appending, so concurrent increments
    # serialize (reference: counter column read-modify-write inside the
    # tablet, cql_operation.cc). Only the client->leader RPC carries them.
    increments: dict = field(default_factory=dict)

    def __post_init__(self):
        if self.tombstone and (self.liveness or self.columns
                               or self.increments):
            raise ValueError("tombstone carries no columns or liveness")

    def resolve_ttl(self, ht: int) -> int:
        """Absolute expire_ht for a write stamped at ``ht``."""
        if self.ttl_us is not None:
            from yugabyte_db_tpu.utils.hybrid_time import BITS_FOR_LOGICAL

            return ht + (self.ttl_us << BITS_FOR_LOGICAL)
        return self.expire_ht

    @property
    def has_ttl(self) -> bool:
        return self.expire_ht != MAX_HT
