"""Device/host residency manager: the HBM block-cache analog.

Reference analog: src/yb/rocksdb/util/cache.cc — the LRU block cache
with a high-pri/low-pri pool split (sized and wired for docdb in
docdb_rocksdb_util.cc) that lets SSTable working sets exceed RAM.  Here
the cached unit is a whole columnar run's device plane group: the
host-side ``ColumnarRun`` stays authoritative, ``TpuRun`` demand-uploads
its ``DeviceRun`` through this cache on first access, and when a
device's budget (``--tpu_hbm_budget_bytes``, PER DEVICE — each chip has
its own HBM) is exceeded the least recently used unpinned plane group
*on that device* is dropped, releasing its device buffers and debiting
the owning engine's ``device`` MemTracker subtree so /memz and /metrics
show true residency.

The budget is a per-device map, not one process-wide pool: every entry
belongs to exactly one owning device (demand re-uploads go back to it),
except sharded mesh stacks, whose external registration carries a
per-device byte map — one shard's bytes charged to the chip actually
holding it.  Admission and eviction are scoped to the admitting
device, so a hot working set on chip 0 never evicts chip 3's shards.
On a single-device host the map has one bucket and behavior is
byte-identical to the old process-wide budget.

Scan resistance mirrors the reference's two-pool policy: point-get and
bounded-scan traffic is admitted to (or promoted into) the protected
high-pri pool; full-table-scan traffic is admitted to the low-pri pool,
so one large scan streams through the low pool and cannot flush the hot
working set.  A configurable fraction of the budget
(``HIGH_PRI_POOL_RATIO``) caps the high pool; overflow demotes its LRU
entries into the low pool, exactly like the reference's high-pri pointer
walk.

Pins keep a plane group resident across a dispatch window (issue→finish
in ``scan_batch_async``, compaction's ``resident_gc_mask``, the cached
delta-overlay primary, the sharded mesh arrays).  Pinned entries are
never evicted — a pinned set larger than the budget overflows it
(non-strict capacity, as in the reference's pinned-usage accounting)
rather than failing the dispatch.

This module deliberately imports no device framework: payloads are built
by caller-supplied closures, so /memz handlers and tests can import it
without touching jax.
"""

from __future__ import annotations

import threading
import weakref
from collections import OrderedDict, deque

from yugabyte_db_tpu.utils.flags import FLAGS
from yugabyte_db_tpu.utils.locking import guarded_by
from yugabyte_db_tpu.utils.memtracker import root_tracker
from yugabyte_db_tpu.utils.metrics import hbm_cache_entity, hbm_device_entity
from yugabyte_db_tpu.utils.sync_point import sync_point

# Fraction of the budget reserved for the protected (high-pri) pool.
HIGH_PRI_POOL_RATIO = 0.8

# Device bucket for callers that never name a device (single-chip hosts,
# tests driving the cache directly).  Callers on a real mesh pass
# "<platform>:<id>" strings (parallel.meshcompat.device_label).
DEFAULT_DEVICE = "device:0"

# Sentinel payload for externally-owned residency (bytes uploaded outside
# the cache but accounted through it, e.g. the sharded mesh arrays).
_EXTERNAL = object()


def _pin_witness():
    """The resource witness when enabled, else None — every pin-count
    transition below reports through this (utils/resources.py)."""
    from yugabyte_db_tpu.utils import resources

    w = resources.witness()
    return w if w.enabled else None


class _Entry:
    __slots__ = ("key", "label", "tracker", "owner_ref", "payload",
                 "nbytes", "aux", "aux_bytes", "pins", "pool", "external",
                 "encoding", "device", "dev_bytes")

    def __init__(self, key: int, label: str, tracker,
                 device: str = DEFAULT_DEVICE):
        self.key = key
        self.label = label
        self.tracker = tracker
        self.owner_ref = None
        self.payload = None
        self.nbytes = 0
        self.aux: dict = {}
        self.aux_bytes = 0
        self.pins = 0
        self.pool = "high"
        self.external = False
        # Plane-format tag of the resident payload ("plain", "encoded",
        # "external"); sampled duck-typed from the payload at admit so
        # /memz can show which runs hold compressed bytes in HBM.
        self.encoding = "plain"
        # The owning device: demand re-uploads target it, and its budget
        # bucket is the one this entry's bytes count against.
        self.device = device
        # External mesh stacks only: per-device byte map (one shard's
        # bytes on the chip holding it).  None for single-device entries.
        self.dev_bytes: dict | None = None

    @property
    def total_bytes(self) -> int:
        return self.nbytes + self.aux_bytes


# _dead is deliberately NOT declared: the weakref death callback
# appends to it lock-free (atomic deque), _drain_dead consumes under
# _lock — see register().
@guarded_by("_lock", "_entries", "_pools", "_next_key", "_resident",
            "_peak_resident", "_dev_resident")
class HbmCache:
    """Process-wide capacity-budgeted cache of device plane groups.

    Keys are integer tokens handed out by :meth:`register`; each token is
    tied to its owner by a weakref, so a dropped run releases its device
    bytes without the cache pinning the host run alive.  ``acquire`` is
    the one read path: hit → LRU touch (plus promotion into the
    protected pool when the access is ``priority="high"``), miss → evict
    down to budget, build the payload via the caller's closure (the
    demand re-upload), charge the owner's MemTracker, admit.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._entries: dict[int, _Entry] = {}
        # Keys whose owners were collected.  Weakref death callbacks run
        # at arbitrary allocation points — including re-entrantly on a
        # thread already inside the cache (the lock is an RLock) — so
        # they must not mutate _entries/_pools directly; they append
        # here (deque.append is atomic) and every public method drains
        # the queue under the lock before touching shared state.
        self._dead: deque[int] = deque()
        # Eviction order: oldest first.  "low" drains before "high".
        self._pools: dict[str, OrderedDict] = {"low": OrderedDict(),
                                               "high": OrderedDict()}
        self._next_key = 1
        self._resident = 0
        self._peak_resident = 0
        # Per-device residency + demand-upload accounting.  Buckets are
        # created on first charge; each gets its {device=...}-labeled
        # gauge/counter pair on the process registry.
        self._dev_resident: dict[str, int] = {}
        self._dev_upload: dict[str, object] = {}
        ent = hbm_cache_entity()
        self._m_hits = ent.counter("yb_hbm_cache_hits")
        self._m_misses = ent.counter("yb_hbm_cache_misses")
        self._m_evictions = ent.counter("yb_hbm_cache_evictions")
        self._m_upload = ent.counter("yb_hbm_demand_upload_bytes")
        ent.gauge("yb_hbm_resident_bytes", self.resident_bytes)
        ent.gauge("yb_hbm_pinned_bytes", self.pinned_bytes)
        ent.gauge("yb_hbm_budget_bytes", self.budget)

    # -- configuration --------------------------------------------------------

    @staticmethod
    def budget() -> int:
        """Current byte budget PER DEVICE; 0 means unbounded."""
        try:
            return int(FLAGS.get("tpu_hbm_budget_bytes"))
        except KeyError:
            return 0

    # -- registration ---------------------------------------------------------

    def register(self, owner, tracker=None, label: str = "",
                 device: str = DEFAULT_DEVICE) -> int:
        """A residency key for ``owner`` (a TpuRun or similar).  The
        entry auto-invalidates when ``owner`` is collected; ``tracker``
        (the engine's device MemTracker) is charged while resident.
        ``device`` names the owning chip's budget bucket — demand
        re-uploads for this key must target that device."""
        with self._lock:
            self._drain_dead()
            key = self._next_key
            self._next_key += 1
            e = _Entry(key, label or type(owner).__name__, tracker,
                       device=device or DEFAULT_DEVICE)
            if owner is not None:
                # Deliberate: the death callback only ENQUEUES into a
                # deque (append is atomic under the GIL); _drain_dead
                # consumes under _lock. This is the deferred-mutation
                # shape the rule prescribes, not the race it flags.
                e.owner_ref = weakref.ref(
                    owner,
                    # yb-lint: disable=iraces/callback-into-locked-state
                    lambda _r, k=key: self._dead.append(k))
            self._entries[key] = e
            return key

    def add_external(self, owner, nbytes: int, tracker=None,
                     label: str = "external",
                     device: str = DEFAULT_DEVICE,
                     dev_bytes: dict | None = None) -> int:
        """Account ``nbytes`` of device residency uploaded outside the
        cache (sharded mesh arrays, the overlay's masked valid plane).
        External entries are permanently pinned until invalidated (or
        their owner is collected); they overflow the budget rather than
        being evictable.  ``dev_bytes`` (device name -> bytes) charges a
        multi-device upload per shard — the sharded mesh stacks — and
        overrides ``nbytes``/``device`` when given."""
        key = self.register(owner, tracker, label, device=device)
        with self._lock:
            self._drain_dead()
            e = self._entries.get(key)
            if e is None:  # owner died during registration
                return key
            e.external = True
            e.payload = _EXTERNAL
            e.encoding = "external"
            if dev_bytes:
                e.dev_bytes = {d: int(n) for d, n in dev_bytes.items()}
                e.nbytes = sum(e.dev_bytes.values())
            else:
                e.nbytes = int(nbytes)
            e.pins = 1
            w = _pin_witness()
            if w is not None:
                w.pin_acquired(key, label=e.label, external=True)
            self._pools["high"][key] = e
            self._charge(e, e.nbytes)
        return key

    def invalidate(self, key: int) -> None:
        """Drop the entry entirely: release device bytes and forget the
        key.  Owner-teardown only — a later acquire() on this key takes
        the unmanaged fallback.  For owners that stay live (planes
        rebuilt in place), use :meth:`release` instead."""
        with self._lock:
            self._drain_dead()
            e = self._entries.pop(key, None)
            if e is not None and e.payload is not None:
                self._release_entry(e, evicted=False)

    def release(self, key: int) -> None:
        """Drop the entry's resident payload but keep the registration:
        the next acquire() demand-rebuilds through the cache, still
        budgeted and MemTracker-accounted.  The right call when the
        owner outlives its current upload (e.g. ALTER grows the host
        planes and the stale device copy must go)."""
        with self._lock:
            self._drain_dead()
            e = self._entries.get(key)
            if e is not None and e.payload is not None:
                self._release_entry(e, evicted=False)

    # -- the read path --------------------------------------------------------

    def acquire(self, key: int, build, nbytes_hint: int | None = None,
                priority: str | None = None, pin: bool = False):
        """The payload for ``key``, demand-built on miss.

        ``build`` returns ``(payload, nbytes)`` — it runs under the cache
        lock, serializing uploads (by design: concurrent uploads under
        memory pressure would overshoot the budget).  ``nbytes_hint``
        lets the cache evict *before* uploading so residency never
        transiently exceeds the budget.  ``priority`` is "high", "low",
        or None; None admits high but never promotes an existing low
        entry (so follow-up accesses inside a full scan don't defeat
        scan resistance).  ``pin=True`` takes a pin before returning.
        """
        with self._lock:
            self._drain_dead()
            e = self._entries.get(key)
            if e is None:
                # Owner already unregistered (e.g. a scan finishing after
                # compaction dropped its run): serve unmanaged so in-flight
                # reads stay correct; nothing to account.
                payload, _ = build()
                return payload
            if e.payload is not None:
                pool = self._pools[e.pool]
                pool.move_to_end(key)
                if priority == "high" and e.pool == "low":
                    self._move_pool(e, "high")
                if pin:
                    e.pins += 1
                    w = _pin_witness()
                    if w is not None:
                        w.pin_acquired(key, label=e.label)
                hit = True
                payload = e.payload
            else:
                payload = self._admit(e, build, nbytes_hint, priority,
                                      pin)
                hit = False
        (self._m_hits if hit else self._m_misses).increment()
        return payload

    def pin(self, key: int, build, nbytes_hint: int | None = None,
            priority: str | None = None):
        """Acquire + pin: the payload stays resident until :meth:`unpin`."""
        return self.acquire(key, build, nbytes_hint, priority, pin=True)

    def peek(self, key: int):
        """The resident payload, or None — never builds, never reorders
        the LRU pools.  For opportunistic reuse of planes that happen to
        be on device (e.g. feeding a stacked-mesh tablet update from the
        device-flush output) where a miss should NOT trigger an upload."""
        with self._lock:
            self._drain_dead()
            e = self._entries.get(key)
            return e.payload if e is not None else None

    def unpin(self, key: int) -> None:
        with self._lock:
            self._drain_dead()
            e = self._entries.get(key)
            if e is None:
                return
            if e.pins > 0:
                e.pins -= 1
                w = _pin_witness()
                if w is not None:
                    w.pin_released(key)
            # Unpinning may unlock deferred evictions on this device.
            b = self.budget()
            if b and self._dev_resident.get(e.device, 0) > b:
                self._evict_until(b, e.device)

    # -- derived-tensor side cars (pallas gather tensors) --------------------

    def aux_get(self, key: int, aux_key):
        with self._lock:
            self._drain_dead()
            e = self._entries.get(key)
            if e is None or e.payload is None:
                return None
            return e.aux.get(aux_key)

    def aux_put(self, key: int, aux_key, value, nbytes: int) -> None:
        """Attach a derived device tensor set to a resident entry; it is
        charged with — and dropped with — the entry.  A no-op if the
        entry was evicted meanwhile (the caller still holds ``value``)."""
        with self._lock:
            self._drain_dead()
            e = self._entries.get(key)
            if e is None or e.payload is None or aux_key in e.aux:
                return
            e.aux[aux_key] = value
            e.aux_bytes += int(nbytes)
            self._charge(e, int(nbytes))
            b = self.budget()
            if b and self._dev_resident.get(e.device, 0) > b:
                self._evict_until(b, e.device)

    # -- internals ------------------------------------------------------------

    def _drain_dead(self) -> None:
        """Reap entries whose owners were collected (lock held).  The
        weakref callbacks only enqueue; all structural mutation happens
        here, at a point where no pool iteration is in progress."""
        while True:
            try:
                key = self._dead.popleft()
            except IndexError:
                return
            e = self._entries.pop(key, None)
            if e is not None and e.payload is not None:
                self._release_entry(e, evicted=False)

    def _admit(self, e: _Entry, build, hint, priority, pin: bool):
        b = self.budget()
        # The device MemTracker limit is the SUM of per-device budgets:
        # one flag value per chip seen so far.
        ndev = max(1, len(self._dev_resident))
        root_tracker().child("device").set_limit((b * ndev) or None)
        if b and hint:
            self._evict_until(max(b - int(hint), 0), e.device)
        payload, nbytes = build()
        e.payload = payload
        # DeviceRun payloads carry .encoded (compressed plane tree vs
        # plain planes under --tpu_plane_encoding); anything else —
        # including a demand re-upload after eviction — defaults plain.
        e.encoding = ("encoded" if getattr(payload, "encoded", False)
                      else "plain")
        e.nbytes = int(nbytes)
        e.aux = {}
        e.aux_bytes = 0
        e.pool = "low" if priority == "low" else "high"
        self._pools[e.pool][e.key] = e
        if pin:
            e.pins += 1
            w = _pin_witness()
            if w is not None:
                w.pin_acquired(e.key, label=e.label)
        self._charge(e, e.nbytes)
        self._m_upload.increment(e.nbytes)
        up = self._dev_upload.get(e.device)
        if up is not None:
            up.increment(e.nbytes)
        if b:
            self._rebalance_high(b, e.device)
            self._evict_until(b, e.device)
        sync_point("hbm_cache:admit", e.label)
        return payload

    def _bump_dev(self, device: str, nbytes: int) -> None:
        """Adjust one device's residency bucket (lock held); first touch
        lazily creates the {device=...}-labeled metric series."""
        if device not in self._dev_resident:
            self._dev_resident[device] = 0
            ent = hbm_device_entity(device)
            ent.gauge("yb_hbm_resident_bytes",
                      lambda d=device: self.device_resident_bytes(d))
            self._dev_upload[device] = ent.counter(
                "yb_hbm_demand_upload_bytes")
        self._dev_resident[device] += nbytes

    def _charge(self, e: _Entry, nbytes: int) -> None:
        self._resident += nbytes
        if self._resident > self._peak_resident:
            self._peak_resident = self._resident
        if e.dev_bytes is not None and nbytes == e.nbytes:
            # External multi-device initial charge: split per shard.
            for d, n in e.dev_bytes.items():
                self._bump_dev(d, n)
        else:
            self._bump_dev(e.device, nbytes)
        if e.tracker is not None:
            e.tracker.consume(nbytes)

    def _move_pool(self, e: _Entry, pool: str) -> None:
        self._pools[e.pool].pop(e.key, None)
        e.pool = pool
        self._pools[pool][e.key] = e

    def _rebalance_high(self, b: int, device: str) -> None:
        """High-pool cap, per device: one chip's protected working set
        can't demote another chip's."""
        cap = int(b * HIGH_PRI_POOL_RATIO)
        high = self._pools["high"]
        hb = sum(en.total_bytes for en in high.values()
                 if not en.external and en.device == device)
        for k in list(high.keys()):
            if hb <= cap:
                break
            en = high[k]
            if en.external or en.device != device:
                continue
            self._move_pool(en, "low")
            hb -= en.total_bytes

    def _evict_until(self, target: int, device: str | None = None) -> None:
        """Evict LRU-first until ``device``'s bucket (or, with
        device=None, global residency) is within ``target``."""
        def over():
            if device is None:
                return self._resident > target
            return self._dev_resident.get(device, 0) > target
        while over():
            if not self._evict_one(device):
                break  # everything left is pinned: allowed overflow

    def _evict_one(self, device: str | None = None) -> bool:
        for pool_name in ("low", "high"):
            for en in self._pools[pool_name].values():
                if en.pins == 0 and not en.external and (
                        device is None or en.device == device):
                    self._release_entry(en, evicted=True)
                    return True
        return False

    def _release_entry(self, e: _Entry, evicted: bool) -> None:
        total = e.total_bytes
        w = _pin_witness()
        if w is not None:
            # Entry teardown retires every pin on the key at once
            # (invalidate / owner collected) — balanced, not a leak.
            w.pins_cleared(e.key)
        self._pools[e.pool].pop(e.key, None)
        e.payload = None
        e.aux = {}
        self._resident -= total
        if e.dev_bytes is not None:
            for d, n in e.dev_bytes.items():
                self._bump_dev(d, -n)
            e.dev_bytes = None
        else:
            self._bump_dev(e.device, -total)
        if e.tracker is not None:
            e.tracker.release(total)
        e.nbytes = 0
        e.aux_bytes = 0
        e.pins = 0
        e.encoding = "plain"
        if evicted:
            self._m_evictions.increment()
            sync_point("hbm_cache:evict", e.label)

    # -- introspection --------------------------------------------------------

    def resident_bytes(self) -> int:
        with self._lock:
            self._drain_dead()
            return self._resident

    def device_resident_bytes(self, device: str | None = None):
        """One device's resident bytes, or the full {device: bytes} map
        when ``device`` is None."""
        with self._lock:
            if device is not None:
                return self._dev_resident.get(device, 0)
            return dict(self._dev_resident)

    def pinned_bytes(self) -> int:
        with self._lock:
            self._drain_dead()
            return sum(e.total_bytes
                       for pool in self._pools.values()
                       for e in pool.values() if e.pins > 0)

    def peak_resident_bytes(self) -> int:
        with self._lock:
            return self._peak_resident

    def evict_unpinned(self) -> int:
        """Drop every unpinned entry (test hook for eviction pressure);
        returns how many entries were evicted."""
        n = 0
        with self._lock:
            self._drain_dead()
            while self._evict_one():
                n += 1
        return n

    def stats(self) -> dict:
        with self._lock:
            self._drain_dead()
            pools = {
                name: {"entries": len(pool),
                       "bytes": sum(e.total_bytes for e in pool.values())}
                for name, pool in self._pools.items()}
            by_enc: dict[str, dict] = {}
            for pool in self._pools.values():
                for e in pool.values():
                    d = by_enc.setdefault(e.encoding,
                                          {"entries": 0, "bytes": 0})
                    d["entries"] += 1
                    d["bytes"] += e.total_bytes
            b = self.budget()
            by_dev: dict[str, dict] = {
                dev: {"resident_bytes": n, "budget_bytes": b,
                      "entries": 0, "pinned_bytes": 0}
                for dev, n in sorted(self._dev_resident.items())}
            for pool in self._pools.values():
                for e in pool.values():
                    devs = (e.dev_bytes if e.dev_bytes is not None
                            else {e.device: e.total_bytes})
                    for dev, n in devs.items():
                        d = by_dev.setdefault(
                            dev, {"resident_bytes": 0, "budget_bytes": b,
                                  "entries": 0, "pinned_bytes": 0})
                        d["entries"] += 1
                        if e.pins > 0:
                            d["pinned_bytes"] += n
            out = {
                "budget_bytes": b,
                "resident_bytes": self._resident,
                "peak_resident_bytes": self._peak_resident,
                "registered": len(self._entries),
                "pools": pools,
                "by_encoding": by_enc,
                "by_device": by_dev,
            }
        out["pinned_bytes"] = self.pinned_bytes()
        out["hits"] = self._m_hits.get()
        out["misses"] = self._m_misses.get()
        out["evictions"] = self._m_evictions.get()
        out["demand_upload_bytes"] = self._m_upload.get()
        return out


def device_nbytes(tree) -> int:
    """Device bytes of a nested dict/list/tuple of arrays (duck-typed:
    anything with .size and .dtype.itemsize) — the footprint charged for
    cache payloads and aux tensors."""
    total = 0
    stack = [tree]
    while stack:
        node = stack.pop()
        if isinstance(node, dict):
            stack.extend(node.values())
        elif isinstance(node, (list, tuple)):
            stack.extend(node)
        elif node is not None:
            total += int(node.size) * node.dtype.itemsize
    return total


_CACHE: HbmCache | None = None
_CACHE_LOCK = threading.Lock()


def hbm_cache() -> HbmCache:
    """The process-wide residency cache (one HBM, one budget)."""
    global _CACHE
    if _CACHE is None:
        with _CACHE_LOCK:
            if _CACHE is None:
                _CACHE = HbmCache()
    return _CACHE
