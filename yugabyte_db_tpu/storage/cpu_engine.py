"""CPU storage engine: the exact oracle and the CPU baseline.

Reference analog: the behavior of DocDB-on-RocksDB reads
(DocRowwiseIterator + IntentAwareIterator + GetSubDocument,
src/yb/docdb/doc_rowwise_iterator.cc) expressed directly: per-key version
lists in sorted runs, merged at read time by storage.merge. Also plays the
role of the in-memory model-checking oracle the reference uses in
randomized DocDB tests (InMemDocDbState, src/yb/docdb/in_mem_docdb.cc) —
the TPU engine must produce identical results on every scan.
"""

from __future__ import annotations

import bisect
import heapq

from yugabyte_db_tpu.models.encoding import decode_doc_key
from yugabyte_db_tpu.models.schema import Schema
from yugabyte_db_tpu.storage.engine import StorageEngine, register_engine
from yugabyte_db_tpu.storage.memtable import (MemTable, NativeMemTable,
                                              make_memtable)
from yugabyte_db_tpu.storage.merge import MergedRow, merge_versions
from yugabyte_db_tpu.storage.row_version import RowVersion
from yugabyte_db_tpu.storage.scan_spec import AggSpec, ScanResult, ScanSpec


class CpuRun:
    """One immutable sorted run: keys ascending, per-key versions ht-desc.

    Reference analog: one SSTable (block_based_table_reader) — here a plain
    sorted list because the CPU engine optimizes for being obviously correct.
    """

    def __init__(self, entries: list[tuple[bytes, list[RowVersion]]]):
        self.keys = [k for k, _ in entries]
        self.versions = [v for _, v in entries]
        self.num_versions = sum(len(v) for v in self.versions)
        self.min_key = self.keys[0] if self.keys else b""
        self.max_key = self.keys[-1] if self.keys else b""

    def scan_keys(self, lower: bytes, upper: bytes):
        i = bisect.bisect_left(self.keys, lower)
        while i < len(self.keys):
            k = self.keys[i]
            if upper and k >= upper:
                return
            yield k
            i += 1

    def get(self, key: bytes) -> list[RowVersion]:
        i = bisect.bisect_left(self.keys, key)
        if i < len(self.keys) and self.keys[i] == key:
            return self.versions[i]
        return []


class RowMaterializer:
    """Shared helper: merged row + decoded key -> output tuple / predicate eval.

    Key columns live in the encoded DocKey (not in the version columns), so
    materialization decodes them positionally (models.encoding layout).
    """

    def __init__(self, schema: Schema):
        self.schema = schema
        self._key_cols = {c.name: i for i, c in enumerate(schema.key_columns)}
        self._val_ids = {c.name: c.col_id for c in schema.value_columns}

    def key_values(self, key: bytes) -> list:
        _, hashed, ranges = decode_doc_key(key)
        return hashed + ranges

    def value(self, name: str, key_vals: list, merged: MergedRow):
        if name in self._key_cols:
            return key_vals[self._key_cols[name]]
        return merged.get(self._val_ids[name])

    def matches(self, spec: ScanSpec, key_vals: list, merged: MergedRow) -> bool:
        return all(
            p.matches(self.value(p.column, key_vals, merged))
            for p in spec.predicates
        )


class Aggregator:
    """Pushdown aggregation: count/sum/min/max/avg with optional GROUP BY.

    Reference analog: QLReadOperation::EvalAggregate /
    PgsqlReadOperation::EvalAggregate (per-tablet partials computed inside
    the scan, src/yb/docdb/pgsql_operation.cc:473).
    """

    def __init__(self, aggs: list[AggSpec], group_by: list[str]):
        self.aggs = aggs
        self.group_by = group_by
        self.groups: dict[tuple, list] = {}

    def _new_acc(self) -> list:
        return [None] * len(self.aggs)

    def add(self, get_value) -> None:
        from yugabyte_db_tpu.storage.expr import eval_expr

        gkey = tuple(get_value(c) for c in self.group_by)
        acc = self.groups.get(gkey)
        if acc is None:
            acc = self.groups[gkey] = self._new_acc()
        for i, a in enumerate(self.aggs):
            if a.fn == "count":
                if a.column is None and a.expr is None:
                    acc[i] = (acc[i] or 0) + 1
                else:
                    v = (eval_expr(a.expr, get_value)
                         if a.expr is not None else get_value(a.column))
                    if v is not None:
                        acc[i] = (acc[i] or 0) + 1
                continue
            v = (eval_expr(a.expr, get_value) if a.expr is not None
                 else get_value(a.column))
            if v is None:
                continue
            if a.fn == "sum":
                acc[i] = v if acc[i] is None else acc[i] + v
            elif a.fn == "min":
                acc[i] = v if acc[i] is None else min(acc[i], v)
            elif a.fn == "max":
                acc[i] = v if acc[i] is None else max(acc[i], v)
            elif a.fn == "avg":
                s, n = acc[i] or (0, 0)
                acc[i] = (s + v, n + 1)

    def results(self) -> list[tuple]:
        if not self.groups and not self.group_by:
            self.groups[()] = self._new_acc()
        rows = []
        for gkey in sorted(self.groups, key=lambda g: tuple(map(_sortable, g))):
            acc = self.groups[gkey]
            out = list(gkey)
            for i, a in enumerate(self.aggs):
                v = acc[i]
                if a.fn == "count":
                    v = v or 0
                elif a.fn == "avg" and v is not None:
                    v = v[0] / v[1]
                out.append(v)
            rows.append(tuple(out))
        return rows

    def column_names(self) -> list[str]:
        names = list(self.group_by)
        for a in self.aggs:
            names.append(a.output_name)
        return names


def _sortable(v):
    # Group keys may mix None with values; sort None first.
    return (v is None, v)


class CpuStorageEngine(StorageEngine):
    def __init__(self, schema: Schema, options: dict | None = None):
        super().__init__(schema, options)
        from yugabyte_db_tpu.storage.run_io import RunPersistence

        self.memtable = make_memtable()
        self.runs: list[CpuRun] = []
        self.mat = RowMaterializer(schema)
        self.flushed_frontier_ht = 0  # max ht persisted into runs
        self.persist = RunPersistence(self.options.get("data_dir"))
        for entries in self.persist.load_all():
            run = CpuRun(entries)
            self.runs.append(run)
            for versions in run.versions:
                for v in versions:
                    self.flushed_frontier_ht = max(self.flushed_frontier_ht, v.ht)

    # -- writes ------------------------------------------------------------
    def alter_schema(self, new_schema) -> None:
        super().alter_schema(new_schema)
        self.mat = RowMaterializer(new_schema)

    def apply(self, rows: list[RowVersion]) -> None:
        self.memtable.apply(rows)
        self._after_apply()

    def apply_block(self, block: bytes) -> None:
        self.memtable.apply_block(block)
        self._after_apply()

    def _after_apply(self) -> None:
        from yugabyte_db_tpu.utils.flags import FLAGS

        limit = self.options.get("memtable_flush_versions",
                                 FLAGS.get("memtable_flush_versions"))
        if self.memtable.num_versions >= limit:
            self.flush()
            self.maybe_compact()
        self._track_memstore()

    # -- lifecycle ---------------------------------------------------------
    def flush(self) -> None:
        if self.memtable.is_empty:
            return
        if self.memtable.max_ht is not None:
            self.flushed_frontier_ht = max(self.flushed_frontier_ht,
                                           self.memtable.max_ht)
        entries = self.memtable.drain_sorted()
        self.persist.save_new(entries)
        self.runs.append(CpuRun(entries))
        self.memtable = make_memtable()
        self._track_memstore()

    def restore_entries(self, entries) -> None:
        self.memtable = make_memtable()
        self.persist.replace_all(entries)
        self.runs = [CpuRun(entries)] if entries else []
        for _key, versions in entries:
            for v in versions:
                self.flushed_frontier_ht = max(self.flushed_frontier_ht,
                                               v.ht)

    def compact(self, history_cutoff_ht: int = 0) -> None:
        if len(self.runs) <= 1 and history_cutoff_ht == 0:
            return
        merged: list[tuple[bytes, list[RowVersion]]] = []
        for key, versions in self._merge_runs_by_key():
            kept = self._gc_versions(key, versions, history_cutoff_ht)
            if kept:
                merged.append((key, kept))
        self.persist.replace_all(merged)
        self.runs = [CpuRun(merged)] if merged else []

    def _merge_runs_by_key(self):
        """Yield (key, versions ht-desc) over all runs, key-merged.

        Reference analog: the MergingIterator k-way merge inside
        CompactionJob::Run (src/yb/rocksdb/db/compaction_job.cc:622).
        """
        def run_iter(run):
            return ((k, run) for k in run.scan_keys(b"", b""))

        iters = [run_iter(run) for run in self.runs]
        current_key = None
        bucket: list[RowVersion] = []
        for key, run in heapq.merge(*iters, key=lambda p: p[0]):
            if key != current_key:
                if current_key is not None:
                    yield current_key, sorted(bucket, key=lambda r: (-r.ht, -r.write_id))
                current_key, bucket = key, []
            bucket.extend(run.get(key))
        if current_key is not None:
            yield current_key, sorted(bucket, key=lambda r: (-r.ht, -r.write_id))

    @staticmethod
    def _gc_versions(key: bytes, versions: list[RowVersion],
                     cutoff: int) -> list[RowVersion]:
        """History GC: keep versions needed by any read at read_ht >= cutoff.

        Reference analog: DocDBCompactionFilter retention
        (src/yb/docdb/docdb_compaction_filter.cc) driven by
        TabletRetentionPolicy's history cutoff.
        """
        if cutoff <= 0:
            return versions
        state = merge_versions(key, versions, cutoff)
        contributing = set(state.value_hts.values())
        if state.live_ht:
            contributing.add(state.live_ht)
        kept = [
            v for v in versions
            if v.ht > cutoff or (v.ht in contributing and v.ht > state.tomb_ht)
        ]
        return kept  # tombstones <= cutoff drop: nothing older remains to shadow

    def dump_entries(self):
        """All flushed (key, versions ht-desc) pairs, key-merged across
        runs — the storage payload of a remote-bootstrap session."""
        return list(self._merge_runs_by_key())

    def stats(self) -> dict:
        return {
            "num_runs": len(self.runs),
            "memtable_versions": self.memtable.num_versions,
            "run_versions": sum(r.num_versions for r in self.runs),
            "flushed_frontier_ht": self.flushed_frontier_ht,
        }

    # -- reads -------------------------------------------------------------
    def _sources(self):
        return [self.memtable] + list(self.runs)

    def _merged_rows(self, spec: ScanSpec):
        """Yield (key, MergedRow) in key order over [lower, upper)."""
        sources = self._sources()
        key_iters = [src.scan_keys(spec.lower, spec.upper) for src in sources]
        merged_keys = heapq.merge(*key_iters)
        last = None
        for key in merged_keys:
            if key == last:
                continue
            last = key
            versions: list[RowVersion] = []
            for src in sources:
                if isinstance(src, (MemTable, NativeMemTable)):
                    versions.extend(src.versions(key))
                else:
                    versions.extend(src.get(key))
            yield key, merge_versions(key, versions, spec.read_ht)

    def scan_batch(self, specs: list[ScanSpec],
                   deadline=None) -> list[ScanResult]:
        """Point gets skip the k-way source merge: one map/bisect lookup
        per source (the DocRowwiseIterator point-get shape); everything
        else takes the generic scan. Results are identical to scan() —
        pinned by tests/test_point_fastpath.py. ``deadline`` is the RPC
        edge's propagated budget (utils.retry.Deadline): checked between
        specs so an expired batch aborts with Code.TIMED_OUT."""
        from yugabyte_db_tpu.storage.scan_spec import point_key_of

        out = []
        for s in specs:
            if deadline is not None:
                deadline.check("cpu_engine.scan_batch")
            pk = point_key_of(s, self.schema)
            out.append(self.scan(s) if pk is None
                       else self._point_scan(s, pk))
        return out

    def _point_scan(self, spec: ScanSpec, key: bytes) -> ScanResult:
        versions: list[RowVersion] = list(self.memtable.versions(key))
        for run in self.runs:
            versions.extend(run.get(key))
        projection = spec.projection or [c.name for c in
                                         self.schema.columns]
        rows: list[tuple] = []
        resume = None
        scanned = 0
        if versions:
            scanned = 1
            merged = merge_versions(key, versions, spec.read_ht)
            if merged.exists:
                key_vals = self.mat.key_values(key)
                if self.mat.matches(spec, key_vals, merged):
                    rows.append(tuple(
                        self.mat.value(name, key_vals, merged)
                        for name in projection))
                    if spec.limit is not None and \
                            len(rows) >= spec.limit:
                        resume = key + b"\x00"
        return ScanResult(projection, rows, resume, scanned)

    def scan(self, spec: ScanSpec) -> ScanResult:
        if spec.is_aggregate:
            return self._scan_aggregate(spec)
        projection = spec.projection or [c.name for c in self.schema.columns]
        rows: list[tuple] = []
        scanned = 0
        resume = None
        for key, merged in self._merged_rows(spec):
            scanned += 1
            if not merged.exists:
                continue
            key_vals = self.mat.key_values(key)
            if not self.mat.matches(spec, key_vals, merged):
                continue
            rows.append(tuple(
                self.mat.value(name, key_vals, merged) for name in projection))
            if spec.limit is not None and len(rows) >= spec.limit:
                resume = key + b"\x00"  # smallest key strictly greater
                break
        return ScanResult(projection, rows, resume, scanned)

    def _scan_aggregate(self, spec: ScanSpec) -> ScanResult:
        agg = Aggregator(spec.aggregates, spec.group_by or [])
        scanned = 0
        for key, merged in self._merged_rows(spec):
            scanned += 1
            if not merged.exists:
                continue
            key_vals = self.mat.key_values(key)
            if not self.mat.matches(spec, key_vals, merged):
                continue
            agg.add(lambda name: self.mat.value(name, key_vals, merged))
        return ScanResult(agg.column_names(), agg.results(), None, scanned)


register_engine("cpu", CpuStorageEngine)
