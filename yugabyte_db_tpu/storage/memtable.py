"""In-memory write buffer: the memtable.

Reference analog: src/yb/rocksdb/memtable (skiplist memtable). Host-side
Python structure: a dict keyed by encoded key with per-key version lists,
plus a lazily-sorted key index for ordered scans. Writes are O(1); the sort
is amortized across scans/flushes. (A C++ skiplist replaces this on the
native path; the interface is what matters here.)
"""

from __future__ import annotations

import bisect

from yugabyte_db_tpu.storage.merge import MergedRow, merge_versions
from yugabyte_db_tpu.storage.row_version import RowVersion


class MemTable:
    def __init__(self):
        self._data: dict[bytes, list[RowVersion]] = {}
        self._sorted_keys: list[bytes] | None = []
        self.num_versions = 0
        self.approx_bytes = 0
        self.min_ht = None
        self.max_ht = None

    def __len__(self) -> int:
        return self.num_versions

    @property
    def is_empty(self) -> bool:
        return self.num_versions == 0

    def apply(self, rows: list[RowVersion]) -> None:
        for r in rows:
            versions = self._data.get(r.key)
            if versions is None:
                self._data[r.key] = [r]
                self._sorted_keys = None  # new key invalidates the index
            else:
                versions.append(r)
            self.num_versions += 1
            self.approx_bytes += len(r.key) + 64 + 16 * len(r.columns)
            if self.min_ht is None or r.ht < self.min_ht:
                self.min_ht = r.ht
            if self.max_ht is None or r.ht > self.max_ht:
                self.max_ht = r.ht

    def _index(self) -> list[bytes]:
        if self._sorted_keys is None:
            self._sorted_keys = sorted(self._data.keys())
        return self._sorted_keys

    def scan_keys(self, lower: bytes, upper: bytes):
        """Yield keys in [lower, upper) in order (upper=b'' means unbounded)."""
        keys = self._index()
        i = bisect.bisect_left(keys, lower)
        while i < len(keys):
            k = keys[i]
            if upper and k >= upper:
                return
            yield k
            i += 1

    def versions(self, key: bytes) -> list[RowVersion]:
        return self._data.get(key, [])

    def merged(self, key: bytes, read_ht: int) -> MergedRow | None:
        versions = self._data.get(key)
        if not versions:
            return None
        return merge_versions(key, versions, read_ht)

    def drain_sorted(self) -> list[tuple[bytes, list[RowVersion]]]:
        """All (key, versions ht-desc) in key order — the flush input."""
        data = self._data

        def order(r):
            return (r.ht, r.write_id)

        return [(k, vs if len(vs := data[k]) == 1
                 else sorted(vs, key=order, reverse=True))
                for k in self._index()]
