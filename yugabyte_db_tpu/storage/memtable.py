"""In-memory write buffer: the memtable.

Reference analog: src/yb/rocksdb/memtable (skiplist memtable). Two
implementations behind one interface:

- ``MemTable`` — pure Python: a dict keyed by encoded key with per-key
  version lists, plus a lazily-sorted key index for ordered scans.
- ``NativeMemTable`` — the C++ ordered map of native/writeplane.cc
  (module yb_wp), applied to directly from encoded row blocks so the hot
  write path never builds per-row Python objects; reads materialize
  RowVersions on demand.

``make_memtable()`` picks the native one when the extension is present.
"""

from __future__ import annotations

import bisect

from yugabyte_db_tpu.storage import rowblock
from yugabyte_db_tpu.storage.merge import MergedRow, merge_versions
from yugabyte_db_tpu.storage.row_version import RowVersion


class MemTable:
    def __init__(self):
        self._data: dict[bytes, list[RowVersion]] = {}
        self._sorted_keys: list[bytes] | None = []
        # Apply-order log backing versions_since(); entries are the same
        # RowVersion objects _data holds, so the overhead is one pointer
        # per version.
        self._log: list[RowVersion] | None = []
        self.num_versions = 0
        self.approx_bytes = 0
        self.min_ht = None
        self.max_ht = None

    def __len__(self) -> int:
        return self.num_versions

    @property
    def is_empty(self) -> bool:
        return self.num_versions == 0

    def apply(self, rows: list[RowVersion]) -> None:
        for r in rows:
            versions = self._data.get(r.key)
            if versions is None:
                self._data[r.key] = [r]
                self._sorted_keys = None  # new key invalidates the index
            else:
                versions.append(r)
            if self._log is not None:
                self._log.append(r)
            self.num_versions += 1
            self.approx_bytes += len(r.key) + 64 + 16 * len(r.columns)
            if self.min_ht is None or r.ht < self.min_ht:
                self.min_ht = r.ht
            if self.max_ht is None or r.ht > self.max_ht:
                self.max_ht = r.ht

    def _index(self) -> list[bytes]:
        if self._sorted_keys is None:
            self._sorted_keys = sorted(self._data.keys())
        return self._sorted_keys

    def scan_keys(self, lower: bytes, upper: bytes):
        """Yield keys in [lower, upper) in order (upper=b'' means unbounded)."""
        keys = self._index()
        i = bisect.bisect_left(keys, lower)
        while i < len(keys):
            k = keys[i]
            if upper and k >= upper:
                return
            yield k
            i += 1

    def has_keys(self, lower: bytes, upper: bytes) -> bool:
        """Any key in [lower, upper)? (the scan-planning emptiness probe)."""
        return next(self.scan_keys(lower, upper), None) is not None

    def versions(self, key: bytes) -> list[RowVersion]:
        return self._data.get(key, [])

    def versions_since(self, n: int) -> list[RowVersion] | None:
        """Row versions applied after global version index ``n`` (i.e.
        once ``num_versions`` was ``n``), in apply order — the delta
        source for the incremental scan overlay.  None when the log is
        unavailable, which tells the caller to rebuild from scratch."""
        if self._log is None:
            return None
        return self._log[n:]

    def merged(self, key: bytes, read_ht: int) -> MergedRow | None:
        versions = self._data.get(key)
        if not versions:
            return None
        return merge_versions(key, versions, read_ht)

    def apply_block(self, block: bytes) -> None:
        """Apply an encoded row block (storage.rowblock layout)."""
        self.apply(rowblock.rows_from_block(block))

    def drain_sorted(self) -> list[tuple[bytes, list[RowVersion]]]:
        """All (key, versions ht-desc) in key order — the flush input."""
        data = self._data

        def order(r):
            return (r.ht, r.write_id)

        return [(k, vs if len(vs := data[k]) == 1
                 else sorted(vs, key=order, reverse=True))
                for k in self._index()]


class NativeMemTable:
    """The C++ memtable (yb_wp.Memtable) behind the MemTable interface.

    apply_block() is the hot path: one native call per replicated batch,
    no per-row Python objects. Reads (versions/merged/drain) materialize
    RowVersions from native tuples — amortized over scans and flushes.

    Rows the native codec cannot represent (integers beyond int64 — the
    tagged-varint grammar's documented Python-fallback case, e.g. inside
    JSONB values) SPILL to a pure-Python MemTable merged on every read:
    an un-encodable value must degrade that row to the slow path, never
    crash the Raft apply stage.
    """

    # Stop logging for versions_since() past this many logged block
    # bytes: a memtable this large is about to flush anyway, and the
    # overlay falls back to a full rebuild when the log is gone.
    LOG_BYTES_CAP = 64 << 20

    def __init__(self):
        from yugabyte_db_tpu.native import yb_wp

        self._mt = yb_wp.Memtable()
        self._spill: MemTable | None = None
        # Apply-order log of ("b", encoded block) / ("r", RowVersion)
        # entries with a parallel list of version-count offsets, backing
        # versions_since().  Blocks are kept encoded (zero copies on the
        # hot path) and decoded lazily on delta reads.
        self._log: list[tuple[str, object]] | None = []
        self._log_starts: list[int] = []
        self._log_bytes = 0

    def __len__(self) -> int:
        return self.num_versions

    @property
    def num_versions(self) -> int:
        n = self._mt.num_versions
        return n + self._spill.num_versions if self._spill else n

    @property
    def approx_bytes(self) -> int:
        n = self._mt.approx_bytes
        return n + self._spill.approx_bytes if self._spill else n

    @property
    def min_ht(self):
        a = self._mt.min_ht
        b = self._spill.min_ht if self._spill else None
        if a is None:
            return b
        return a if b is None else min(a, b)

    @property
    def max_ht(self):
        a = self._mt.max_ht
        b = self._spill.max_ht if self._spill else None
        if a is None:
            return b
        return a if b is None else max(a, b)

    @property
    def is_empty(self) -> bool:
        return self.num_versions == 0

    def _log_note(self, start: int, kind: str, payload,
                  nbytes: int) -> None:
        if self._log is None:
            return
        self._log_bytes += nbytes
        if self._log_bytes > self.LOG_BYTES_CAP:
            self._log = None
            self._log_starts = []
            return
        self._log.append((kind, payload))
        self._log_starts.append(start)

    def apply_block(self, block: bytes) -> None:
        start = self.num_versions
        self._mt.apply_block(block)
        self._log_note(start, "b", block, len(block))

    def apply(self, rows: list[RowVersion]) -> None:
        try:
            self.apply_block(rowblock.encode_rows(rows))
        except (OverflowError, ValueError, TypeError):
            for r in rows:  # isolate the un-encodable row(s)
                try:
                    self.apply_block(rowblock.encode_rows([r]))
                except (OverflowError, ValueError, TypeError):
                    if self._spill is None:
                        self._spill = MemTable()
                    start = self.num_versions
                    self._spill.apply([r])
                    self._log_note(start, "r", r, len(r.key) + 64)

    def versions_since(self, n: int) -> list[RowVersion] | None:
        """Row versions applied after global version index ``n``, in
        apply order (see MemTable.versions_since).  None once the log
        was capped — callers must fall back to a full rebuild."""
        if self._log is None:
            return None
        out: list[RowVersion] = []
        i = max(bisect.bisect_right(self._log_starts, n) - 1, 0)
        for kind, payload in self._log[i:]:
            start = self._log_starts[i]
            i += 1
            if kind == "b":
                rows = rowblock.rows_from_block(payload)
            else:
                rows = [payload]
            if start + len(rows) <= n:
                continue
            out.extend(rows[max(n - start, 0):])
        return out

    def scan_keys(self, lower: bytes, upper: bytes):
        native = self._mt.scan_keys(lower, upper)
        if not self._spill:
            return iter(native)
        import heapq

        merged = heapq.merge(native, self._spill.scan_keys(lower, upper))
        last = [None]

        def dedup():
            for k in merged:
                if k != last[0]:
                    last[0] = k
                    yield k
        return dedup()

    def has_keys(self, lower: bytes, upper: bytes) -> bool:
        if self._mt.has_keys(lower, upper):
            return True
        return bool(self._spill) and self._spill.has_keys(lower, upper)

    def versions(self, key: bytes) -> list[RowVersion]:
        out = [RowVersion(*t) for t in self._mt.versions(key)]
        if self._spill:
            out.extend(self._spill.versions(key))
        return out

    def merged(self, key: bytes, read_ht: int) -> MergedRow | None:
        versions = self.versions(key)
        if not versions:
            return None
        return merge_versions(key, versions, read_ht)

    def point_lookup(self, keys: list[bytes], read_ht: int, col_id: int):
        """Batch point-column lookup served entirely in C++ (the native
        request-batch path). Returns None when spilled rows exist — the
        spill may shadow any key, so no answer is definitive."""
        if self._spill:
            return None
        return self._mt.point_lookup(keys, read_ht, col_id)

    def drain_sorted(self) -> list[tuple[bytes, list[RowVersion]]]:
        native = [(k, [RowVersion(*t) for t in vers])
                  for k, vers in self._mt.drain_sorted()]
        if not self._spill:
            return native
        by_key = dict(native)
        for k, vers in self._spill.drain_sorted():
            if k in by_key:
                both = by_key[k] + vers
                both.sort(key=lambda r: (r.ht, r.write_id), reverse=True)
                by_key[k] = both
            else:
                by_key[k] = vers
        return [(k, by_key[k]) for k in sorted(by_key)]


def make_memtable():
    """The fastest available memtable implementation."""
    if rowblock.HAVE_NATIVE:
        return NativeMemTable()
    return MemTable()
