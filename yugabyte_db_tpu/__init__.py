"""yugabyte_db_tpu — a TPU-native distributed SQL database framework.

A brand-new implementation of the capabilities of YugaByte DB (reference:
glycerine/yugabyte-db v1.2.4): a hybrid-time MVCC document store (DocDB)
sharded into replicated tablets, serving Cassandra-compatible (YCQL),
Redis-compatible (YEDIS) and PostgreSQL-compatible (YSQL) APIs.

Design stance (TPU-first, not a port):

- Control plane (RPC, consensus, WAL, tablet lifecycle, catalog, txns) runs
  on host CPU, mirroring the reference's C++ architecture
  (src/yb/tserver, src/yb/consensus, src/yb/master).
- The storage-engine data plane is rebuilt for TPU: SSTable data blocks
  (reference: src/yb/rocksdb/table/block_builder.cc row-wise prefix-delta
  blocks) become HBM-resident columnar blocks, and range scans, predicate
  filtering, MVCC visibility resolution, aggregate pushdown and compaction
  merges run as JAX/XLA/Pallas kernels, selected by a
  ``tablet_storage_engine=tpu`` option behind the storage seam (reference:
  ``common::YQLStorageIf``, src/yb/common/ql_storage_interface.h:31).

Subpackage map (reference directory in parens):

- ``utils``      base libraries: status, hybrid time, encoding (src/yb/util, src/yb/gutil)
- ``models``     the data model: types, values, doc keys, schema, partitioning (src/yb/common, src/yb/docdb key encoding)
- ``storage``    the LSM storage engine: memtable, columnar runs, compaction (src/yb/rocksdb + src/yb/docdb storage)
- ``ops``        TPU kernels: scan/filter/MVCC/aggregate/merge (the new capability; no reference analog — replaces per-row iterators)
- ``parallel``   device-mesh sharding of tablets, ICI collectives (replaces single-threaded per-tablet scans)
- ``tablet``     replicated shard: MVCC mgr, operation pipeline, WAL, bootstrap (src/yb/tablet, src/yb/consensus/log*)
- ``consensus``  Raft consensus (src/yb/consensus)
- ``rpc``        messenger/proxy/service RPC framework (src/yb/rpc)
- ``tserver``    data-node daemon (src/yb/tserver)
- ``master``     control plane: catalog, placement, load balancing (src/yb/master)
- ``client``     routing client: meta cache, batcher, sessions (src/yb/client)
- ``yql``        API frontends: cql/, redis/, pgsql/ (src/yb/yql)
"""

__version__ = "0.1.0"
