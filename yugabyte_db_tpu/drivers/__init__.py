"""Vendored thin wire-protocol clients (stock-driver analogs).

The reference proves its YQL frontends against real drivers — the Java
CQL driver (java/yb-cql), Jedis (java/yb-jedis-tests), and libpq
(src/yb/yql/pgwrapper/pg_libpq-test.cc). Stock drivers cannot be
installed in this environment, so these are the thinnest faithful
client-side implementations of each protocol, written INDEPENDENTLY of
the server wire modules (own framing, own value codecs) so interop
tests exercise the server's bytes the way a foreign driver would —
including the control-connection schema-discovery handshake a DataStax
driver performs against system.local / system.peers / system_schema.*.

They are usable components, not test fixtures: the CLI tools can speak
to a remote cluster through them.
"""

from yugabyte_db_tpu.drivers.minicql import CqlConnection, CqlError
from yugabyte_db_tpu.drivers.minipg import PgConnection, PgError
from yugabyte_db_tpu.drivers.miniredis import RedisConnection, RedisError

__all__ = [
    "CqlConnection", "CqlError",
    "PgConnection", "PgError",
    "RedisConnection", "RedisError",
]
