"""Thin RESP (Redis Serialization Protocol) client (Jedis analog).

Implements the client side from the RESP2 spec, independent of the
server's codec: array-of-bulk-strings command encoding, full reply
parsing (simple string, error, integer, bulk, nested arrays), command
pipelining, AUTH/SELECT session setup, and the subscribe/publish
message-stream flow.

Reference analog: the Jedis usage in java/yb-jedis-tests.
"""

from __future__ import annotations

import socket


class RedisError(Exception):
    pass


class RedisConnection:
    def __init__(self, host: str, port: int,
                 password: str | None = None, db: int | None = None,
                 timeout: float = 10.0):
        self.sock = socket.create_connection((host, port),
                                             timeout=timeout)
        self._buf = b""
        if password is not None:
            self.command("AUTH", password)
        if db is not None:
            self.command("SELECT", db)

    # -- encoding ------------------------------------------------------------
    @staticmethod
    def _encode(args: tuple) -> bytes:
        out = [b"*%d\r\n" % len(args)]
        for a in args:
            if isinstance(a, (bytes, bytearray)):
                b = bytes(a)
            else:
                b = str(a).encode("utf-8")
            out.append(b"$%d\r\n" % len(b))
            out.append(b + b"\r\n")
        return b"".join(out)

    # -- reply parsing -------------------------------------------------------
    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise RedisError("connection closed")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n + 2:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise RedisError("connection closed")
            self._buf += chunk
        data, self._buf = self._buf[:n], self._buf[n + 2:]
        return data

    def _read_reply(self):
        line = self._read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode("utf-8")
        if kind == b"-":
            raise RedisError(rest.decode("utf-8"))
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n < 0:
                return None
            return self._read_exact(n)
        if kind == b"*":
            n = int(rest)
            if n < 0:
                return None
            return [self._read_reply() for _ in range(n)]
        raise RedisError(f"bad reply type {kind!r}")

    # -- commands ------------------------------------------------------------
    def command(self, *args):
        self.sock.sendall(self._encode(args))
        return self._read_reply()

    def pipeline(self, commands: list[tuple]):
        """Send all commands, then read all replies (errors returned
        in-place, as redis-py pipelines do)."""
        self.sock.sendall(b"".join(self._encode(c) for c in commands))
        out = []
        for _ in commands:
            try:
                out.append(self._read_reply())
            except RedisError as e:
                out.append(e)
        return out

    # -- pub/sub -------------------------------------------------------------
    def subscribe(self, *channels: str):
        """SUBSCRIBE and consume the per-channel confirmations."""
        self.sock.sendall(self._encode(("SUBSCRIBE",) + channels))
        acks = [self._read_reply() for _ in channels]
        return acks

    def get_message(self, timeout: float = 5.0):
        """Next pushed message on a subscribed connection."""
        self.sock.settimeout(timeout)
        try:
            return self._read_reply()
        finally:
            self.sock.settimeout(None)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
