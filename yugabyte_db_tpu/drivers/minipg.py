"""Thin PostgreSQL frontend/backend protocol v3 client (libpq analog).

Implements the client side from the protocol spec, independent of the
server's wire module: startup packet, cleartext-password auth, the
simple query flow (PQexec) and the extended flow PQexecParams uses
(Parse/Bind/Describe/Execute/Sync), RowDescription-driven text-format
decoding by type OID, ErrorResponse field parsing, and transaction
status tracked from ReadyForQuery.

Reference analog: the libpq usage in
src/yb/yql/pgwrapper/pg_libpq-test.cc.
"""

from __future__ import annotations

import socket
import struct

_U32 = struct.Struct(">I")
_PROTO = 196608           # 3.0

_OID_BOOL = 16
_OID_BYTEA = 17
_OID_INT8 = 20
_OID_INT2 = 21
_OID_INT4 = 23
_OID_TEXT = 25
_OID_FLOAT4 = 700
_OID_FLOAT8 = 701
_OID_NUMERIC = 1700


class PgError(Exception):
    def __init__(self, fields: dict):
        self.severity = fields.get("S", "ERROR")
        self.code = fields.get("C", "XX000")
        self.message = fields.get("M", "")
        super().__init__(f"{self.severity} {self.code}: {self.message}")


class PgResultSet:
    def __init__(self):
        self.columns: list[str] = []
        self.oids: list[int] = []
        self.rows: list[tuple] = []
        self.command_tag: str = ""


def _decode_text(oid: int, raw: bytes | None):
    if raw is None:
        return None
    s = raw.decode("utf-8")
    if oid in (_OID_INT2, _OID_INT4, _OID_INT8):
        return int(s)
    if oid in (_OID_FLOAT4, _OID_FLOAT8):
        return float(s)
    if oid == _OID_NUMERIC:
        import decimal

        return decimal.Decimal(s)
    if oid == _OID_BOOL:
        return s == "t"
    if oid == _OID_BYTEA and s.startswith("\\x"):
        return bytes.fromhex(s[2:])
    return s


def _param_text(v) -> bytes | None:
    if v is None:
        return None
    if isinstance(v, bool):
        return b"true" if v else b"false"
    if isinstance(v, (bytes, bytearray)):
        return b"\\x" + bytes(v).hex().encode()
    return str(v).encode("utf-8")


class PgConnection:
    """One backend session. execute() = PQexec (simple protocol);
    execute_params() = PQexecParams (extended protocol)."""

    def __init__(self, host: str, port: int, user: str = "yb",
                 password: str | None = None,
                 database: str | None = None, timeout: float = 10.0):
        self.sock = socket.create_connection((host, port),
                                             timeout=timeout)
        self._buf = b""
        self.parameters: dict[str, str] = {}
        self.txn_status = b"I"
        self._startup(user, password, database or user)

    # -- messaging -----------------------------------------------------------
    def _send(self, tag: bytes, payload: bytes = b"") -> None:
        self.sock.sendall(tag + _U32.pack(len(payload) + 4) + payload)

    def _read_msg(self):
        while len(self._buf) < 5:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise PgError({"M": "connection closed"})
            self._buf += chunk
        tag = self._buf[:1]
        (ln,) = _U32.unpack_from(self._buf, 1)
        while len(self._buf) < 1 + ln:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise PgError({"M": "connection closed"})
            self._buf += chunk
        payload = self._buf[5:1 + ln]
        self._buf = self._buf[1 + ln:]
        return tag, payload

    @staticmethod
    def _error_fields(payload: bytes) -> dict:
        fields = {}
        i = 0
        while i < len(payload) and payload[i:i + 1] != b"\x00":
            code = chr(payload[i])
            j = payload.index(b"\x00", i + 1)
            fields[code] = payload[i + 1:j].decode("utf-8", "replace")
            i = j + 1
        return fields

    # -- startup -------------------------------------------------------------
    def _startup(self, user, password, database) -> None:
        kv = (f"user\x00{user}\x00database\x00{database}\x00"
              "application_name\x00minipg\x00\x00").encode()
        self.sock.sendall(_U32.pack(len(kv) + 8) + _U32.pack(_PROTO) + kv)
        while True:
            tag, payload = self._read_msg()
            if tag == b"R":
                (code,) = _U32.unpack_from(payload)
                if code == 0:
                    continue
                if code == 3:  # cleartext password
                    pw = (password or "").encode() + b"\x00"
                    self._send(b"p", pw)
                    continue
                raise PgError({"M": f"unsupported auth code {code}"})
            if tag == b"S":
                k, v = payload.split(b"\x00")[:2]
                self.parameters[k.decode()] = v.decode()
            elif tag == b"K":
                pass  # BackendKeyData
            elif tag == b"E":
                raise PgError(self._error_fields(payload))
            elif tag == b"Z":
                self.txn_status = payload[:1]
                return

    # -- result collection ---------------------------------------------------
    def _collect(self) -> PgResultSet:
        res = PgResultSet()
        err = None
        while True:
            tag, payload = self._read_msg()
            if tag == b"T":
                (n,) = struct.unpack_from(">H", payload)
                off = 2
                for _ in range(n):
                    j = payload.index(b"\x00", off)
                    res.columns.append(payload[off:j].decode())
                    off = j + 1
                    _tbl, _att, oid, _sz, _mod, _fmt = struct.unpack_from(
                        ">IHIhih", payload, off)
                    res.oids.append(oid)
                    off += 18
            elif tag == b"D":
                (n,) = struct.unpack_from(">H", payload)
                off = 2
                vals = []
                for i in range(n):
                    (ln,) = struct.unpack_from(">i", payload, off)
                    off += 4
                    if ln < 0:
                        vals.append(None)
                    else:
                        oid = res.oids[i] if i < len(res.oids) else _OID_TEXT
                        vals.append(_decode_text(oid,
                                                 payload[off:off + ln]))
                        off += ln
                res.rows.append(tuple(vals))
            elif tag == b"C":
                res.command_tag = payload.rstrip(b"\x00").decode()
            elif tag in (b"1", b"2", b"3", b"n", b"I", b"t", b"s"):
                pass  # ParseComplete/BindComplete/CloseComplete/NoData/
                #       EmptyQuery/ParameterDescription/PortalSuspended
            elif tag == b"E":
                err = PgError(self._error_fields(payload))
            elif tag == b"Z":
                self.txn_status = payload[:1]
                if err is not None:
                    raise err
                return res

    # -- simple protocol -----------------------------------------------------
    def execute(self, sql: str) -> PgResultSet:
        self._send(b"Q", sql.encode("utf-8") + b"\x00")
        return self._collect()

    # -- extended protocol (PQexecParams shape) ------------------------------
    def execute_params(self, sql: str, params: list) -> PgResultSet:
        parse = b"\x00" + sql.encode("utf-8") + b"\x00" \
            + struct.pack(">H", 0)
        self._send(b"P", parse)
        bind = b"\x00\x00" + struct.pack(">H", 0)  # portal, stmt, fmts
        bind += struct.pack(">H", len(params))
        for p in params:
            bind += _pbytes(_param_text(p))
        bind += struct.pack(">H", 0)  # result formats: all text
        self._send(b"B", bind)
        self._send(b"D", b"P\x00")    # Describe portal
        self._send(b"E", b"\x00" + _U32.pack(0))
        self._send(b"S")
        return self._collect()

    def prepare(self, name: str, sql: str) -> None:
        parse = name.encode() + b"\x00" + sql.encode("utf-8") + b"\x00" \
            + struct.pack(">H", 0)
        self._send(b"P", parse)
        self._send(b"S")
        self._collect()

    def execute_prepared(self, name: str, params: list) -> PgResultSet:
        bind = b"\x00" + name.encode() + b"\x00" + struct.pack(">H", 0)
        bind += struct.pack(">H", len(params))
        for p in params:
            bind += _pbytes(_param_text(p))
        bind += struct.pack(">H", 0)
        self._send(b"B", bind)
        self._send(b"D", b"P\x00")
        self._send(b"E", b"\x00" + _U32.pack(0))
        self._send(b"S")
        return self._collect()

    def close(self) -> None:
        try:
            self._send(b"X")
            self.sock.close()
        except OSError:
            pass


def _pbytes(b: bytes | None) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b
