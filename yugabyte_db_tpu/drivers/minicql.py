"""Thin CQL native-protocol v4 client (DataStax-driver analog).

Implements the client side of the protocol from the spec, independent
of the server's wire module: own frame codec, own typed-value
(de)serialization keyed off the RESULT metadata's wire type ids, the
SASL-PLAIN auth exchange, prepared statements, and result paging.
`discover()` performs the control-connection handshake a stock driver
runs right after STARTUP — reading system.local, system.peers, and the
system_schema tables to build its topology + schema view.

Reference analog: the driver side expected by
src/yb/yql/cql/cqlserver/cql_message.{h,cc}; handshake shape from the
java/yb-cql driver tests.
"""

from __future__ import annotations

import socket
import struct
import threading

_HEADER = struct.Struct(">BBhBi")   # version, flags, stream, opcode, len

_OP_ERROR = 0x00
_OP_STARTUP = 0x01
_OP_READY = 0x02
_OP_AUTHENTICATE = 0x03
_OP_OPTIONS = 0x05
_OP_SUPPORTED = 0x06
_OP_QUERY = 0x07
_OP_RESULT = 0x08
_OP_PREPARE = 0x09
_OP_EXECUTE = 0x0A
_OP_AUTH_RESPONSE = 0x0F
_OP_AUTH_SUCCESS = 0x10

_RESULT_VOID = 0x0001
_RESULT_ROWS = 0x0002
_RESULT_SET_KEYSPACE = 0x0003
_RESULT_PREPARED = 0x0004
_RESULT_SCHEMA_CHANGE = 0x0005

# Wire type option ids (protocol v4 §6).
T_ASCII, T_BIGINT, T_BLOB, T_BOOLEAN = 0x0001, 0x0002, 0x0003, 0x0004
T_COUNTER, T_DECIMAL, T_DOUBLE, T_FLOAT = 0x0005, 0x0006, 0x0007, 0x0008
T_INT, T_TIMESTAMP, T_UUID, T_VARCHAR = 0x0009, 0x000B, 0x000C, 0x000D
T_VARINT, T_TIMEUUID, T_INET, T_DATE = 0x000E, 0x000F, 0x0010, 0x0011
T_TIME, T_SMALLINT, T_TINYINT = 0x0012, 0x0013, 0x0014
T_LIST, T_MAP, T_SET, T_UDT, T_TUPLE = 0x0020, 0x0021, 0x0022, 0x0030, 0x0031

_INT_WIDTHS = {T_BIGINT: 8, T_COUNTER: 8, T_TIMESTAMP: 8, T_INT: 4,
               T_SMALLINT: 2, T_TINYINT: 1}


class CqlError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(f"[{code:#06x}] {message}")
        self.code = code
        self.message = message


class _Buf:
    def __init__(self, data: bytes):
        self.b = data
        self.i = 0

    def take(self, n: int) -> bytes:
        if self.i + n > len(self.b):
            raise CqlError(0x000A, "short frame")
        v = self.b[self.i:self.i + n]
        self.i += n
        return v

    def byte(self) -> int:
        return self.take(1)[0]

    def short(self) -> int:
        return struct.unpack(">H", self.take(2))[0]

    def int32(self) -> int:
        return struct.unpack(">i", self.take(4))[0]

    def string(self) -> str:
        return self.take(self.short()).decode("utf-8")

    def bytes_(self) -> bytes | None:
        n = self.int32()
        return None if n < 0 else self.take(n)

    def short_bytes(self) -> bytes:
        return self.take(self.short())

    def type_spec(self):
        """Recursive type option: (id, params) — params hold element
        specs for collections / tuples, field list for UDTs."""
        tid = self.short()
        if tid in (T_LIST, T_SET):
            return (tid, [self.type_spec()])
        if tid == T_MAP:
            return (tid, [self.type_spec(), self.type_spec()])
        if tid == T_TUPLE:
            return (tid, [self.type_spec() for _ in range(self.short())])
        if tid == T_UDT:
            self.string()  # keyspace
            self.string()  # type name
            fields = []
            for _ in range(self.short()):
                fname = self.string()
                fields.append((fname, self.type_spec()))
            return (tid, fields)
        return (tid, None)


def _pstr(s: str) -> bytes:
    b = s.encode("utf-8")
    return struct.pack(">H", len(b)) + b


def _plstr(s: str) -> bytes:
    b = s.encode("utf-8")
    return struct.pack(">i", len(b)) + b


def _pbytes(b: bytes | None) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


def encode_cql(value) -> bytes | None:
    """Client-side bind serialization by Python type (what a driver
    does before it learns the server's bind metadata)."""
    import datetime
    import decimal
    import uuid

    if value is None:
        return None
    if isinstance(value, bool):
        return b"\x01" if value else b"\x00"
    if isinstance(value, int):
        return struct.pack(">q", value)
    if isinstance(value, float):
        return struct.pack(">d", value)
    if isinstance(value, str):
        return value.encode("utf-8")
    if isinstance(value, (bytes, bytearray)):
        return bytes(value)
    if isinstance(value, decimal.Decimal):
        sign, digits, exp = value.as_tuple()
        unscaled = int("".join(map(str, digits)))
        if sign:
            unscaled = -unscaled
        n = max(1, (unscaled.bit_length() + 8) // 8)
        return struct.pack(">i", -exp) + unscaled.to_bytes(n, "big",
                                                          signed=True)
    if isinstance(value, uuid.UUID):
        return value.bytes
    if isinstance(value, datetime.date):
        days = (value - datetime.date(1970, 1, 1)).days
        return struct.pack(">I", days + (1 << 31))
    raise CqlError(0x2200, f"cannot serialize {type(value).__name__}")


def encode_cql_typed(value, spec) -> bytes | None:
    """Bind serialization keyed off the server's bind metadata: a
    prepared INT column takes 4 bytes on the wire, SMALLINT 2, FLOAT a
    4-byte IEEE single — not the 8-byte guess the untyped path makes
    from the Python type. Falls back to encode_cql for types whose
    wire form does not depend on the column (text, blob, uuid, ...)."""
    if value is None:
        return None
    is_int = isinstance(value, int) and not isinstance(value, bool)
    is_num = is_int or isinstance(value, float)
    tid, _params = spec
    if tid in _INT_WIDTHS and is_int:
        width = _INT_WIDTHS[tid]
        try:
            return value.to_bytes(width, "big", signed=True)
        except OverflowError:
            raise CqlError(
                0x2200, f"value {value!r} out of range for "
                f"{width}-byte integer column") from None
    if tid == T_FLOAT and is_num:
        return struct.pack(">f", float(value))
    if tid == T_DOUBLE and is_num:
        return struct.pack(">d", float(value))
    if tid == T_VARINT and is_int:
        n = max(1, (value.bit_length() + 8) // 8)
        return value.to_bytes(n, "big", signed=True)
    if tid == T_BOOLEAN and isinstance(value, bool):
        return b"\x01" if value else b"\x00"
    # Type mismatch or column-independent wire form: the untyped
    # encoder's bytes go out and the server reports any mismatch.
    return encode_cql(value)


def decode_cql(spec, raw: bytes | None):
    """Wire bytes -> Python value from the RESULT metadata type spec."""
    import datetime
    import decimal
    import uuid

    if raw is None:
        return None
    tid, params = spec
    if tid in _INT_WIDTHS or tid == T_VARINT:
        return int.from_bytes(raw, "big", signed=True)
    if tid == T_BOOLEAN:
        return raw != b"\x00"
    if tid == T_DOUBLE:
        return struct.unpack(">d", raw)[0]
    if tid == T_FLOAT:
        return struct.unpack(">f", raw)[0]
    if tid in (T_VARCHAR, T_ASCII):
        return raw.decode("utf-8")
    if tid == T_DECIMAL:
        scale = struct.unpack(">i", raw[:4])[0]
        unscaled = int.from_bytes(raw[4:], "big", signed=True)
        return decimal.Decimal(unscaled).scaleb(-scale)
    if tid in (T_UUID, T_TIMEUUID):
        return uuid.UUID(bytes=raw)
    if tid == T_DATE:
        days = struct.unpack(">I", raw)[0] - (1 << 31)
        return datetime.date(1970, 1, 1) + datetime.timedelta(days=days)
    if tid == T_TIME:
        ns = struct.unpack(">q", raw)[0]
        us, _ = divmod(ns, 1000)
        s, us = divmod(us, 10 ** 6)
        m, s = divmod(s, 60)
        h, m = divmod(m, 60)
        return datetime.time(h, m, s, us)
    if tid in (T_LIST, T_SET):
        b = _Buf(raw)
        n = b.int32()
        out = [decode_cql(params[0], b.bytes_()) for _ in range(n)]
        return set(out) if tid == T_SET and _hashable(out) else out
    if tid == T_MAP:
        b = _Buf(raw)
        n = b.int32()
        return {decode_cql(params[0], b.bytes_()):
                decode_cql(params[1], b.bytes_()) for _ in range(n)}
    if tid == T_TUPLE:
        b = _Buf(raw)
        return tuple(decode_cql(p, b.bytes_()) for p in params)
    if tid == T_UDT:
        b = _Buf(raw)
        out = {}
        for fname, fspec in params:
            if b.i >= len(b.b):
                out[fname] = None
            else:
                out[fname] = decode_cql(fspec, b.bytes_())
        return out
    return raw


def _hashable(items) -> bool:
    try:
        set(items)
        return True
    except TypeError:
        return False


class CqlResult:
    def __init__(self, kind: str, columns=None, rows=None,
                 paging_state=None, keyspace=None):
        self.kind = kind                # "rows"|"void"|"set_keyspace"|
        self.columns = columns or []    # "schema_change"
        self.rows = rows or []
        self.paging_state = paging_state
        self.keyspace = keyspace

    @property
    def has_more_pages(self) -> bool:
        return self.paging_state is not None


class Prepared:
    def __init__(self, stmt_id: bytes, bind_specs: list):
        self.stmt_id = stmt_id
        self.bind_specs = bind_specs


class CqlConnection:
    """One driver connection: OPTIONS -> STARTUP -> (auth) -> queries."""

    def __init__(self, host: str, port: int, user: str | None = None,
                 password: str | None = None, timeout: float = 10.0):
        self.sock = socket.create_connection((host, port),
                                             timeout=timeout)
        self._buf = b""
        self._stream = 0
        self._lock = threading.Lock()
        self.supported = self._handshake(user, password)

    # -- framing -------------------------------------------------------------
    def _send(self, opcode: int, body: bytes) -> int:
        self._stream = (self._stream + 1) % 32768
        hdr = _HEADER.pack(0x04, 0, self._stream, opcode, len(body))
        self.sock.sendall(hdr + body)
        return self._stream

    def _recv_frame(self):
        """Next response frame (any stream): (stream, opcode, body).
        ERROR frames are returned, not raised — callers decide."""
        while len(self._buf) < _HEADER.size:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise CqlError(0x0000, "connection closed")
            self._buf += chunk
        ver, _fl, stream, opcode, ln = _HEADER.unpack_from(self._buf)
        if ver != 0x84:
            raise CqlError(0x000A, f"bad response version {ver:#x}")
        total = _HEADER.size + ln
        while len(self._buf) < total:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise CqlError(0x0000, "connection closed")
            self._buf += chunk
        body = self._buf[_HEADER.size:total]
        self._buf = self._buf[total:]
        return stream, opcode, body

    def _recv(self, want_stream: int):
        while True:
            stream, opcode, body = self._recv_frame()
            if stream != want_stream:
                continue  # e.g. unsolicited EVENT frames
            if opcode == _OP_ERROR:
                b = _Buf(body)
                raise CqlError(b.int32(), b.string())
            return opcode, body

    def _call(self, opcode: int, body: bytes):
        with self._lock:
            return self._recv(self._send(opcode, body))

    # -- handshake -----------------------------------------------------------
    def _handshake(self, user, password) -> dict:
        op, body = self._call(_OP_OPTIONS, b"")
        supported = {}
        if op == _OP_SUPPORTED:
            b = _Buf(body)
            for _ in range(b.short()):
                key = b.string()
                supported[key] = [b.string()
                                  for _ in range(b.short())]
        startup = struct.pack(">H", 1) + _pstr("CQL_VERSION") \
            + _pstr("3.0.0")
        op, body = self._call(_OP_STARTUP, startup)
        if op == _OP_AUTHENTICATE:
            token = b"\x00" + (user or "").encode() + b"\x00" \
                + (password or "").encode()
            op, _ = self._call(_OP_AUTH_RESPONSE, _pbytes(token))
            if op != _OP_AUTH_SUCCESS:
                raise CqlError(0x0100, f"auth failed (opcode {op:#x})")
        elif op != _OP_READY:
            raise CqlError(0x000A, f"unexpected STARTUP reply {op:#x}")
        return supported

    # -- queries -------------------------------------------------------------
    @staticmethod
    def _query_params(values=None, page_size=None,
                      paging_state=None, bind_specs=None) -> bytes:
        flags = (0x01 if values else 0) | (0x04 if page_size else 0) \
            | (0x08 if paging_state else 0)
        out = struct.pack(">HB", 0x0001, flags)  # consistency ONE
        if values:
            out += struct.pack(">H", len(values))
            for i, v in enumerate(values):
                if bind_specs is not None and i < len(bind_specs):
                    out += _pbytes(encode_cql_typed(v, bind_specs[i]))
                else:
                    out += _pbytes(encode_cql(v))
        if page_size:
            out += struct.pack(">i", page_size)
        if paging_state:
            out += _pbytes(paging_state)
        return out

    def execute(self, query: str, values=None, page_size=None,
                paging_state=None) -> CqlResult:
        body = _plstr(query) + self._query_params(values, page_size,
                                                  paging_state)
        op, payload = self._call(_OP_QUERY, body)
        return self._parse_result(op, payload)

    def prepare(self, query: str) -> Prepared:
        op, payload = self._call(_OP_PREPARE, _plstr(query))
        if op != _OP_RESULT:
            raise CqlError(0x000A, f"unexpected PREPARE reply {op:#x}")
        b = _Buf(payload)
        kind = b.int32()
        if kind != _RESULT_PREPARED:
            raise CqlError(0x000A, f"unexpected result kind {kind}")
        stmt_id = b.short_bytes()
        # Bind-variable metadata (v4): flags, col count, pk count +
        # pk indices, then the (possibly global) column specs.
        flags = b.int32()
        n_cols = b.int32()
        for _ in range(b.int32()):
            b.short()  # pk index
        if flags & 0x0001:
            b.string()
            b.string()
        specs = []
        for _ in range(n_cols):
            if not flags & 0x0001:
                b.string()
                b.string()
            b.string()  # bind marker name
            specs.append(b.type_spec())
        return Prepared(stmt_id, specs)

    def execute_prepared(self, prep: Prepared, values=None,
                         page_size=None, paging_state=None) -> CqlResult:
        body = struct.pack(">H", len(prep.stmt_id)) + prep.stmt_id \
            + self._query_params(values, page_size, paging_state,
                                 bind_specs=prep.bind_specs)
        op, payload = self._call(_OP_EXECUTE, body)
        return self._parse_result(op, payload)

    def execute_prepared_many(self, prep: Prepared, values_list,
                              window: int = 128):
        """Pipelined EXECUTEs: up to `window` requests in flight on
        distinct stream ids before collecting responses — the stream
        multiplexing every stock driver does on one connection.
        Per-request errors come back in-place as CqlError values (like
        a redis pipeline), so one bad statement neither aborts the
        batch nor desyncs the connection."""
        out: list = [None] * len(values_list)
        with self._lock:
            pending: dict[int, int] = {}  # stream -> result index
            i = 0
            while i < len(values_list) or pending:
                while i < len(values_list) and len(pending) < window:
                    body = (struct.pack(">H", len(prep.stmt_id))
                            + prep.stmt_id
                            + self._query_params(
                                values_list[i],
                                bind_specs=prep.bind_specs))
                    pending[self._send(_OP_EXECUTE, body)] = i
                    i += 1
                stream, op, payload = self._recv_frame()
                j = pending.pop(stream, None)
                if j is None:
                    continue  # e.g. unsolicited EVENT frames
                if op == _OP_ERROR:
                    b = _Buf(payload)
                    out[j] = CqlError(b.int32(), b.string())
                else:
                    out[j] = self._parse_result(op, payload)
        return out

    def fetch_all(self, query: str, values=None,
                  page_size: int = 100) -> CqlResult:
        """Drain all pages (the driver-side paging loop)."""
        res = self.execute(query, values, page_size=page_size)
        rows = list(res.rows)
        while res.has_more_pages:
            res = self.execute(query, values, page_size=page_size,
                               paging_state=res.paging_state)
            rows.extend(res.rows)
        return CqlResult("rows", res.columns, rows)

    # -- control connection (stock-driver schema discovery) -----------------
    def discover(self) -> dict:
        """The handshake a DataStax driver runs after STARTUP: read
        system.local, system.peers, and the schema tables."""
        local = self.execute("SELECT * FROM system.local")
        peers = self.execute("SELECT * FROM system.peers")
        keyspaces = self.execute(
            "SELECT * FROM system_schema.keyspaces")
        tables = self.execute("SELECT * FROM system_schema.tables")
        columns = self.execute("SELECT * FROM system_schema.columns")
        types = self.execute("SELECT * FROM system_schema.types")
        local_row = dict(zip(local.columns, local.rows[0])) \
            if local.rows else {}
        schema: dict = {}
        ks_i = keyspaces.columns.index("keyspace_name")
        for r in keyspaces.rows:
            schema[r[ks_i]] = {"tables": {}, "types": {}}
        tks = tables.columns.index("keyspace_name")
        ttn = tables.columns.index("table_name")
        for r in tables.rows:
            schema.setdefault(r[tks], {"tables": {}, "types": {}})
            schema[r[tks]]["tables"][r[ttn]] = []
        cks = columns.columns.index("keyspace_name")
        ctn = columns.columns.index("table_name")
        ccn = columns.columns.index("column_name")
        for r in columns.rows:
            tbl = schema.get(r[cks], {}).get("tables", {}).get(r[ctn])
            if tbl is not None:
                tbl.append(r[ccn])
        yks = types.columns.index("keyspace_name")
        ytn = types.columns.index("type_name")
        for r in types.rows:
            schema.setdefault(r[yks], {"tables": {}, "types": {}})
            schema[r[yks]]["types"][r[ytn]] = r
        return {"local": local_row,
                "peers": [dict(zip(peers.columns, r))
                          for r in peers.rows],
                "schema": schema}

    # -- RESULT parsing ------------------------------------------------------
    @staticmethod
    def _metadata(b: _Buf):
        flags = b.int32()
        n_cols = b.int32()
        paging_state = b.bytes_() if flags & 0x0002 else None
        names, specs = [], []
        if not flags & 0x0004:  # no_metadata unset
            gks = gtb = None
            if flags & 0x0001:  # global table spec
                gks, gtb = b.string(), b.string()
            for _ in range(n_cols):
                if not flags & 0x0001:
                    b.string()
                    b.string()
                names.append(b.string())
                specs.append(b.type_spec())
        return names, specs, paging_state

    def _parse_result(self, op: int, payload: bytes) -> CqlResult:
        if op != _OP_RESULT:
            raise CqlError(0x000A, f"unexpected reply opcode {op:#x}")
        b = _Buf(payload)
        kind = b.int32()
        if kind == _RESULT_VOID:
            return CqlResult("void")
        if kind == _RESULT_SET_KEYSPACE:
            return CqlResult("set_keyspace", keyspace=b.string())
        if kind == _RESULT_SCHEMA_CHANGE:
            return CqlResult("schema_change")
        if kind == _RESULT_PREPARED:
            raise CqlError(0x000A, "PREPARED outside prepare()")
        if kind != _RESULT_ROWS:
            raise CqlError(0x000A, f"unknown result kind {kind}")
        names, specs, paging_state = self._metadata(b)
        n_rows = b.int32()
        rows = []
        for _ in range(n_rows):
            rows.append(tuple(decode_cql(spec, b.bytes_())
                              for spec in specs))
        return CqlResult("rows", names, rows, paging_state)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
