"""Per-request tracing + the /rpcz sample store.

Reference analog: src/yb/util/trace.{h,cc} — a Trace is a ring of
timestamped messages attached to the current request (TRACE("...") from
anywhere below the dispatch), dumped for slow RPCs — plus the rpcz
sampling of src/yb/server/rpcz-path-handler.cc and
src/yb/rpc/rpcz_store.cc: recent and slowest samples per method,
browsable over HTTP while the server runs.

Usage::

    with trace_request("ts.write") as t:
        ...
        TRACE("submitted to raft")      # from any frame below
        ...
    store.record(t)                      # duration + messages sampled

TRACE() is a no-op (one contextvar read) when no trace is active, so
library code can trace unconditionally.
"""

from __future__ import annotations

import contextvars
import threading
import time
from collections import deque

_current: contextvars.ContextVar["Trace | None"] = \
    contextvars.ContextVar("active_trace", default=None)

MAX_MESSAGES = 64


class Trace:
    __slots__ = ("method", "start_wall", "start", "entries", "duration_us",
                 "dropped", "_done")

    def __init__(self, method: str):
        self.method = method
        self.start_wall = time.time()
        self.start = time.monotonic()
        self.entries: list[tuple[float, str]] = []
        self.duration_us: int = 0
        self.dropped = 0
        self._done = False

    def trace(self, msg: str) -> None:
        if len(self.entries) >= MAX_MESSAGES:
            self.dropped += 1
            return
        self.entries.append((time.monotonic() - self.start, msg))

    def finish(self) -> None:
        """Idempotent: the first call fixes the duration (the sample may
        already be recorded when a later finish runs)."""
        if not self._done:
            self._done = True
            self.duration_us = int((time.monotonic() - self.start) * 1e6)

    def dump(self) -> dict:
        out = {
            "method": self.method,
            "start": self.start_wall,
            "duration_us": self.duration_us,
            "messages": [f"{dt * 1e6:8.0f}us {m}"
                         for dt, m in self.entries],
        }
        if self.dropped:
            out["dropped_messages"] = self.dropped
        return out


def TRACE(msg: str, *args) -> None:  # noqa: N802 — reference macro name
    """Append to the active request trace, if any (trace.h TRACE())."""
    t = _current.get()
    if t is not None:
        t.trace(msg % args if args else msg)


class trace_request:
    """Context manager: install a Trace as the active one for this
    (thread/context) for the duration of a request."""

    __slots__ = ("trace", "_token")

    def __init__(self, method: str):
        self.trace = Trace(method)
        self._token = None

    def __enter__(self) -> Trace:
        self._token = _current.set(self.trace)
        return self.trace

    def __exit__(self, *exc) -> None:
        _current.reset(self._token)
        self.trace.finish()
        return None


class RpczStore:
    """Recent + slowest samples per method (rpc/rpcz_store.cc)."""

    def __init__(self, recent_per_method: int = 8, slow_keep: int = 32,
                 slow_threshold_us: int = 500_000):
        self.recent_per_method = recent_per_method
        self.slow_threshold_us = slow_threshold_us
        self._recent: dict[str, deque] = {}
        self._slow: deque = deque(maxlen=slow_keep)
        self._lock = threading.Lock()

    def record(self, trace: Trace) -> None:
        with self._lock:
            dq = self._recent.get(trace.method)
            if dq is None:
                dq = self._recent[trace.method] = deque(
                    maxlen=self.recent_per_method)
            dq.append(trace)
            if trace.duration_us >= self.slow_threshold_us:
                self._slow.append(trace)
        # every sampled request is also one /tracing.json slice
        TRACE_EVENTS.record(trace.method, trace.start_wall,
                            trace.duration_us)

    def dump(self) -> dict:
        with self._lock:
            return {
                "methods": {
                    m: [t.dump() for t in dq]
                    for m, dq in sorted(self._recent.items())
                },
                "slow": [t.dump() for t in self._slow],
                "slow_threshold_us": self.slow_threshold_us,
            }


# -- chromium trace events (/tracing.json) -----------------------------------

class TraceEventLog:
    """Process-wide ring of Chromium trace-event records, browsable in
    Perfetto / chrome://tracing (reference: src/yb/util/debug/
    trace_event.h + the /tracing.json handler,
    tracing-path-handlers.cc). Complete events ("ph":"X") only — each
    traced request or explicitly marked span is one slice."""

    def __init__(self, capacity: int = 4096):
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=capacity)

    def record(self, name: str, start_wall_s: float, duration_us: int,
               tid: int | None = None, args: dict | None = None) -> None:
        ev = {"name": name, "ph": "X", "pid": 1,
              "tid": tid if tid is not None else threading.get_ident(),
              "ts": int(start_wall_s * 1e6), "dur": int(duration_us)}
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def dump(self) -> dict:
        with self._lock:
            return {"traceEvents": list(self._events),
                    "displayTimeUnit": "ms"}


TRACE_EVENTS = TraceEventLog()


class trace_event:
    """Span context manager feeding /tracing.json:

        with trace_event("compaction", tablet=tid):
            ...
    """

    def __init__(self, name: str, **args):
        self.name = name
        self.args = args or None

    def __enter__(self):
        self._wall = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        TRACE_EVENTS.record(self.name, self._wall,
                            (time.perf_counter() - self._t0) * 1e6,
                            args=self.args)
        return False


def dump_stacks() -> str:
    """All live threads' Python stacks (the pprof/stacks analog of
    src/yb/server/pprof-path-handlers.cc, for a Python runtime)."""
    import sys
    import traceback

    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {tid} ({names.get(tid, '?')}) ---")
        out.extend(line.rstrip()
                   for line in traceback.format_stack(frame))
    return "\n".join(out) + "\n"
