"""SyncPoint: deterministic cross-thread interleaving control for tests.

Reference analog: src/yb/util/sync_point.h:61 (itself from rocksdb) —
named points in production code that tests order relative to each other
(LoadDependency: point A must be REACHED before point B may proceed) or
hook with callbacks. Disabled by default: a process() call without an
enabled singleton is one predicate check.

    SYNC_POINT.load_dependency([("flush:done", "scan:start")])
    SYNC_POINT.enable()
    ... threads call sync_point("flush:done") / sync_point("scan:start")
    SYNC_POINT.disable_and_clear()
"""

from __future__ import annotations

import threading


class SyncPoint:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._enabled = False
        self._cleared: set[str] = set()
        # point -> set of predecessor points that must clear first
        self._predecessors: dict[str, set[str]] = {}
        self._callbacks: dict[str, object] = {}

    def load_dependency(self, deps: list[tuple[str, str]]) -> None:
        """deps: (before, after) pairs — ``after`` blocks until
        ``before`` has been processed (sync_point.h:58 LoadDependency)."""
        with self._lock:
            for before, after in deps:
                self._predecessors.setdefault(after, set()).add(before)

    def set_callback(self, point: str, fn) -> None:
        with self._lock:
            self._callbacks[point] = fn

    def enable(self) -> None:
        with self._lock:
            self._enabled = True

    def disable_and_clear(self) -> None:
        with self._cv:
            self._enabled = False
            self._cleared.clear()
            self._predecessors.clear()
            self._callbacks.clear()
            self._cv.notify_all()

    def process(self, point: str, arg=None,
                timeout_s: float = 10.0) -> None:
        if not self._enabled:  # racy-read fast path: off = no cost
            return
        with self._cv:
            if not self._enabled:
                return
            cb = self._callbacks.get(point)
            deadline = None
            need = self._predecessors.get(point)
            if need:
                import time

                deadline = time.monotonic() + timeout_s
                while not need <= self._cleared:
                    if not self._enabled:
                        return
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"sync point {point!r} waited for "
                            f"{sorted(need - self._cleared)}")
                    self._cv.wait(timeout=remaining)
            self._cleared.add(point)
            self._cv.notify_all()
        if cb is not None:
            cb(arg)


SYNC_POINT = SyncPoint()


def sync_point(point: str, arg=None) -> None:
    """The production-side hook (TEST_SYNC_POINT macro analog)."""
    SYNC_POINT.process(point, arg)
