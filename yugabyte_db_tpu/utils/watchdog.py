"""Standing stall detector: flags operations that exceed their
latency budget while they are still running.

Reference analog: the kernel-stack watchdog
(src/yb/util/kernel_stack_watchdog.h) — threads register each
latency-sensitive section (WAL fsync, Raft apply, engine flush); a
sampler thread flags sections still running past their threshold, so a
wedged apply/fsync surfaces as a logged stall event and a metric
instead of silent throughput loss. Sections that finish late between
samples are flagged post-hoc, so nothing escapes by racing the sampler.

Stress rigs treat the collected stall records as a standing check; the
sampler is process-wide and always on once the first section registers.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from contextlib import contextmanager

LOG = logging.getLogger("yb.watchdog")

_SAMPLE_INTERVAL_S = 0.25
_MAX_RECORDS = 256


class StallWatchdog:
    def __init__(self, interval_s: float = _SAMPLE_INTERVAL_S):
        self._interval = interval_s
        self._lock = threading.Lock()
        self._active: dict[int, tuple] = {}  # token -> record
        self._flagged: set[int] = set()
        self._records: list[dict] = []
        self._ids = itertools.count()
        self._thread: threading.Thread | None = None
        self.stall_count = 0  # lifetime total (server /metrics exports)

    # -- registration -------------------------------------------------------
    @contextmanager
    def watch(self, label: str, threshold_s: float = 1.0):
        """Wrap one latency-sensitive section. The sampler flags it if
        it is still running past threshold_s; a completion past the
        threshold that the sampler missed is flagged on exit."""
        self._ensure_thread()
        token = next(self._ids)
        start = time.monotonic()
        rec = (label, start, threshold_s, threading.current_thread().name)
        with self._lock:
            self._active[token] = rec
        try:
            yield
        finally:
            dur = time.monotonic() - start
            with self._lock:
                self._active.pop(token, None)
                flagged = token in self._flagged
                self._flagged.discard(token)
                if dur > threshold_s:
                    # Record the FINAL duration even when the sampler
                    # already flagged the in-flight section — the
                    # completed record is what duration-based standing
                    # checks assert on. One stall = one stall_count,
                    # even when both sampler and exit record it.
                    self._record_locked(label, dur, rec[3], done=True,
                                        count=not flagged)

    def _ensure_thread(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._thread = threading.Thread(
                target=self._loop, name="stall-watchdog", daemon=True)
            self._thread.start()

    # -- sampling -----------------------------------------------------------
    def _loop(self) -> None:
        while True:
            time.sleep(self._interval)
            try:
                now = time.monotonic()
                with self._lock:
                    for token, (label, start, thr, tname) in \
                            list(self._active.items()):
                        if token in self._flagged or now - start <= thr:
                            continue
                        self._flagged.add(token)
                        self._record_locked(label, now - start, tname,
                                            done=False)
            except Exception:  # the watchdog itself must never die
                LOG.exception("stall-watchdog sampler failed")

    def _record_locked(self, label: str, dur: float, tname: str,
                       done: bool, count: bool = True) -> None:
        if count:
            self.stall_count += 1
        if len(self._records) >= _MAX_RECORDS:
            del self._records[: _MAX_RECORDS // 2]
        self._records.append({"label": label, "seconds": round(dur, 3),
                              "thread": tname, "completed": done,
                              "at": time.time()})
        LOG.warning("stall: %s running %.2fs on %s%s", label, dur, tname,
                    "" if done else " (still running)")

    # -- inspection (the stress rigs' standing check) -----------------------
    def stalls(self, label_prefix: str = "") -> list[dict]:
        with self._lock:
            return [dict(r) for r in self._records
                    if r["label"].startswith(label_prefix)]

    def reset(self) -> None:
        with self._lock:
            self._records.clear()


_WATCHDOG: StallWatchdog | None = None
_WD_LOCK = threading.Lock()


def watchdog() -> StallWatchdog:
    global _WATCHDOG
    if _WATCHDOG is None:
        with _WD_LOCK:
            if _WATCHDOG is None:
                _WATCHDOG = StallWatchdog()
    return _WATCHDOG
