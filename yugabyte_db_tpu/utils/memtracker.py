"""Hierarchical memory trackers.

Reference analog: src/yb/util/mem_tracker.h — a tree of named trackers;
consumption propagates to ancestors; /memz dumps the tree; the global
memstore budget (docdb_rocksdb_util.cc:437 memory_monitor) triggers
flushes when the memtable subtree exceeds its limit.
"""

from __future__ import annotations

import threading


class MemTracker:
    def __init__(self, name: str, parent: "MemTracker | None" = None,
                 limit: int | None = None):
        self.name = name
        self.parent = parent
        self.limit = limit
        self._lock = threading.Lock()
        self._consumption = 0
        self._peak = 0
        self._children: dict[str, MemTracker] = {}
        if parent is not None:
            with parent._lock:
                parent._children[name] = self

    def child(self, name: str, limit: int | None = None) -> "MemTracker":
        # lookup-and-create under ONE lock hold: two concurrent callers
        # must get the same node, or accounting splits across duplicates
        with self._lock:
            existing = self._children.get(name)
            if existing is not None:
                return existing
            c = MemTracker(name, None, limit)
            c.parent = self
            self._children[name] = c
            return c

    def consume(self, bytes_: int) -> None:
        node = self
        while node is not None:
            with node._lock:
                node._consumption += bytes_
                if node._consumption > node._peak:
                    node._peak = node._consumption
            node = node.parent

    def release(self, bytes_: int) -> None:
        self.consume(-bytes_)

    @property
    def consumption(self) -> int:
        with self._lock:
            return self._consumption

    @property
    def peak(self) -> int:
        with self._lock:
            return self._peak

    def over_limit(self) -> bool:
        return self.limit is not None and self.consumption > self.limit

    def set_limit(self, limit: int | None) -> None:
        """Update the limit shown next to consumption in /memz dumps —
        for runtime-settable budgets (e.g. the HBM residency budget
        mirrored onto the root->device subtree)."""
        with self._lock:
            self.limit = limit

    def detach(self) -> None:
        """Remove this tracker from its parent (releasing any residual
        consumption up the tree)."""
        residual = self.consumption
        if residual:
            self.release(residual)
        if self.parent is not None:
            with self.parent._lock:
                self.parent._children.pop(self.name, None)

    def dump(self) -> dict:
        with self._lock:
            children = list(self._children.values())
            out = {"consumption": self._consumption, "peak": self._peak}
            if self.limit is not None:
                out["limit"] = self.limit
        if children:
            out["children"] = {c.name: c.dump() for c in children}
        return out


_root = MemTracker("root")


def root_tracker() -> MemTracker:
    return _root
