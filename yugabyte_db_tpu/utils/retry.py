"""Unified retry/deadline policy for every blocking RPC loop.

Reference analog: src/yb/rpc/rpc.h (RpcRetrier: exponential backoff with
jitter budgeted against the call's deadline) and the TabletInvoker /
MetaCache retry discipline (src/yb/client/tablet_rpc.cc) — every retry
loop in the reference debits ONE propagated deadline, classifies the
failure by Status code, and backs off with jitter so a thundering herd
of retries cannot synchronize.

Two primitives:

- ``Deadline``: an absolute point on the monotonic clock. Created once
  at the RPC edge, passed down through every layer, and debited by each
  attempt — a callee never waits past the caller's budget
  (``deadline.timeout(cap)`` caps a per-attempt transport timeout at
  the remaining budget).

- ``RetryPolicy``: backoff shape + retriable-code classification. The
  ``attempts()`` iterator drives a retry loop: it yields numbered
  ``Attempt``s, sleeps the (jittered, exponentially growing) backoff
  between them, and stops when the deadline or attempt budget is
  exhausted — the loop body only decides success / retriable / terminal.

    policy = RetryPolicy(timeout_s=10.0)
    for attempt in policy.attempts():
        try:
            resp = transport.send(dst, m, p, timeout=attempt.timeout(2.0))
        except TransportError as e:
            attempt.note(e)
            continue
        if policy.retriable(resp.get("code")):
            attempt.note(resp)
            continue
        return resp
    raise Unavailable(...)   # attempts exhausted
"""

from __future__ import annotations

import random
import time

from yugabyte_db_tpu.utils.status import Code, Status, StatusError

# Codes a retry can plausibly outwait: transient transport/availability
# failures and leadership churn. Everything else (corruption, invalid
# argument, txn conflicts/aborts...) is terminal — retrying cannot
# change the outcome. EXPIRED is deliberately absent: it means the
# operation's own deadline passed, the one budget retries debit.
RETRIABLE_CODES = frozenset({
    Code.TIMED_OUT,
    Code.SERVICE_UNAVAILABLE,
    Code.NETWORK_ERROR,
    Code.TRY_AGAIN,
    Code.LEADER_NOT_READY,
    Code.LEADER_HAS_NO_LEASE,
})

# String response codes (the RPC payload convention) a retry can
# outwait; mirrors RETRIABLE_CODES for dict-shaped responses.
RETRIABLE_WIRE_CODES = frozenset({
    "timed_out", "not_leader", "service_unavailable", "try_again",
    "leader_not_ready", "network_error", "not_found",
})


class DeadlineExpired(StatusError):
    """The propagated budget ran out (Code.TIMED_OUT on the wire)."""

    def __init__(self, message: str):
        super().__init__(Status(Code.TIMED_OUT, message))


class Deadline:
    """An absolute expiry on the monotonic clock, passed down the call
    chain so every layer debits the same budget."""

    __slots__ = ("expires_at",)

    def __init__(self, expires_at: float):
        self.expires_at = expires_at

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        return cls(time.monotonic() + seconds)

    @classmethod
    def infinite(cls) -> "Deadline":
        return cls(float("inf"))

    def remaining(self) -> float:
        return self.expires_at - time.monotonic()

    def expired(self) -> bool:
        return self.remaining() <= 0

    def timeout(self, cap: float | None = None) -> float:
        """Per-attempt wait budget: the remaining deadline, capped at
        ``cap`` (floored at 0 — a caller passing this to a transport
        gets an immediate timeout rather than a negative wait)."""
        rem = max(0.0, self.remaining())
        if cap is None or self.expires_at == float("inf"):
            return cap if cap is not None else rem
        return min(cap, rem)

    def check(self, what: str = "operation") -> None:
        """Raise DeadlineExpired if the budget ran out."""
        if self.expired():
            raise DeadlineExpired(f"{what}: deadline expired")

    def __repr__(self) -> str:
        return f"Deadline(remaining={self.remaining():.3f}s)"


class Attempt:
    """One iteration of a retry loop: its ordinal, the shared deadline,
    and the last failure noted (for the exhaustion error message)."""

    __slots__ = ("number", "deadline", "last")

    def __init__(self, number: int, deadline: Deadline):
        self.number = number
        self.deadline = deadline
        self.last = None

    def timeout(self, cap: float | None = None) -> float:
        return self.deadline.timeout(cap)

    def note(self, failure: object) -> None:
        """Record why this attempt failed (carried to the next attempt
        and surfaced when the policy gives up)."""
        self.last = failure


class RetryPolicy:
    """Exponential backoff with jitter, budgeted against one deadline.

    ``timeout_s`` is the overall budget when the caller doesn't pass an
    explicit Deadline; ``max_attempts=None`` means deadline-bounded
    only. The jitter factor spreads each backoff uniformly over
    ``[base*(1-jitter), base*(1+jitter)]`` so synchronized retries
    de-correlate (the reference's RandomizedNumber in RpcRetrier)."""

    def __init__(self, *, timeout_s: float | None = None,
                 max_attempts: int | None = None,
                 initial_backoff_s: float = 0.02,
                 max_backoff_s: float = 1.0,
                 multiplier: float = 2.0,
                 jitter: float = 0.25,
                 retriable_codes: frozenset = RETRIABLE_CODES,
                 retriable_wire_codes: frozenset = RETRIABLE_WIRE_CODES,
                 rng: random.Random | None = None,
                 sleep=time.sleep):
        if timeout_s is None and max_attempts is None:
            raise ValueError("RetryPolicy needs timeout_s or max_attempts "
                             "(an unbounded retry loop is the bug this "
                             "class exists to prevent)")
        self.timeout_s = timeout_s
        self.max_attempts = max_attempts
        self.initial_backoff_s = initial_backoff_s
        self.max_backoff_s = max_backoff_s
        self.multiplier = multiplier
        self.jitter = jitter
        self.retriable_codes = retriable_codes
        self.retriable_wire_codes = retriable_wire_codes
        self._rng = rng or random.Random()
        self._sleep = sleep

    # -- classification ------------------------------------------------------
    def retriable(self, failure: object) -> bool:
        """Is this failure worth another attempt? Accepts a Status, a
        Code, a wire code string, an exception, or a response dict with
        a ``code`` key."""
        if failure is None:
            return False
        if isinstance(failure, Status):
            return failure.code in self.retriable_codes
        if isinstance(failure, Code):
            return failure in self.retriable_codes
        if isinstance(failure, str):
            return failure in self.retriable_wire_codes
        if isinstance(failure, dict):
            return self.retriable(failure.get("code"))
        if isinstance(failure, StatusError):
            return failure.status.code in self.retriable_codes
        if isinstance(failure, (TimeoutError, ConnectionError)):
            return True
        return False

    # -- the retry loop driver -----------------------------------------------
    def backoff_s(self, attempt_number: int) -> float:
        """Jittered backoff before attempt ``attempt_number + 1``."""
        base = min(self.max_backoff_s,
                   self.initial_backoff_s
                   * (self.multiplier ** (attempt_number - 1)))
        lo = base * (1.0 - self.jitter)
        hi = base * (1.0 + self.jitter)
        return self._rng.uniform(lo, hi)

    def attempts(self, deadline: Deadline | None = None,
                 timeout_s: float | None = None):
        """Yield ``Attempt``s until the deadline or attempt budget is
        exhausted, sleeping the jittered backoff between yields (never
        past the deadline). The caller returns on success; falling out
        of the loop means the policy gave up."""
        if deadline is None:
            budget = timeout_s if timeout_s is not None else self.timeout_s
            deadline = (Deadline.after(budget) if budget is not None
                        else Deadline.infinite())
        attempt = Attempt(0, deadline)
        while True:
            attempt = Attempt(attempt.number + 1, deadline)
            yield attempt
            if (self.max_attempts is not None
                    and attempt.number >= self.max_attempts):
                return
            pause = self.backoff_s(attempt.number)
            rem = deadline.remaining()
            if rem <= 0:
                return
            self._sleep(min(pause, rem))
            if deadline.expired():
                return

    def call(self, fn, *, deadline: Deadline | None = None,
             timeout_s: float | None = None, describe: str = "rpc"):
        """Run ``fn(attempt)`` until it succeeds or the budget runs out.
        A retriable exception (per ``retriable()``) triggers backoff;
        anything else propagates immediately. Exhaustion re-raises the
        last failure (or DeadlineExpired if nothing ever ran)."""
        last_exc: Exception | None = None
        for attempt in self.attempts(deadline=deadline, timeout_s=timeout_s):
            try:
                return fn(attempt)
            except Exception as e:  # noqa: BLE001 — classified below
                if not self.retriable(e):
                    raise
                last_exc = e
        if last_exc is not None:
            raise last_exc
        raise DeadlineExpired(f"{describe}: no attempt fit the deadline")


# Default policies for the common call shapes; callers with different
# budgets construct their own.
DEFAULT_RPC_POLICY = RetryPolicy(timeout_s=10.0)
