"""Metrics: counters, gauges, histograms + Prometheus text exposition.

Reference analog: src/yb/util/metrics.h — MetricRegistry/MetricEntity
with METRIC_DEFINE_* metrics attached to entities (server, tablet), HDR
histograms for latencies, and the PrometheusWriter (metrics.h:584) that
renders the registry for scraping.

Shapes:
- Counter: monotonically increasing int.
- Gauge: set() directly, or constructed with a callback sampled at
  scrape time (how per-tablet row counts surface without bookkeeping).
- Histogram: exponential buckets (powers of 2 in microseconds by
  default) with count/sum — the Prometheus histogram contract; covers
  the reference's HDR-histogram latency use.

Entities carry label sets (e.g. tablet_id); the registry renders
everything in one pass, grouping series by metric name.
"""

from __future__ import annotations

import logging
import threading
import time
import weakref


class Counter:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0
        self._lock = threading.Lock()

    def increment(self, by: int = 1) -> None:
        with self._lock:
            self.value += by

    def get(self) -> int:
        return self.value


class Gauge:
    __slots__ = ("_value", "_fn")

    def __init__(self, fn=None):
        self._value = 0
        self._fn = fn

    def set(self, v) -> None:
        self._value = v

    def get(self):
        if self._fn is not None:
            try:
                return self._fn()
            except Exception:  # noqa: BLE001 — scrape must not die
                return 0
        return self._value


# Exponential bucket bounds (microseconds): 64us .. ~67s
DEFAULT_BUCKETS = tuple(64 * (2 ** i) for i in range(21))


class Histogram:
    __slots__ = ("buckets", "counts", "count", "sum", "_lock")

    def __init__(self, buckets=DEFAULT_BUCKETS):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)
        self.count = 0
        self.sum = 0
        self._lock = threading.Lock()

    def observe(self, v) -> None:
        i = 0
        for i, b in enumerate(self.buckets):
            if v <= b:
                break
        else:
            i = len(self.buckets)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.sum += v

    def observe_duration_us(self, start_monotonic: float) -> None:
        self.observe(int((time.monotonic() - start_monotonic) * 1e6))

    def percentile(self, p: float):
        """Approximate percentile from bucket upper bounds."""
        with self._lock:
            if self.count == 0:
                return 0
            target = self.count * p
            acc = 0
            for i, n in enumerate(self.counts):
                acc += n
                if acc >= target:
                    return (self.buckets[i] if i < len(self.buckets)
                            else self.buckets[-1])
            return self.buckets[-1]


class MetricEntity:
    """One labeled owner of metrics (server / tablet / table)."""

    def __init__(self, registry: "MetricRegistry", labels: dict):
        self.registry = registry
        self.labels = dict(labels)
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str, fn=None) -> Gauge:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Gauge(fn)
            elif fn is not None:
                m._fn = fn
            return m

    def histogram(self, name: str, buckets=None) -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = (
                    Histogram(buckets) if buckets is not None
                    else Histogram())
            return m

    def _get(self, name, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls()
            return m


class MetricRegistry:
    """All of one process's metrics; renders Prometheus text."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entities: list[MetricEntity] = []
        self._collectors: list = []  # callables refreshing gauges pre-scrape

    def entity(self, **labels) -> MetricEntity:
        e = MetricEntity(self, labels)
        with self._lock:
            self._entities.append(e)
        return e

    def remove_entity(self, entity: MetricEntity) -> None:
        with self._lock:
            try:
                self._entities.remove(entity)
            except ValueError:
                pass

    def add_collector(self, fn) -> None:
        """fn() runs before each scrape (register/refresh dynamic
        entities, e.g. per-tablet gauges after tablets move)."""
        with self._lock:
            self._collectors.append(fn)

    def prometheus_text(self) -> str:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — scrape must not die
                count_swallowed("metrics.collector", e)
        with self._lock:
            entities = list(self._entities)
        by_name: dict[str, list] = {}
        for e in entities:
            with e._lock:
                metrics = dict(e._metrics)
            for name, m in metrics.items():
                by_name.setdefault(name, []).append((e.labels, m))
        out = []
        for name in sorted(by_name):
            series = by_name[name]
            kind = ("counter" if isinstance(series[0][1], Counter)
                    else "histogram" if isinstance(series[0][1], Histogram)
                    else "gauge")
            out.append(f"# TYPE {name} {kind}")
            for labels, m in series:
                ls = _labels(labels)
                if isinstance(m, Histogram):
                    with m._lock:
                        counts = list(m.counts)
                        total, s = m.count, m.sum
                    acc = 0
                    for i, b in enumerate(m.buckets):
                        acc += counts[i]
                        out.append(
                            f"{name}_bucket{_labels(labels, le=b)} {acc}")
                    out.append(
                        f'{name}_bucket{_labels(labels, le="+Inf")} {total}')
                    out.append(f"{name}_sum{ls} {s}")
                    out.append(f"{name}_count{ls} {total}")
                else:
                    out.append(f"{name}{ls} {m.get()}")
        return "\n".join(out) + "\n"


def _labels(labels: dict, **extra) -> str:
    items = {**labels, **extra}
    if not items:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(items.items()))
    return "{" + inner + "}"


# -- process-wide registry + swallowed-error accounting ----------------------
# Daemons construct their own registries for per-server metrics; this one
# exists so cross-cutting health counters (swallowed errors, scrape
# failures) have a home regardless of which daemon — or no daemon — is
# running in the process.
_PROCESS_REGISTRY = MetricRegistry()
_SWALLOW_LOG = logging.getLogger("yugabyte_db_tpu.swallowed")
_SWALLOW_ENTITIES: dict[str, MetricEntity] = {}
_SWALLOW_LOCK = threading.Lock()


def process_registry() -> MetricRegistry:
    return _PROCESS_REGISTRY


def count_swallowed(site: str, exc: object = None) -> None:
    """Record a deliberately-swallowed exception: bump
    ``yb_swallowed_errors{site=...}`` on the process registry and leave a
    debug-level trace. For best-effort paths (retry loops, shutdown,
    scrapes) where the except block would otherwise discard the error
    invisibly — the counter makes a noisy failure site show up on a
    dashboard even when nobody has debug logging on. Never raises."""
    try:
        with _SWALLOW_LOCK:
            ent = _SWALLOW_ENTITIES.get(site)
            if ent is None:
                ent = _PROCESS_REGISTRY.entity(site=site)
                _SWALLOW_ENTITIES[site] = ent
        ent.counter("yb_swallowed_errors").increment()
        _SWALLOW_LOG.debug("swallowed at %s: %r", site, exc)
    except Exception:  # noqa: BLE001 — error accounting must not throw
        _SWALLOW_LOG.debug("count_swallowed failed at site %s", site)


# -- fault-injection observability -------------------------------------------
_FAULT_ENTITIES: dict[str, MetricEntity] = {}
_FAULT_LOCK = threading.Lock()


def count_fault_fired(name: str) -> None:
    """Bump ``yb_faults_fired{name=...}`` on the process registry: one
    series per fault point, incremented every time the fault actually
    fires. The fault-sweep harness asserts its injection schedule
    against this counter, so a fault point that silently stops being
    evaluated shows up as a sweep failure. Never raises."""
    try:
        with _FAULT_LOCK:
            ent = _FAULT_ENTITIES.get(name)
            if ent is None:
                ent = _PROCESS_REGISTRY.entity(name=name)
                _FAULT_ENTITIES[name] = ent
        ent.counter("yb_faults_fired").increment()
    except Exception:  # noqa: BLE001 — accounting must not throw
        _SWALLOW_LOG.debug("count_fault_fired failed for %s", name)


def faults_fired(name: str) -> int:
    """Current ``yb_faults_fired{name=...}`` value (0 if never fired)."""
    with _FAULT_LOCK:
        ent = _FAULT_ENTITIES.get(name)
    return ent.counter("yb_faults_fired").get() if ent is not None else 0


# -- compile-discipline observability -----------------------------------------
_JIT_ENTITIES: dict[str, MetricEntity] = {}
_JIT_LOCK = threading.Lock()


def count_jit_compile(entry: str, n: int = 1) -> None:
    """Bump ``yb_jit_compiles{entry=...}`` on the process registry: one
    series per @compile_contract entry point (utils/jitting.py),
    incremented on every actual XLA trace/compile event. Steady-state
    growth of any series is a retrace bug — bench rounds snapshot these
    counters around the measured loop to prove zero recompiles on hot
    scan/aggregate keys. Never raises."""
    try:
        with _JIT_LOCK:
            ent = _JIT_ENTITIES.get(entry)
            if ent is None:
                ent = _PROCESS_REGISTRY.entity(entry=entry)
                _JIT_ENTITIES[entry] = ent
        ent.counter("yb_jit_compiles").increment(n)
    except Exception:  # noqa: BLE001 — accounting must not throw
        _SWALLOW_LOG.debug("count_jit_compile failed for %s", entry)


def jit_compiles(entry: str | None = None):
    """Current ``yb_jit_compiles`` value for one entry (0 if never
    compiled), or the full {entry: count} snapshot when ``entry`` is
    None."""
    with _JIT_LOCK:
        ents = dict(_JIT_ENTITIES)
    if entry is not None:
        ent = ents.get(entry)
        return ent.counter("yb_jit_compiles").get() if ent else 0
    return {e: ent.counter("yb_jit_compiles").get()
            for e, ent in sorted(ents.items())}


# -- serving-path observability ----------------------------------------------
# Batch-size bucket bounds (ops per drained request batch): 1 .. 4096.
BATCH_SIZE_BUCKETS = tuple(2 ** i for i in range(13))

_SERVE_ENTITIES: dict[str, MetricEntity] = {}
_SERVE_LOCK = threading.Lock()


def observe_serve_batch(proto: str, ops: int) -> None:
    """Record one request batch entering a serving path: bump the
    per-protocol batch-size histogram ``yb_serve_batch_ops{proto=...}``
    on the process registry. The distribution answers the question the
    native request-batch path (docs/serving-path.md) lives on: are
    clients actually pipelining, and how much per-batch work does one
    native call amortize? Never raises."""
    try:
        with _SERVE_LOCK:
            ent = _SERVE_ENTITIES.get(proto)
            if ent is None:
                ent = _PROCESS_REGISTRY.entity(proto=proto)
                _SERVE_ENTITIES[proto] = ent
        ent.histogram("yb_serve_batch_ops",
                      buckets=BATCH_SIZE_BUCKETS).observe(ops)
    except Exception:  # noqa: BLE001 — accounting must not throw
        _SWALLOW_LOG.debug("observe_serve_batch failed for %s", proto)


# -- HBM residency-cache observability ----------------------------------------
_HBM_ENTITY: MetricEntity | None = None
_HBM_DEVICE_ENTITIES: dict[str, MetricEntity] = {}


def hbm_cache_entity() -> MetricEntity:
    """The process-registry entity carrying the HBM residency-cache
    series (``yb_hbm_cache_hits``/``misses``/``evictions``,
    ``yb_hbm_demand_upload_bytes``, ``yb_hbm_resident_bytes``) — same
    pattern as ``yb_serve_batch_ops``: the cache is process-wide, so its
    series render on every daemon's /metrics scrape."""
    global _HBM_ENTITY
    with _SERVE_LOCK:
        if _HBM_ENTITY is None:
            _HBM_ENTITY = _PROCESS_REGISTRY.entity()
        return _HBM_ENTITY


def hbm_device_entity(device: str) -> MetricEntity:
    """Per-device HBM residency series: one ``{device=...}``-labeled
    entity per mesh device, carrying
    ``yb_hbm_resident_bytes{device=...}`` and
    ``yb_hbm_demand_upload_bytes{device=...}``.  The unlabeled totals on
    :func:`hbm_cache_entity` stay — both render under the same metric
    name, the labeled series break the totals down by chip."""
    with _SERVE_LOCK:
        ent = _HBM_DEVICE_ENTITIES.get(device)
        if ent is None:
            ent = _PROCESS_REGISTRY.entity(device=device)
            _HBM_DEVICE_ENTITIES[device] = ent
        return ent


_HOST_VERIFY_ENTITY: MetricEntity | None = None


# -- resource-witness observability -------------------------------------------
# Lock-hold duration bucket bounds (seconds): 1us .. ~4.2s, powers of 4.
LOCK_HOLD_S_BUCKETS = tuple(1e-6 * (4 ** i) for i in range(12))

_LOCK_HOLD_ENTITIES: dict[str, MetricEntity] = {}
_RESOURCE_WITNESS_ENTITY: MetricEntity | None = None


def observe_lock_hold_s(cls: str, seconds: float) -> None:
    """Record one lock hold interval (acquire -> release by one thread)
    into the per-owner-class histogram ``yb_lock_hold_seconds{cls=...}``
    on the process registry. Fed by the resource witness
    (utils/resources.py, ``--pin_witness``); the p99 of this series is
    the iholds/ story told live — a lock held across fsync/RPC shows up
    as a fat tail on its class. Never raises."""
    try:
        with _SERVE_LOCK:
            ent = _LOCK_HOLD_ENTITIES.get(cls)
            if ent is None:
                ent = _PROCESS_REGISTRY.entity(cls=cls)
                _LOCK_HOLD_ENTITIES[cls] = ent
        ent.histogram("yb_lock_hold_seconds",
                      buckets=LOCK_HOLD_S_BUCKETS).observe(seconds)
    except Exception:  # noqa: BLE001 — accounting must not throw
        _SWALLOW_LOG.debug("observe_lock_hold_s failed for %s", cls)


def resource_witness_entity() -> MetricEntity:
    """The process-registry entity carrying the resource-witness
    counters (``yb_resource_pin_acquires`` / ``yb_resource_pin_releases``
    / ``yb_resource_holds_across_blocking``) — process-wide, so the
    series render on every daemon's /metrics scrape."""
    global _RESOURCE_WITNESS_ENTITY
    with _SERVE_LOCK:
        if _RESOURCE_WITNESS_ENTITY is None:
            _RESOURCE_WITNESS_ENTITY = _PROCESS_REGISTRY.entity()
        return _RESOURCE_WITNESS_ENTITY


# -- write-path observability --------------------------------------------------
# WAL sync latency bucket bounds (milliseconds): 1/16 ms .. ~32 s.
WAL_SYNC_MS_BUCKETS = tuple(0.0625 * (2 ** i) for i in range(20))

_WRITE_PATH_ENTITY: MetricEntity | None = None
_FLUSH_PATH_ENTITIES: dict[str, MetricEntity] = {}


def _write_path_entity() -> MetricEntity:
    global _WRITE_PATH_ENTITY
    with _SERVE_LOCK:
        if _WRITE_PATH_ENTITY is None:
            _WRITE_PATH_ENTITY = _PROCESS_REGISTRY.entity()
        return _WRITE_PATH_ENTITY


def observe_group_commit_batch(entries: int) -> None:
    """Record one leader-side group-commit round: bump the
    ``yb_group_commit_batch_size`` histogram with the number of Raft
    entries coalesced into this WAL sync + AppendEntries window. A p50
    stuck at 1 means concurrent writers are not actually sharing
    replication rounds. Never raises."""
    try:
        _write_path_entity().histogram(
            "yb_group_commit_batch_size",
            buckets=BATCH_SIZE_BUCKETS).observe(entries)
    except Exception:  # noqa: BLE001 — accounting must not throw
        _SWALLOW_LOG.debug("observe_group_commit_batch failed")


def observe_wal_sync_ms(ms: float) -> None:
    """Record one WAL group-commit sync duration (flush + fsync) on the
    ``yb_wal_sync_ms`` histogram. Never raises."""
    try:
        _write_path_entity().histogram(
            "yb_wal_sync_ms", buckets=WAL_SYNC_MS_BUCKETS).observe(ms)
    except Exception:  # noqa: BLE001 — accounting must not throw
        _SWALLOW_LOG.debug("observe_wal_sync_ms failed")


def count_flush_path(path: str) -> None:
    """Bump ``yb_flush_device{path=device|host}``: which build path a
    memtable flush took. ``device`` = the op log replayed into columnar
    planes with the sort permutation applied on-device (ops/flush.py);
    ``host`` = the numpy/native fallback. Never raises."""
    try:
        with _SERVE_LOCK:
            ent = _FLUSH_PATH_ENTITIES.get(path)
            if ent is None:
                ent = _PROCESS_REGISTRY.entity(path=path)
                _FLUSH_PATH_ENTITIES[path] = ent
        ent.counter("yb_flush_device").increment()
    except Exception:  # noqa: BLE001 — accounting must not throw
        _SWALLOW_LOG.debug("count_flush_path failed for %s", path)


def flush_path_count(path: str) -> int:
    """Current ``yb_flush_device{path=...}`` value (0 if never bumped)."""
    with _SERVE_LOCK:
        ent = _FLUSH_PATH_ENTITIES.get(path)
    return ent.counter("yb_flush_device").get() if ent is not None else 0


def group_commit_percentile(p: float):
    """Approximate percentile of ``yb_group_commit_batch_size`` (0 when
    no group-commit round has been recorded) — bench/test introspection."""
    h = _write_path_entity().histogram("yb_group_commit_batch_size",
                                       buckets=BATCH_SIZE_BUCKETS)
    return h.percentile(p)


# -- plane-encoding observability ---------------------------------------------
# Compressed-plane accounting (--tpu_plane_encoding): engines register
# themselves as providers; the gauges below sample them at scrape time,
# so a closed/collected engine silently drops out (weakrefs, no
# unregister call needed). Label values cover every encoding leaf kind
# the columnar encoder can emit plus "plain" for unencoded planes.
PLANE_ENCODINGS = ("plain", "bits", "const", "delta16", "rle", "dict")

_PLANE_LOCK = threading.Lock()
_PLANE_PROVIDERS: dict[int, weakref.ref] = {}
_PLANE_ENTITIES: dict[str, MetricEntity] = {}
_PLANE_RATIO_ENTITY: MetricEntity | None = None


def register_plane_stats(provider) -> None:
    """Register an engine-like ``provider`` whose ``plane_stats()``
    returns ``{"tablet": str, "by_encoding": {kind: bytes},
    "encoded_bytes": int, "logical_bytes": int}`` for its current run
    set. First registration lazily creates the process-registry series
    ``yb_plane_bytes{encoding=...}`` (stored bytes per plane encoding)
    and ``yb_plane_encoded_ratio`` (stored / logical across all
    providers; 1.0 when nothing is encoded). Never raises."""
    global _PLANE_RATIO_ENTITY
    try:
        with _PLANE_LOCK:
            _PLANE_PROVIDERS[id(provider)] = weakref.ref(provider)
            if _PLANE_RATIO_ENTITY is None:
                for k in PLANE_ENCODINGS:
                    ent = _PROCESS_REGISTRY.entity(encoding=k)
                    _PLANE_ENTITIES[k] = ent
                    ent.gauge("yb_plane_bytes",
                              fn=lambda k=k: plane_stats_snapshot()
                              ["by_encoding"].get(k, 0))
                _PLANE_RATIO_ENTITY = _PROCESS_REGISTRY.entity()
                _PLANE_RATIO_ENTITY.gauge(
                    "yb_plane_encoded_ratio",
                    fn=lambda: plane_stats_snapshot()["encoded_ratio"])
    except Exception:  # noqa: BLE001 — accounting must not throw
        _SWALLOW_LOG.debug("register_plane_stats failed")


def plane_stats_snapshot() -> dict:
    """Aggregate plane-encoding stats over every live provider:
    ``{"tablets": [per-provider dicts], "by_encoding": {kind: bytes},
    "encoded_bytes", "logical_bytes", "encoded_ratio"}``. The ratio is
    stored-over-logical bytes (< 1.0 means compression is winning)."""
    with _PLANE_LOCK:
        refs = list(_PLANE_PROVIDERS.items())
    tablets = []
    by: dict[str, int] = {}
    for pid, ref in refs:
        p = ref()
        if p is None:
            with _PLANE_LOCK:
                _PLANE_PROVIDERS.pop(pid, None)
            continue
        try:
            st = p.plane_stats()
        except Exception:  # noqa: BLE001 — scrape must not die
            count_swallowed("metrics.plane_stats")
            continue
        tablets.append(st)
        for k, v in st.get("by_encoding", {}).items():
            by[k] = by.get(k, 0) + int(v)
    encoded = sum(by.values())
    logical = sum(int(t.get("logical_bytes", 0)) for t in tablets)
    return {"tablets": tablets, "by_encoding": by,
            "encoded_bytes": encoded, "logical_bytes": logical,
            "encoded_ratio": (encoded / logical) if logical else 1.0}


def count_host_verify_rows(n: int) -> None:
    """Bump ``yb_scan_host_verify_rows`` by the number of fetched rows
    the host re-verified after a device scan. The device predicate mask
    for string columns is a conservative SUPERSET (ops/scan.py: ``!=``
    on strings stays all-true), so every masked row crosses back for
    host-side verification — this counter makes that silent cliff
    measurable. Never raises."""
    global _HOST_VERIFY_ENTITY
    try:
        with _SERVE_LOCK:
            if _HOST_VERIFY_ENTITY is None:
                _HOST_VERIFY_ENTITY = _PROCESS_REGISTRY.entity()
        _HOST_VERIFY_ENTITY.counter("yb_scan_host_verify_rows").increment(n)
    except Exception:  # noqa: BLE001 — accounting must not throw
        _SWALLOW_LOG.debug("count_host_verify_rows failed")


# -- cluster-elasticity observability -----------------------------------------
# Splits and leader moves are rare, cluster-shaping events: both get
# process-wide counters the master bumps as each operation COMMITS (a
# dispatched-but-failed split does not count), and the traffic-sweep
# harness asserts its own ledger against them exactly like the fault
# sweep does against yb_faults_fired.
_ELASTICITY_ENTITY: MetricEntity | None = None
_REQ_LATENCY_ENTITIES: dict[str, MetricEntity] = {}

# Request latencies are client-observed seconds: sub-ms point ops up
# through multi-second split-stall retries must all land in-range.
REQUEST_LATENCY_S_BUCKETS = tuple(1e-5 * (2 ** i) for i in range(22))


def _elasticity_entity() -> MetricEntity:
    global _ELASTICITY_ENTITY
    with _SERVE_LOCK:
        if _ELASTICITY_ENTITY is None:
            _ELASTICITY_ENTITY = _PROCESS_REGISTRY.entity()
        return _ELASTICITY_ENTITY


def count_tablet_split() -> None:
    """Bump ``yb_tablet_splits_total``: one committed tablet split
    (parent swapped for both children in the catalog). Never raises."""
    try:
        _elasticity_entity().counter("yb_tablet_splits_total").increment()
    except Exception:  # noqa: BLE001 — accounting must not throw
        _SWALLOW_LOG.debug("count_tablet_split failed")


def tablet_splits_total() -> int:
    """Current ``yb_tablet_splits_total`` value (0 if none committed)."""
    return _elasticity_entity().counter("yb_tablet_splits_total").get()


def count_leader_move() -> None:
    """Bump ``yb_leader_moves_total``: one leader-balancer stepdown
    actually issued to a tserver. Never raises."""
    try:
        _elasticity_entity().counter("yb_leader_moves_total").increment()
    except Exception:  # noqa: BLE001 — accounting must not throw
        _SWALLOW_LOG.debug("count_leader_move failed")


def leader_moves_total() -> int:
    """Current ``yb_leader_moves_total`` value (0 if none issued)."""
    return _elasticity_entity().counter("yb_leader_moves_total").get()


def observe_request_latency(proto: str, seconds: float) -> None:
    """Record one client-observed request latency into the
    per-protocol histogram ``yb_request_latency_seconds{proto=...}``
    on the process registry. The traffic sweep feeds this from every
    op it issues (ycsb_a/ycsb_b/ycsb_e/tpch/redis) and asserts its
    per-protocol p99 SLOs against the same series a dashboard scrape
    sees. Never raises."""
    try:
        with _SERVE_LOCK:
            ent = _REQ_LATENCY_ENTITIES.get(proto)
            if ent is None:
                ent = _PROCESS_REGISTRY.entity(proto=proto)
                _REQ_LATENCY_ENTITIES[proto] = ent
        ent.histogram("yb_request_latency_seconds",
                      buckets=REQUEST_LATENCY_S_BUCKETS).observe(seconds)
    except Exception:  # noqa: BLE001 — accounting must not throw
        _SWALLOW_LOG.debug("observe_request_latency failed for %s", proto)


def request_latency_percentile(proto: str, p: float):
    """Approximate percentile (seconds) of one protocol's
    ``yb_request_latency_seconds`` series; 0 when nothing observed."""
    with _SERVE_LOCK:
        ent = _REQ_LATENCY_ENTITIES.get(proto)
    if ent is None:
        return 0
    return ent.histogram("yb_request_latency_seconds",
                         buckets=REQUEST_LATENCY_S_BUCKETS).percentile(p)
