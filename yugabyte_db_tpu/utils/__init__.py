"""Base libraries: status/result, hybrid time, byte-comparable encoding, planes.

Reference analog: src/yb/util (Status/Result, hybrid time helpers,
memcmpable_varint.cc) and src/yb/gutil.
"""

from yugabyte_db_tpu.utils.status import Status, StatusError, ok, not_found, invalid_argument
from yugabyte_db_tpu.utils.hybrid_time import HybridTime, HybridClock, LogicalClock
