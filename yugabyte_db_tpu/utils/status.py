"""Status / Result error-propagation primitives.

Reference analog: src/yb/util/status.h and src/yb/util/result.h. The reference
threads ``Status``/``Result<T>`` through every layer instead of exceptions; in
Python we keep a ``Status`` value type for RPC/wire surfaces (protocol error
frames need structured codes) and a ``StatusError`` exception carrying one for
in-process propagation.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field


class Code(enum.IntEnum):
    OK = 0
    NOT_FOUND = 1
    CORRUPTION = 2
    NOT_SUPPORTED = 3
    INVALID_ARGUMENT = 4
    IO_ERROR = 5
    ALREADY_PRESENT = 6
    RUNTIME_ERROR = 7
    NETWORK_ERROR = 8
    ILLEGAL_STATE = 9
    NOT_AUTHORIZED = 10
    ABORTED = 11
    REMOTE_ERROR = 12
    SERVICE_UNAVAILABLE = 13
    TIMED_OUT = 14
    UNINITIALIZED = 15
    CONFIGURATION_ERROR = 16
    INCOMPLETE = 17
    END_OF_FILE = 18
    INTERNAL_ERROR = 19
    EXPIRED = 20
    LEADER_NOT_READY = 21
    LEADER_HAS_NO_LEASE = 22
    TRY_AGAIN = 23
    QL_ERROR = 24


@dataclass(frozen=True)
class Status:
    code: Code = Code.OK
    message: str = ""
    # Optional structured payload (e.g. CQL error code) for the wire protocols.
    payload: dict = field(default_factory=dict)

    @property
    def is_ok(self) -> bool:
        return self.code == Code.OK

    def __bool__(self) -> bool:
        return self.is_ok

    def __str__(self) -> str:
        if self.is_ok:
            return "OK"
        return f"{self.code.name}: {self.message}"

    def raise_if_error(self) -> "Status":
        if not self.is_ok:
            raise StatusError(self)
        return self


class StatusError(Exception):
    """Exception carrying a Status, for in-process error propagation."""

    def __init__(self, status: Status):
        super().__init__(str(status))
        self.status = status


class InvalidArgument(StatusError):
    def __init__(self, message: str):
        super().__init__(Status(Code.INVALID_ARGUMENT, message))


class NotFound(StatusError):
    def __init__(self, message: str):
        super().__init__(Status(Code.NOT_FOUND, message))


class AlreadyPresent(StatusError):
    def __init__(self, message: str):
        super().__init__(Status(Code.ALREADY_PRESENT, message))


class IllegalState(StatusError):
    def __init__(self, message: str):
        super().__init__(Status(Code.ILLEGAL_STATE, message))


class TabletSplit(StatusError):
    """The addressed tablet has been sealed for (or replaced by) a
    split: the caller's location entry is stale at TABLET granularity.
    Carries the split tablet's id so the client can invalidate exactly
    that entry and re-plan from fresh locations (reference: the
    TABLET_SPLIT error of tserver_error.h driving per-tablet meta-cache
    invalidation in client-side LookupRpc retries)."""

    def __init__(self, tablet_id: str):
        super().__init__(Status(Code.ILLEGAL_STATE,
                                f"tablet {tablet_id} has been split",
                                {"tablet_id": tablet_id}))
        self.tablet_id = tablet_id


OK = Status()


def ok() -> Status:
    return OK


def not_found(message: str) -> Status:
    return Status(Code.NOT_FOUND, message)


def invalid_argument(message: str) -> Status:
    return Status(Code.INVALID_ARGUMENT, message)


def illegal_state(message: str) -> Status:
    return Status(Code.ILLEGAL_STATE, message)


def ql_error(message: str, **payload) -> Status:
    return Status(Code.QL_ERROR, message, payload)
