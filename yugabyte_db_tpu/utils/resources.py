"""The runtime resource witness: pin attribution + lock-hold durations.

Dynamic half of the ``ires/`` and ``iholds/`` static families, mirroring
the lock witness (utils/locking.py) and the compile witness
(utils/jitting.py): the static pass proves the tree leak- and
hold-clean on paper, this module checks the claim against a live run.

- **Pin attribution** (``ires/``): every residency pin taken through
  ``HbmCache.acquire(pin=True)/pin/add_external`` is attributed to its
  acquire site and thread; every ``unpin``/``invalidate`` retires one.
  Whatever is still outstanding at dump time — excluding external
  entries, which are permanently pinned by design — is a leak, and the
  dump names the exact frame that took it.

- **Hold durations** (``iholds/``): locks wrapped by the witness (the
  ``@guarded_by`` guard locks, see utils/locking.py) record every
  acquire→release interval into ``yb_lock_hold_seconds{cls}``, and the
  blocking seams (``transport.send``, the WAL fsync) call
  :func:`note_blocking` so any lock the calling thread still holds at
  that point is flagged as a (class, blocking-kind) hold observation.

Enable with the ``--pin_witness`` flag or :func:`enable_resource_witness`
BEFORE constructing the system under test (locks are only wrapped on
instances built while a witness is enabled).  Feed the dump to ``python
-m yugabyte_db_tpu.analysis --witness-check``: a leaked pin always
contradicts the static clean bill, and a hold observation contradicts
unless the static pass knows the (class, kind) pair — either as a
finding to fix or under a justified inline suppression (see
``ires.resource_contradictions``).

Everything here is best-effort and exception-free: the witness observes
the system, it must never perturb it.
"""

from __future__ import annotations

import json
import logging
import threading
import time

_LOG = logging.getLogger("yugabyte_db_tpu.swallowed")

_SITE_CAP = 8  # acquire sites kept per hold key (enough to debug)

# Frames belonging to the instrumentation itself, skipped when
# attributing an event to its caller.
_OWN_FILES = ("resources.py", "locking.py", "residency.py")


def _caller_site() -> str:
    """file:line of the nearest frame outside the instrumentation."""
    import sys

    try:
        f = sys._getframe(2)
        while f is not None and \
                f.f_code.co_filename.endswith(_OWN_FILES):
            f = f.f_back
        if f is None:
            return "?"
        return f"{f.f_code.co_filename}:{f.f_lineno}"
    except Exception:  # noqa: BLE001 — witness must never throw
        return "?"


class ResourceWitness:
    """Process-wide accumulator of pin lifetimes and lock-hold facts."""

    def __init__(self):
        self._lock = threading.Lock()
        self.enabled = False
        # pin key -> list of {"key","site","thread","external"}, one
        # per outstanding pin (a pin count attributed per-acquire).
        self._pins: dict[object, list] = {}
        # (cls, blocking kind) -> [count, first site]
        self._holds: dict[tuple, list] = {}
        # Per-thread stack of (lock identity, cls, acquire monotonic).
        self._tls = threading.local()
        self.pin_acquires = 0
        self.pin_releases = 0

    # -- pin lifecycle (hooked from storage/residency.py) --------------------

    def pin_acquired(self, key, label: str = "",
                     external: bool = False) -> None:
        try:
            rec = {"key": f"{label or 'pin'}#{key}",
                   "site": _caller_site(),
                   "thread": threading.current_thread().name,
                   "external": external}
            with self._lock:
                self._pins.setdefault(key, []).append(rec)
                self.pin_acquires += 1
            from yugabyte_db_tpu.utils.metrics import resource_witness_entity
            resource_witness_entity().counter(
                "yb_resource_pin_acquires").increment()
        except Exception:  # noqa: BLE001 — witness must never throw
            _LOG.debug("pin_acquired failed for %r", key)

    def pin_released(self, key) -> None:
        try:
            with self._lock:
                recs = self._pins.get(key)
                if recs:
                    recs.pop()
                    if not recs:
                        del self._pins[key]
                self.pin_releases += 1
            from yugabyte_db_tpu.utils.metrics import resource_witness_entity
            resource_witness_entity().counter(
                "yb_resource_pin_releases").increment()
        except Exception:  # noqa: BLE001 — witness must never throw
            _LOG.debug("pin_released failed for %r", key)

    def pins_cleared(self, key) -> None:
        """Entry teardown (invalidate / owner collected): every pin on
        the key is retired at once — balanced, not a leak."""
        try:
            with self._lock:
                recs = self._pins.pop(key, None)
                if recs:
                    self.pin_releases += len(recs)
        except Exception:  # noqa: BLE001 — witness must never throw
            _LOG.debug("pins_cleared failed for %r", key)

    def outstanding(self) -> list[dict]:
        """Every non-external pin still held, oldest first — after a
        quiesce (overlays dropped, unpinned evicted) these are leaks."""
        with self._lock:
            return [dict(r) for recs in self._pins.values()
                    for r in recs if not r["external"]]

    # -- lock holds (hooked from utils/locking.py _WitnessLock) ---------------

    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def lock_acquired(self, lock) -> None:
        try:
            self._held().append(
                (id(lock), getattr(lock, "_cls", "") or "?",
                 time.monotonic()))
        except Exception:  # noqa: BLE001 — witness must never throw
            _LOG.debug("lock_acquired recording failed")

    def lock_released(self, lock) -> None:
        try:
            held = self._held()
            for i in range(len(held) - 1, -1, -1):
                if held[i][0] == id(lock):
                    _, cls, t0 = held.pop(i)
                    from yugabyte_db_tpu.utils.metrics import \
                        observe_lock_hold_s
                    observe_lock_hold_s(cls, time.monotonic() - t0)
                    return
        except Exception:  # noqa: BLE001 — witness must never throw
            _LOG.debug("lock_released recording failed")

    def note_blocking(self, kind: str) -> None:
        """A blocking seam (``rpc``, ``fsync``, ...) is about to run on
        the calling thread: flag every witness-wrapped lock it still
        holds as a (class, kind) hold-across-blocking observation."""
        if not self.enabled:
            return
        try:
            held = getattr(self._tls, "held", None)
            if not held:
                return
            site = _caller_site()
            with self._lock:
                for _, cls, _t0 in held:
                    row = self._holds.get((cls, kind))
                    if row is None:
                        row = self._holds[(cls, kind)] = [0, site]
                    row[0] += 1
            from yugabyte_db_tpu.utils.metrics import resource_witness_entity
            resource_witness_entity().counter(
                "yb_resource_holds_across_blocking").increment()
        except Exception:  # noqa: BLE001 — witness must never throw
            _LOG.debug("note_blocking recording failed for %s", kind)

    # -- reporting ------------------------------------------------------------

    def holds(self) -> list[dict]:
        with self._lock:
            return [{"cls": k[0], "blocking": k[1], "count": row[0],
                     "site": row[1]}
                    for k, row in sorted(self._holds.items())]

    def clear(self) -> None:
        with self._lock:
            self._pins.clear()
            self._holds.clear()
            self.pin_acquires = 0
            self.pin_releases = 0

    def dump(self, path: str) -> str:
        payload = {"version": 1, "kind": "yb-resource-witness",
                   "leaks": self.outstanding(),
                   "holds": self.holds(),
                   "counters": {"pin_acquires": self.pin_acquires,
                                "pin_releases": self.pin_releases}}
        with open(path, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2)
            f.write("\n")
        return path


_WITNESS = ResourceWitness()


def witness() -> ResourceWitness:
    return _WITNESS


def enable_resource_witness() -> None:
    from yugabyte_db_tpu.utils import locking

    _WITNESS.enabled = True
    # Locks wrap (and report acquire/release) only while some witness
    # is live — flip the locking-side fast-path flag on.
    locking.set_hold_tracking(True)


def disable_resource_witness() -> None:
    from yugabyte_db_tpu.utils import locking

    _WITNESS.enabled = False
    locking.set_hold_tracking(False)


def resource_witness_enabled() -> bool:
    return _WITNESS.enabled


def note_blocking(kind: str) -> None:
    """Module-level seam marker (cheap no-op while disabled)."""
    w = _WITNESS
    if w.enabled:
        w.note_blocking(kind)


def dump_resource_witness(path: str) -> str:
    return _WITNESS.dump(path)


def load_resource_witness_dump(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if data.get("kind") != "yb-resource-witness":
        raise ValueError(f"{path}: not a resource-witness dump")
    return data
