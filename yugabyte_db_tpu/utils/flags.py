"""Flags: a typed runtime-settable configuration registry with tags.

Reference analog: the gflags + flag-tags system (src/yb/util/flag_tags.h
— stable/evolving/advanced/unsafe/runtime) and the SetFlag RPC of
GenericService (src/yb/server/generic_service.cc). Flags tagged
``runtime`` may change on a live process; ``unsafe`` flags require
explicit unlocking, mirroring --unlock_unsafe_flags.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

VALID_TAGS = {"stable", "evolving", "advanced", "runtime", "unsafe",
              "hidden"}


@dataclass
class FlagInfo:
    name: str
    default: object
    help: str
    tags: frozenset = frozenset()
    value: object = None


class FlagRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._flags: dict[str, FlagInfo] = {}
        self.unsafe_unlocked = False

    def define(self, name: str, default, help_: str = "",
               tags=()) -> None:
        tags = frozenset(tags)
        bad = tags - VALID_TAGS
        if bad:
            raise ValueError(f"unknown flag tags {sorted(bad)}")
        with self._lock:
            if name in self._flags:
                return  # idempotent re-import
            self._flags[name] = FlagInfo(name, default, help_, tags,
                                         default)

    def get(self, name: str):
        with self._lock:
            return self._flags[name].value

    def set(self, name: str, value, force: bool = False) -> None:
        with self._lock:
            f = self._flags[name]
            if "unsafe" in f.tags and not (self.unsafe_unlocked or force):
                raise PermissionError(
                    f"flag {name} is tagged unsafe; unlock unsafe flags "
                    "first")
            if not isinstance(value, type(f.default)) and \
                    f.default is not None:
                value = type(f.default)(value)
            f.value = value

    def all(self) -> list[FlagInfo]:
        with self._lock:
            return [FlagInfo(f.name, f.default, f.help, f.tags, f.value)
                    for f in self._flags.values()]


FLAGS = FlagRegistry()

# Core flags (grown as subsystems adopt them).
FLAGS.define("memtable_flush_versions", 1 << 60,
             "versions buffered before an automatic flush",
             ("stable", "runtime"))
FLAGS.define("compaction_trigger", 4,
             "sorted-run count triggering universal compaction",
             ("stable", "runtime"))
FLAGS.define("txn_expiry_s", 10.0,
             "seconds without heartbeat before a txn is auto-aborted",
             ("evolving", "runtime"))
FLAGS.define("max_clock_skew_us", 500_000,
             "bound on tolerated inter-node clock skew",
             ("stable",))
FLAGS.define("follower_unavailable_considered_failed_sec", 5.0,
             "tserver liveness timeout", ("stable",))
FLAGS.define("tpu_engine_use_pallas", False,
             "route eligible flat-run aggregate scans through the "
             "hand-written Pallas fold kernel (ops.pallas_agg) instead "
             "of the XLA scan program", ("evolving", "runtime"))
FLAGS.define("tpu_hbm_budget_bytes", 0,
             "PER-DEVICE capacity budget for device-resident (HBM) "
             "columnar run planes; 0 = unbounded. When set, run planes "
             "are demand-uploaded through the storage.residency cache "
             "and evicted LRU per device with a scan-resistant two-pool "
             "policy (reference: rocksdb/util/cache.cc high-pri/low-pri "
             "split). Each mesh chip gets its own bucket of this size",
             ("evolving", "runtime"))
FLAGS.define("tpu_run_placement", "default",
             "which device a tablet's run planes live on: 'default' = "
             "jax's default device (single-chip behavior), "
             "'round_robin' = spread runs across the local mesh so "
             "per-device HBM budgets are actually load-balanced",
             ("evolving", "runtime"))
FLAGS.define("global_memstore_limit_bytes", 1 << 40,
             "process-wide memtable budget; crossing it flushes the "
             "engine that noticed (reference: the shared memory_monitor "
             "across rocksdb instances)", ("stable", "runtime"))
FLAGS.define("use_cassandra_authentication", False,
             "require CQL authentication + per-statement role "
             "permission checks (reference: the flag of the same name "
             "gating auth in the CQL proxy)", ("stable", "runtime"))
FLAGS.define("ysql_require_auth", False,
             "require cleartext-password authentication on the PG wire "
             "(reference: pg_hba password auth via initdb defaults)",
             ("stable", "runtime"))
FLAGS.define("fault.ts_write_respond_failed", 0.0,
             "probability a successful tablet write responds failure "
             "anyway (client-retry / exactly-once testing; reference: "
             "FLAGS_respond_write_failed_probability)",
             ("unsafe", "runtime", "hidden"))
FLAGS.define("fault.wal_sync_failed", 0.0,
             "probability a WAL group-commit sync raises IOError",
             ("unsafe", "runtime", "hidden"))
FLAGS.define("tpu_breaker_failure_threshold", 3,
             "consecutive device-dispatch faults before the TPU engine's "
             "circuit breaker opens and scans re-serve from the host path",
             ("advanced", "runtime"))
FLAGS.define("tpu_breaker_cooldown_s", 1.0,
             "seconds an open TPU-engine breaker waits before admitting "
             "one half-open probe dispatch",
             ("advanced", "runtime"))
FLAGS.define("fault.tpu_dispatch", 0.0,
             "probability a device (TPU) dispatch raises — exercises the "
             "storage/breaker.py circuit breaker and the host re-serve "
             "path",
             ("unsafe", "runtime", "hidden"))
FLAGS.define("lock_witness", False,
             "record (field, lock-held) observations for every "
             "@guarded_by-declared field write (utils/locking.py); dump "
             "is cross-checked against yb-lint's static guarded facts "
             "via python -m yugabyte_db_tpu.analysis --witness-check",
             ("advanced", "runtime", "hidden"))
FLAGS.define("compile_witness", False,
             "count actual XLA trace/compile events per "
             "@compile_contract-declared jit entry (utils/jitting.py); "
             "dump is cross-checked against yb-lint's static compile "
             "contracts via python -m yugabyte_db_tpu.analysis "
             "--witness-check",
             ("advanced", "runtime", "hidden"))
FLAGS.define("pin_witness", False,
             "attribute every residency pin acquire/release to an owner "
             "site and thread, record per-lock hold durations into "
             "yb_lock_hold_seconds{cls}, and flag locks held across "
             "blocking seams (utils/resources.py); dump is cross-checked "
             "against yb-lint's static resource facts via python -m "
             "yugabyte_db_tpu.analysis --witness-check",
             ("advanced", "runtime", "hidden"))
FLAGS.define("fault.seed", 0,
             "non-zero: seed the fault-injection RNG so probabilistic "
             "faults replay deterministically (the sweep harness sets "
             "this; 0 = unseeded)",
             ("unsafe", "runtime", "hidden"))
FLAGS.define("raft_group_commit_window_us", 200,
             "microseconds the leader-side commit pipeline waits after "
             "the first append before issuing one WAL sync + one "
             "AppendEntries round per peer for every entry admitted in "
             "the window; 0 disables coalescing (every append signals "
             "peers immediately, the pre-group-commit behaviour)",
             ("evolving", "runtime"))
FLAGS.define("raft_max_inflight_ops", 4096,
             "backpressure bound on the leader's append->apply window: "
             "append_leader blocks while last_index - applied_index "
             "reaches this many entries (bounded apply-queue depth for "
             "the ack-at-commit pipeline)",
             ("evolving", "runtime"))
FLAGS.define("tpu_device_flush", True,
             "build flush runs on-device: replay the memtable op log "
             "into staged columnar planes and apply the sort "
             "permutation via a jitted gather (ops/flush.py), "
             "pre-seeding the run's resident device planes; falls back "
             "to the host path when the run exceeds the HBM residency "
             "budget or the device dispatch faults",
             ("evolving", "runtime"))
FLAGS.define("tpu_plane_encoding", "auto",
             "compressed device plane encodings for columnar runs: "
             "'auto' picks per-column encodings (dictionary for varlen, "
             "RLE/delta16/const for ints, bit-packed bools) at build "
             "time via a cheap stats pass and the kernels read the "
             "compressed planes directly; 'off' uploads uncompressed "
             "planes (the pre-encoding format). Pathological columns "
             "(dictionary overflow, low run-length) transparently fall "
             "back to uncompressed per plane",
             ("evolving", "runtime"))
FLAGS.define("fault.raft_apply_stall", 0.0,
             "non-zero: the Raft apply stage stalls (committed entries "
             "stay unapplied) — used by the commit_ack_crash fault-sweep "
             "round to widen the commit-ack/apply window deterministically",
             ("unsafe", "runtime", "hidden"))
FLAGS.define("tablet_split_size_bytes", 0,
             "size threshold for master-driven tablet splitting: a "
             "tablet whose reported on-disk size (WAL + flushed runs) "
             "crosses this many bytes is split at its median resident "
             "key; 0 disables size-based splitting (reference: "
             "FLAGS_tablet_split_size_threshold_bytes of "
             "catalog_manager's tablet-split heuristics)",
             ("evolving", "runtime"))
FLAGS.define("tablet_split_ops_per_sec", 0.0,
             "op-rate threshold for master-driven tablet splitting: a "
             "tablet whose heartbeat-reported op rate sustains above "
             "this many ops/s is split at its median resident key; 0 "
             "disables load-based splitting (reference: the automatic "
             "tablet-splitting thresholds of the reference's "
             "TabletSplitManager)",
             ("evolving", "runtime"))
FLAGS.define("enable_leader_balancing", False,
             "run the master's leader load-balancer pass: when the "
             "spread between the most- and least-leader-loaded live "
             "tservers reaches 2, step one leader down toward the "
             "least-loaded tserver (one move per pass; reference: "
             "the leader-balancing half of cluster_balance.cc)",
             ("evolving", "runtime"))
