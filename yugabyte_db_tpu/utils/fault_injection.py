"""Fault injection: flag-driven probabilistic/deterministic failures.

Reference analog: src/yb/util/fault_injection.h:49 (MAYBE_FAULT) and
the per-service probability flags
(FLAGS_respond_write_failed_probability, tablet_service.cc:784) —
production code marks fault points; tests arm them via flags.

    FLAGS.set("fault.ts_write_respond_failed", 1.0)   # always
    FLAGS.set("fault.ts_write_respond_failed", 0.0)   # never (default)
    arm_fault_once("fault.wal_sync")                  # exactly one hit
"""

from __future__ import annotations

import random
import threading

_lock = threading.Lock()
_once: dict[str, int] = {}   # fault name -> remaining forced hits
_rng = random.Random()


def arm_fault_once(name: str, times: int = 1) -> None:
    """Force the next ``times`` evaluations of ``name`` to fire
    (deterministic tests; beats probability flags for exactness)."""
    with _lock:
        _once[name] = _once.get(name, 0) + times


def clear_faults() -> None:
    with _lock:
        _once.clear()


def maybe_fault(name: str) -> bool:
    """True when the named fault should fire. Checks armed one-shot
    hits first, then the flag ``name`` as a probability in [0, 1]
    (unknown flag = 0: disabled)."""
    with _lock:
        n = _once.get(name, 0)
        if n > 0:
            _once[name] = n - 1
            return True
    from yugabyte_db_tpu.utils.flags import FLAGS

    try:
        p = float(FLAGS.get(name))
    except (KeyError, TypeError, ValueError):
        return False
    return p > 0 and _rng.random() < p


class FaultInjected(Exception):
    """Raised by fault points that abort the operation."""
