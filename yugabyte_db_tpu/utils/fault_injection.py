"""Fault injection: flag-driven probabilistic/deterministic failures.

Reference analog: src/yb/util/fault_injection.h:49 (MAYBE_FAULT) and
the per-service probability flags
(FLAGS_respond_write_failed_probability, tablet_service.cc:784) —
production code marks fault points; tests arm them via flags.

    FLAGS.set("fault.ts_write_respond_failed", 1.0)   # always
    FLAGS.set("fault.ts_write_respond_failed", 0.0)   # never (default)
    arm_fault_once("fault.wal_sync")                  # exactly one hit

Reproducibility + observability: ``fault.seed`` (non-zero) seeds the
probability RNG so a randomized sweep replays byte-for-byte, and every
fault that fires bumps ``yb_faults_fired{name=...}`` on the process
registry — the sweep harness asserts its injection count against the
metric instead of trusting its own bookkeeping.
"""

from __future__ import annotations

import random
import threading

_lock = threading.Lock()
_once: dict[str, int] = {}   # fault name -> remaining forced hits
_rng = random.Random()
_applied_seed = 0            # last fault.seed value folded into _rng


def arm_fault_once(name: str, times: int = 1) -> None:
    """Force the next ``times`` evaluations of ``name`` to fire
    (deterministic tests; beats probability flags for exactness)."""
    with _lock:
        _once[name] = _once.get(name, 0) + times


def clear_faults() -> None:
    with _lock:
        _once.clear()


def _count_fired(name: str) -> None:
    from yugabyte_db_tpu.utils.metrics import count_fault_fired

    count_fault_fired(name)


def _maybe_reseed_locked() -> None:
    """Fold a changed ``fault.seed`` flag into the RNG (0 = unseeded).
    Lazy so ``FLAGS.set("fault.seed", s)`` takes effect at the next
    fault evaluation, matching the runtime-mutable flag contract."""
    global _applied_seed
    from yugabyte_db_tpu.utils.flags import FLAGS

    try:
        seed = int(FLAGS.get("fault.seed"))
    except (KeyError, TypeError, ValueError):
        return
    if seed != _applied_seed:
        _applied_seed = seed
        if seed != 0:
            _rng.seed(seed)


def maybe_fault(name: str) -> bool:
    """True when the named fault should fire. Checks armed one-shot
    hits first, then the flag ``name`` as a probability in [0, 1]
    (unknown flag = 0: disabled). Every fire counts in
    ``yb_faults_fired{name=...}``."""
    with _lock:
        n = _once.get(name, 0)
        if n > 0:
            _once[name] = n - 1
            _count_fired(name)
            return True
    from yugabyte_db_tpu.utils.flags import FLAGS

    try:
        p = float(FLAGS.get(name))
    except (KeyError, TypeError, ValueError):
        return False
    if p <= 0:
        return False
    with _lock:
        _maybe_reseed_locked()
        fired = _rng.random() < p
    if fired:
        _count_fired(name)
    return fired


class FaultInjected(Exception):
    """Raised by fault points that abort the operation."""
