"""Hybrid time: the MVCC timestamp of the whole framework.

Reference analog: src/yb/common/hybrid_time.h:69 — a 64-bit value packing a
physical microsecond timestamp in the high 52 bits and a 12-bit logical
counter in the low bits, and src/yb/server/hybrid_clock.h:55 — the clock that
issues them (physical wall clock, logical increments within one microsecond,
``Update()`` on message receipt for causality).

TPU note: a HybridTime must be comparable *inside* device kernels (MVCC
visibility is a per-row ``commit_ht <= read_ht`` mask). TPUs have no cheap
int64, so device-side we represent a hybrid time as two int32 "planes"
(see yugabyte_db_tpu.utils.planes): hi = bits 63..32 (always < 2^31 since
HT < 2^63), lo = bits 31..0 bias-flipped so signed int32 comparison equals
unsigned comparison. Host-side it is a plain Python int.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

# 12 bits of logical counter below the microsecond physical component,
# matching the reference (hybrid_time.h kBitsForLogicalComponent = 12).
BITS_FOR_LOGICAL = 12
LOGICAL_MASK = (1 << BITS_FOR_LOGICAL) - 1

_MAX_HT = (1 << 63) - 1

# Bound on tolerated clock skew between nodes: remote/client-supplied hybrid
# times further than this ahead of the local clock are rejected instead of
# ratcheting the clock (reference: FLAGS_max_clock_skew_usec,
# src/yb/server/hybrid_clock.cc).
MAX_CLOCK_SKEW_US = 500_000


@dataclass(frozen=True, order=True)
class HybridTime:
    """An immutable hybrid timestamp. Total order == integer order on .value."""

    value: int

    # -- constructors ------------------------------------------------------
    @staticmethod
    def from_micros(micros: int, logical: int = 0) -> "HybridTime":
        # '+' (not '|') so a logical overflow carries into the physical
        # component instead of silently wrapping backwards in time.
        return HybridTime((micros << BITS_FOR_LOGICAL) + logical)

    @staticmethod
    def min() -> "HybridTime":
        return _MIN

    @staticmethod
    def max() -> "HybridTime":
        return _MAX

    @staticmethod
    def invalid() -> "HybridTime":
        return _INVALID

    # -- accessors ---------------------------------------------------------
    @property
    def physical_micros(self) -> int:
        return self.value >> BITS_FOR_LOGICAL

    @property
    def logical(self) -> int:
        return self.value & LOGICAL_MASK

    @property
    def is_valid(self) -> bool:
        return self.value >= 0

    def incremented(self) -> "HybridTime":
        return HybridTime(self.value + 1)

    def decremented(self) -> "HybridTime":
        return HybridTime(self.value - 1)

    def __repr__(self) -> str:
        if self.value == _MAX_HT:
            return "HT<max>"
        if self.value == 0:
            return "HT<min>"
        if self.value < 0:
            return "HT<invalid>"
        return f"HT{{p:{self.physical_micros} l:{self.logical}}}"


_MIN = HybridTime(0)
_MAX = HybridTime(_MAX_HT)
_INVALID = HybridTime(-1)


class HybridClock:
    """Issues monotonically increasing hybrid times from the wall clock.

    Reference analog: src/yb/server/hybrid_clock.h:55 (Now/Update). The clock
    never goes backwards: if the wall clock regresses or stalls within one
    microsecond, the logical component increments; ``update`` ratchets the
    clock forward on receipt of a remote hybrid time (causality across nodes).
    """

    def __init__(self, now_micros=None):
        self._lock = threading.Lock()
        self._last = 0  # last issued HT value
        self._now_micros = now_micros or (lambda: time.time_ns() // 1000)

    def now(self) -> HybridTime:
        physical = self._now_micros() << BITS_FOR_LOGICAL
        with self._lock:
            if physical > self._last:
                self._last = physical
            else:
                self._last += 1
            return HybridTime(self._last)

    def update(self, observed: HybridTime) -> None:
        """Ratchet the clock to be >= an observed remote hybrid time."""
        if not observed.is_valid:
            return
        with self._lock:
            if observed.value > self._last:
                self._last = observed.value

    def max_global_now(self) -> HybridTime:
        """Upper bound on any hybrid time issued anywhere (clock-skew bound).

        Read-only: observing the bound must not issue a timestamp.
        Single-process deployments have no skew; multi-node config adds it.
        """
        physical = self._now_micros() << BITS_FOR_LOGICAL
        with self._lock:
            return HybridTime(max(self._last, physical))


class LogicalClock:
    """Purely logical clock for deterministic tests.

    Reference analog: src/yb/server/logical_clock.h.
    """

    def __init__(self, initial: int = 1):
        self._lock = threading.Lock()
        self._value = initial

    def now(self) -> HybridTime:
        with self._lock:
            ht = HybridTime(self._value)
            self._value += 1
            return ht

    def update(self, observed: HybridTime) -> None:
        with self._lock:
            if observed.value >= self._value:
                self._value = observed.value + 1

    def peek(self) -> HybridTime:
        with self._lock:
            return HybridTime(self._value)
