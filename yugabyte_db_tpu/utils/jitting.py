"""@compile_contract declarations + the runtime compile witness.

Reference analog: the reference tree keeps the storage hot path free of
per-request setup cost by pinning every prepared execution plan at the
``YQLStorageIf`` boundary; the JAX equivalent of "per-request setup" is
an unintended retrace — a jitted entry point recompiling because a
static argument, closure capture, or array shape varies per request.
This module supplies both halves of the discipline, mirroring
``utils/locking.py``'s @guarded_by + lock-witness pattern:

- :func:`compile_contract` is a decorator declaring "this jitted entry
  compiles at most N distinct programs over the life of the process".
  The declaration is a plain literal
  (``@compile_contract("seg_aggregate", max_compiles=32)``) so yb-lint's
  ``ijit/`` pass reads it straight off the AST and checks every call
  site statically for per-request static args, mutable closure captures,
  and data-derived shapes.

- The **compile witness** is the dynamic half: when enabled (the
  ``--compile_witness`` debug flag, or :func:`enable_compile_witness`
  in tests), every actual XLA trace/compile event of a contracted entry
  is counted (via the jitted callable's compiled-program cache size — a
  cache growth across a call IS a compile). A dump of those counts is
  fed to ``python -m yugabyte_db_tpu.analysis --witness-check <dump>``,
  which fails when any entry exceeds its declared budget or when an
  entry the static pass proved stable recompiled after
  :func:`mark_steady_state` — the static pass keeps the budgets honest,
  the witness keeps the static pass honest.

Every compile event also bumps ``yb_jit_compiles{entry=...}`` on the
process metric registry (witness on or off), so every daemon's
``/metrics`` scrape and every bench round can prove zero steady-state
recompiles. When the witness is disabled the per-dispatch cost is two
compiled-cache-size probes (C++ attribute reads on the jit object).
"""

from __future__ import annotations

import functools
import json
import threading

# entry name -> declared max_compiles, in registration order. Filled at
# import time by @compile_contract decorations; read by the witness dump
# and by tests. The static pass reads the same budgets off the AST.
_CONTRACTS: dict[str, int] = {}
_CONTRACTS_LOCK = threading.Lock()


class CompileWitness:
    """Process-wide accumulator of per-entry compile counts. Everything
    is best-effort and exception-free: the witness observes the system,
    it must never perturb it."""

    _SITE_CAP = 8  # compile call sites kept per entry (enough to debug)

    def __init__(self):
        self._lock = threading.Lock()
        self.enabled = False
        self._steady = False
        # entry -> [compiles, steady_compiles, [sites...]]
        self._obs: dict[str, list] = {}

    def record(self, entry: str, n: int = 1) -> None:
        try:
            with self._lock:
                row = self._obs.get(entry)
                if row is None:
                    row = self._obs[entry] = [0, 0, []]
                row[0] += n
                if self._steady:
                    row[1] += n
                if len(row[2]) < self._SITE_CAP:
                    row[2].append(_caller_site())
        # The witness observes dispatches on the serve path; raising (or
        # even logging) from here would perturb the system under test.
        # yb-lint: disable=errors/swallowed-exception
        except Exception:  # noqa: BLE001 — witness must never throw
            pass

    def mark_steady_state(self) -> None:
        """Compiles recorded after this mark are *steady-state* — the
        warmup is over, every program the workload needs exists. A
        steady-state compile on an entry the static pass proved stable
        is a witness-check contradiction."""
        with self._lock:
            self._steady = True

    def observations(self) -> list[dict]:
        with self._lock, _CONTRACTS_LOCK:
            return [{"entry": e, "compiles": row[0], "steady": row[1],
                     "budget": _CONTRACTS.get(e), "sites": list(row[2])}
                    for e, row in sorted(self._obs.items())]

    def clear(self) -> None:
        with self._lock:
            self._obs.clear()
            self._steady = False

    def dump(self, path: str) -> str:
        payload = {"version": 1, "kind": "yb-compile-witness",
                   "observations": self.observations()}
        with open(path, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2)
            f.write("\n")
        return path


def _caller_site() -> str:
    """file:line of the dispatch that compiled (the frame below the
    contract wrapper); "?" when unavailable."""
    import sys

    try:
        f = sys._getframe(3)
        while f is not None and f.f_code.co_filename.endswith("jitting.py"):
            f = f.f_back
        if f is None:
            return "?"
        return f"{f.f_code.co_filename}:{f.f_lineno}"
    except Exception:  # noqa: BLE001 — witness must never throw
        return "?"


_WITNESS = CompileWitness()


def witness() -> CompileWitness:
    return _WITNESS


def enable_compile_witness() -> None:
    _WITNESS.enabled = True


def disable_compile_witness() -> None:
    _WITNESS.enabled = False


def compile_witness_enabled() -> bool:
    return _WITNESS.enabled


def mark_steady_state() -> None:
    _WITNESS.mark_steady_state()


def dump_compile_witness(path: str) -> str:
    return _WITNESS.dump(path)


def load_compile_witness_dump(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if data.get("kind") != "yb-compile-witness":
        raise ValueError(f"{path}: not a compile-witness dump")
    return data


def declared_contracts() -> dict[str, int]:
    """entry -> max_compiles for every contract registered at runtime."""
    with _CONTRACTS_LOCK:
        return dict(_CONTRACTS)


# -- the declaration decorator ------------------------------------------------

def _is_jitted(obj) -> bool:
    """A jax.jit product: exposes the compiled-program cache probe."""
    return callable(obj) and hasattr(obj, "_cache_size")


def _note_compiles(entry: str, n: int) -> None:
    from yugabyte_db_tpu.utils import metrics

    metrics.count_jit_compile(entry, n)
    if _WITNESS.enabled:
        _WITNESS.record(entry, n)


class ContractedJit:
    """Wraps a jitted callable; a growth of its compiled-program cache
    across a dispatch is a trace/compile event for the contract's entry.
    Transparent otherwise — attribute access delegates to the jit
    object, so ``.lower``/``.clear_cache`` etc. keep working."""

    __slots__ = ("_fn", "_entry")

    def __init__(self, fn, entry: str):
        self._fn = fn
        self._entry = entry

    def __call__(self, *args, **kwargs):
        fn = self._fn
        try:
            before = fn._cache_size()
        except Exception:  # noqa: BLE001 — probe is best-effort
            before = None
        out = fn(*args, **kwargs)
        if before is not None:
            try:
                delta = fn._cache_size() - before
            except Exception:  # noqa: BLE001 — probe is best-effort
                delta = 0
            if delta > 0:
                _note_compiles(self._entry, delta)
        return out

    def __getattr__(self, name):
        return getattr(self._fn, name)


def compile_contract(entry: str, max_compiles: int):
    """Declare a jitted entry point's compile budget.

    Pure-literal usage only (string + int constants), so the static pass
    can read the declaration off the AST. Two shapes:

    - a **factory** returning ``jax.jit(...)`` — decorate *under* the
      ``lru_cache`` so the signature cache keeps one wrapper per
      signature::

          @functools.lru_cache(maxsize=128)
          @compile_contract("seg_aggregate", max_compiles=32)
          def compiled_seg_aggregate(sig): ...

    - a **directly jitted** function — decorate above the jit::

          @compile_contract("replay_flush", max_compiles=8)
          @functools.partial(jax.jit, static_argnames=("R",))
          def replay_flush(...): ...

    Either way the callable the caller ends up holding counts actual
    XLA compile events against ``yb_jit_compiles{entry=...}`` and, when
    enabled, the compile witness. ``max_compiles`` bounds the *distinct
    compiled programs* over the process lifetime (one per static
    signature / shape bucket), not dispatches.
    """
    if not isinstance(entry, str) or not entry \
            or not isinstance(max_compiles, int) or max_compiles < 1:
        raise TypeError("compile_contract(entry, max_compiles) takes a "
                        "string literal and a positive int literal")
    with _CONTRACTS_LOCK:
        _CONTRACTS[entry] = max_compiles

    def deco(obj):
        if _is_jitted(obj):
            wrapped = ContractedJit(obj, entry)
            return wrapped

        @functools.wraps(obj)
        def factory(*args, **kwargs):
            out = obj(*args, **kwargs)
            return ContractedJit(out, entry) if _is_jitted(out) else out

        factory.__compile_contract__ = (entry, max_compiles)
        return factory

    return deco
