"""int32 "plane" representation of unsigned 64/32-bit scalars for TPU kernels.

TPUs have no native int64 and JAX defaults to 32-bit. Every ordered quantity
the device kernels compare — hybrid times, key-prefix words — is therefore
carried as one or more **int32 planes** chosen so that *signed* int32
comparisons reproduce the unsigned/lexicographic order:

- a u64 ``v < 2**63`` (hybrid times) splits into ``hi = v >> 32`` (fits a
  non-negative int32 because v < 2^63) and ``lo = (v & 0xFFFFFFFF) ^ 0x80000000``
  reinterpreted as int32. Bias-flipping the low word maps unsigned order onto
  signed order: (a ^ 2^31 as i32) < (b ^ 2^31 as i32)  ⇔  a <u b.
- a u32 key word bias-flips the same way into a single plane.

Host-side helpers here are numpy; device kernels in yugabyte_db_tpu.ops
operate on the resulting arrays directly.
"""

from __future__ import annotations

import numpy as np

_BIAS = np.uint32(0x80000000)


def u32_to_plane(words: np.ndarray) -> np.ndarray:
    """uint32 array -> int32 plane preserving unsigned order under signed compare."""
    return (words.astype(np.uint32) ^ _BIAS).view(np.int32)


def plane_to_u32(plane: np.ndarray) -> np.ndarray:
    return plane.view(np.uint32) ^ _BIAS


def u64_to_planes(values: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """uint64 array (< 2^63) -> (hi int32, lo int32 bias-flipped) planes.

    (hi_a, lo_a) <lex (hi_b, lo_b) under signed int32 comparison iff a < b.
    """
    v = values.astype(np.uint64)
    hi = (v >> np.uint64(32)).astype(np.int64)
    if (hi >= (1 << 31)).any():
        raise ValueError("u64 plane split requires values < 2**63")
    lo = (v & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return hi.astype(np.int32), u32_to_plane(lo)


def planes_to_u64(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    return (hi.astype(np.uint64) << np.uint64(32)) | plane_to_u32(lo).astype(np.uint64)


def ht_to_planes(ht_values: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Hybrid-time int64 array -> (hi, lo) int32 planes. HT is always < 2^63."""
    return u64_to_planes(ht_values.astype(np.int64).view(np.uint64))


import functools


@functools.lru_cache(maxsize=4096)
def scalar_ht_planes(ht_value: int) -> tuple[int, int]:
    """A single hybrid time -> (hi, lo) python ints suitable as jnp.int32.
    Cached: servers resolve the same read points (and MAX_HT) constantly."""
    hi, lo = ht_to_planes(np.array([ht_value], dtype=np.int64))
    return int(hi[0]), int(lo[0])


def i64_to_ordered_planes(values: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Signed int64 -> (hi, lo) int32 planes; signed-lex plane order == value order.

    Sign-flips to u64 (v ^ 2^63) then bias-flips both 32-bit words.
    """
    u = values.astype(np.int64).view(np.uint64) ^ np.uint64(1 << 63)
    hi = (u >> np.uint64(32)).astype(np.uint32)
    lo = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return u32_to_plane(hi), u32_to_plane(lo)


def ordered_planes_to_i64(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    u = (plane_to_u32(hi).astype(np.uint64) << np.uint64(32)) | \
        plane_to_u32(lo).astype(np.uint64)
    return (u ^ np.uint64(1 << 63)).view(np.int64)


def f64_to_ordered_planes(values: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """float64 -> (hi, lo) int32 planes; plane order == IEEE total order
    (with -0.0 == 0.0 canonicalized). Same transform as the key encoding:
    negative: flip all bits, else set sign bit."""
    v = values.astype(np.float64).copy()
    v[v == 0.0] = 0.0  # canonicalize -0.0
    bits = v.view(np.uint64)
    neg = (bits >> np.uint64(63)).astype(bool)
    flipped = np.where(neg, ~bits, bits | np.uint64(1 << 63))
    hi = (flipped >> np.uint64(32)).astype(np.uint32)
    lo = (flipped & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return u32_to_plane(hi), u32_to_plane(lo)


def ordered_planes_to_f64(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    flipped = (plane_to_u32(hi).astype(np.uint64) << np.uint64(32)) | \
        plane_to_u32(lo).astype(np.uint64)
    neg = ~(flipped >> np.uint64(63)).astype(bool)
    bits = np.where(neg, ~flipped, flipped & ~np.uint64(1 << 63))
    return bits.view(np.float64)


def varlen_prefix_planes(raws: list[bytes]) -> tuple[np.ndarray, np.ndarray]:
    """First 8 bytes of each byte string -> (hi, lo) int32 planes whose
    signed-lex order equals byte order on the 8-byte prefix. Equal planes are
    a TIE (strings may differ past 8 bytes) — callers must host-verify."""
    planes = key_prefix_planes(list(raws), num_words=2)
    return planes[:, 0], planes[:, 1]


def bytes_to_key_words(data: bytes, num_words: int) -> np.ndarray:
    """Key bytes -> fixed-width big-endian uint32 words, zero-padded.

    Zero padding is order-correct for the DocKey encoding because encoded keys
    are prefix-free at every component boundary (terminators/type tags are
    nonzero), so no valid encoded key is a strict prefix of another within the
    compared width except when they share components — ties are resolved by
    the full key bytes on host (see storage.block boundary handling).
    """
    width = num_words * 4
    padded = data[:width].ljust(width, b"\x00")
    return np.frombuffer(padded, dtype=">u4").astype(np.uint32)


def key_prefix_planes(keys: list[bytes], num_words: int) -> np.ndarray:
    """Encoded keys -> [N, num_words] int32 planes; signed-lex order == byte order
    on the first 4*num_words bytes."""
    out = np.empty((len(keys), num_words), dtype=np.uint32)
    for i, k in enumerate(keys):
        out[i] = bytes_to_key_words(k, num_words)
    return u32_to_plane(out)
