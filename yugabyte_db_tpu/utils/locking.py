"""@guarded_by declarations + the runtime lock-witness recorder.

Reference analog: the Clang thread-safety annotations the reference tree
puts on every shared field (``GUARDED_BY(lock_)``, src/yb/gutil/
thread_annotations.h) and the TSan runs that cross-check them.  Python
has neither, so this module supplies both halves:

- :func:`guarded_by` is a class decorator declaring "these fields are
  protected by this lock attribute".  The declaration is a plain literal
  (``@guarded_by("_lock", "_state", "_entries")``) so yb-lint's
  ``iraces/`` pass reads it straight off the AST and enforces it
  statically on every write site, interprocedurally.

- The **lock witness** is the dynamic half: when enabled (the
  ``--lock_witness`` debug flag, or :func:`enable_lock_witness` in
  tests), every rebind of a declared field records whether the declared
  lock was actually held by the writing thread.  A dump of those
  observations is fed to ``python -m yugabyte_db_tpu.analysis
  --witness-check <dump>``, which fails if runtime behaviour ever
  contradicts a static "guarded" fact — the static pass keeps the
  declarations honest, the witness keeps the static pass honest.

Scope: the witness sees attribute *rebinds* (``self._state = x``,
``self._n += 1``).  In-place container mutation (``self._d[k] = v``)
never calls ``__setattr__``; those sites are covered statically by
``iraces/`` only.  When disabled (the default) the per-write cost is one
attribute load and a falsy check; locks are only wrapped for ownership
tracking on instances constructed while the witness is enabled.
"""

from __future__ import annotations

import json
import threading

_UNTRACKED = -1  # lock ownership not decidable (lock created pre-enable)

# Flipped by utils.resources.enable_resource_witness(): when True,
# _WitnessLock reports outermost acquire/release transitions to the
# resource witness (hold durations + holds-across-blocking). A plain
# module global so the disabled-path cost is one falsy check.
_HOLD_TRACKING = False


def set_hold_tracking(on: bool) -> None:
    global _HOLD_TRACKING
    _HOLD_TRACKING = on


def _resource_witness():
    from yugabyte_db_tpu.utils import resources

    return resources.witness()


class _WitnessLock:
    """Wraps a Lock/RLock to track per-thread ownership (re-entrant
    count) so the witness can ask "does the *writing* thread hold it?"
    — ``Lock.locked()`` only answers "does anyone?"."""

    __slots__ = ("_inner", "_tls", "_cls")

    def __init__(self, inner, cls_name: str = ""):
        self._inner = inner
        self._tls = threading.local()
        self._cls = cls_name

    def acquire(self, *args, **kwargs):
        got = self._inner.acquire(*args, **kwargs)
        if got:
            depth = getattr(self._tls, "depth", 0) + 1
            self._tls.depth = depth
            if depth == 1 and _HOLD_TRACKING:
                _resource_witness().lock_acquired(self)
        return got

    def release(self):
        self._inner.release()
        depth = getattr(self._tls, "depth", 1) - 1
        self._tls.depth = depth
        if depth == 0 and _HOLD_TRACKING:
            _resource_witness().lock_released(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def held_by_current_thread(self) -> bool:
        return getattr(self._tls, "depth", 0) > 0

    def locked(self):
        return self._inner.locked()

    # Condition-variable protocol: threading.Condition copies these three
    # from its lock at construction. Without them it falls back to
    # non-reentrant-Lock defaults, which misdetect ownership of a wrapped
    # RLock (acquire(0) re-enters and "succeeds") and release only one
    # level across a wait.
    def _is_owned(self):
        probe = getattr(self._inner, "_is_owned", None)
        if probe is not None:
            return probe()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def _release_save(self):
        depth = getattr(self._tls, "depth", 0)
        saver = getattr(self._inner, "_release_save", None)
        if saver is not None:
            inner_state = saver()
        else:
            self._inner.release()
            inner_state = None
        self._tls.depth = 0
        # A condition wait genuinely drops the lock: close this hold
        # interval (the re-acquire after the wait opens a new one).
        if depth > 0 and _HOLD_TRACKING:
            _resource_witness().lock_released(self)
        return inner_state, depth

    def _acquire_restore(self, state):
        inner_state, depth = state
        restore = getattr(self._inner, "_acquire_restore", None)
        if restore is not None:
            restore(inner_state)
        else:
            self._inner.acquire()
        self._tls.depth = depth
        if depth > 0 and _HOLD_TRACKING:
            _resource_witness().lock_acquired(self)


def _ownership(lock) -> int:
    """1/0 when decidable for the current thread, _UNTRACKED otherwise."""
    if isinstance(lock, _WitnessLock):
        return 1 if lock.held_by_current_thread() else 0
    # RLock (and Condition) expose _is_owned(); stable CPython internals,
    # good enough for a debug-only witness.
    probe = getattr(lock, "_is_owned", None)
    if probe is not None:
        try:
            return 1 if probe() else 0
        except Exception:  # noqa: BLE001 — witness must never throw
            return _UNTRACKED
    return _UNTRACKED


class LockWitness:
    """Process-wide accumulator of (class, field, lock) -> held/unheld
    write observations.  Everything is best-effort and exception-free:
    the witness observes the system, it must never perturb it."""

    _SITE_CAP = 8  # unheld call sites kept per key (enough to debug)

    def __init__(self):
        self._lock = threading.Lock()
        self.enabled = False
        # (cls_name, field, lock_attr) -> [held, unheld, [sites...]]
        self._obs: dict[tuple, list] = {}

    def record(self, cls_name: str, field: str, lock_attr: str,
               owned: int) -> None:
        if owned == _UNTRACKED:
            return
        try:
            key = (cls_name, field, lock_attr)
            with self._lock:
                row = self._obs.get(key)
                if row is None:
                    row = self._obs[key] = [0, 0, []]
                if owned:
                    row[0] += 1
                else:
                    row[1] += 1
                    if len(row[2]) < self._SITE_CAP:
                        row[2].append(_caller_site())
        # The witness observes every instrumented write; throwing (or
        # even logging) from here would perturb the system under test.
        # yb-lint: disable=errors/swallowed-exception
        except Exception:  # noqa: BLE001 — witness must never throw
            pass

    def observations(self) -> list[dict]:
        with self._lock:
            return [{"class": k[0], "field": k[1], "lock": k[2],
                     "held": row[0], "unheld": row[1],
                     "unheld_sites": list(row[2])}
                    for k, row in sorted(self._obs.items())]

    def clear(self) -> None:
        with self._lock:
            self._obs.clear()

    def dump(self, path: str) -> str:
        payload = {"version": 1, "kind": "yb-lock-witness",
                   "observations": self.observations()}
        with open(path, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2)
            f.write("\n")
        return path


def _caller_site() -> str:
    """file:line of the write that produced an unheld observation (the
    frame below the instrumented __setattr__); "?" when unavailable."""
    import sys

    try:
        f = sys._getframe(3)
        while f is not None and f.f_code.co_filename.endswith("locking.py"):
            f = f.f_back
        if f is None:
            return "?"
        return f"{f.f_code.co_filename}:{f.f_lineno}"
    except Exception:  # noqa: BLE001 — witness must never throw
        return "?"


_WITNESS = LockWitness()


def witness() -> LockWitness:
    return _WITNESS


def enable_lock_witness() -> None:
    _WITNESS.enabled = True


def disable_lock_witness() -> None:
    _WITNESS.enabled = False


def lock_witness_enabled() -> bool:
    return _WITNESS.enabled


def dump_lock_witness(path: str) -> str:
    return _WITNESS.dump(path)


def load_witness_dump(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if data.get("kind") != "yb-lock-witness":
        raise ValueError(f"{path}: not a lock-witness dump")
    return data


# -- the declaration decorator ------------------------------------------------

def guarded_by(lock_attr: str, *fields: str):
    """Class decorator: declare ``fields`` protected by ``self.<lock_attr>``.

    Pure-literal usage only (string constants), so the static pass can
    read the declaration off the AST::

        @guarded_by("_lock", "_state", "_opened_at")
        class CircuitBreaker: ...

    Stackable for classes with more than one lock.  At runtime the
    decorator records the mapping on the class and — only while the
    witness is enabled — instruments ``__setattr__`` to log whether the
    declared lock is held at each field rebind.  Writes inside
    ``__init__`` are construction, not sharing, and are not recorded.
    """
    if not isinstance(lock_attr, str) or not fields \
            or not all(isinstance(f, str) for f in fields):
        raise TypeError("guarded_by(lock_attr, *fields) takes string "
                        "literals")

    def deco(cls):
        decl = dict(getattr(cls, "__guarded_by__", {}))
        for f in fields:
            decl[f] = lock_attr
        cls.__guarded_by__ = decl
        locks = set(getattr(cls, "__guard_locks__", ()))
        locks.add(lock_attr)
        cls.__guard_locks__ = frozenset(locks)
        if cls.__dict__.get("__gb_instrumented__") is not True:
            _instrument(cls)
        return cls

    return deco


def _instrument(cls) -> None:
    import functools

    cls.__gb_instrumented__ = True
    orig_setattr = cls.__setattr__
    orig_init = cls.__init__

    def __setattr__(self, name, value):
        w = _WITNESS
        if w.enabled or _HOLD_TRACKING:
            klass = type(self)
            if name in klass.__guard_locks__ \
                    and not isinstance(value, _WitnessLock) \
                    and hasattr(value, "acquire"):
                value = _WitnessLock(value, klass.__name__)
            elif w.enabled:
                lock_attr = klass.__guarded_by__.get(name)
                if lock_attr is not None \
                        and getattr(self, "_gb_constructed", False):
                    w.record(klass.__name__, name, lock_attr,
                             _ownership(getattr(self, lock_attr, None)))
        orig_setattr(self, name, value)

    @functools.wraps(orig_init)
    def __init__(self, *args, **kwargs):
        orig_init(self, *args, **kwargs)
        try:
            object.__setattr__(self, "_gb_constructed", True)
        except AttributeError:
            pass  # __slots__ class: witness degrades to declarations-only

    cls.__setattr__ = __setattr__
    cls.__init__ = __init__
