"""Compact self-describing binary codec for WAL records and RPC payloads.

Reference analog: protobuf serialization of consensus/log records
(src/yb/consensus/consensus.proto, log.proto). A hand-rolled tagged format
keeps the framework dependency-free; the C++ runtime implements the same
format (native/codec.cc) so host tools can read WAL segments.

Wire grammar (tag byte, then payload):
  N 0x00 | T 0x01 | F 0x02 | I 0x03 varint(zigzag) | D 0x04 8B f64 LE
  S 0x05 varint len + utf8 | B 0x06 varint len + bytes
  L 0x07 varint count + items | M 0x08 varint count + key/value pairs
  X 0x09 varint len + rich-scalar component bytes (models.encoding)
"""

from __future__ import annotations

import struct

(_T_NONE, _T_TRUE, _T_FALSE, _T_INT, _T_F64, _T_STR, _T_BYTES, _T_LIST,
 _T_MAP, _T_EXT) = range(10)


def _write_varint(out: bytearray, v: int) -> None:
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    shift = 0
    result = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _zigzag(v: int) -> int:
    # Arbitrary-precision zigzag: non-negative -> 2v, negative -> -2v-1.
    return (v << 1) if v >= 0 else ((-v - 1) << 1) | 1


def _unzigzag(v: int) -> int:
    return (v >> 1) if not v & 1 else -((v >> 1) + 1)


def _encode_into(out: bytearray, v) -> None:
    if v is None:
        out.append(_T_NONE)
    elif v is True:
        out.append(_T_TRUE)
    elif v is False:
        out.append(_T_FALSE)
    elif isinstance(v, int):
        out.append(_T_INT)
        _write_varint(out, _zigzag(v))
    elif isinstance(v, float):
        out.append(_T_F64)
        out += struct.pack("<d", v)
    elif isinstance(v, str):
        raw = v.encode("utf-8", "surrogateescape")
        out.append(_T_STR)
        _write_varint(out, len(raw))
        out += raw
    elif isinstance(v, (bytes, bytearray, memoryview)):
        out.append(_T_BYTES)
        _write_varint(out, len(v))
        out += bytes(v)
    elif isinstance(v, (list, tuple)):
        out.append(_T_LIST)
        _write_varint(out, len(v))
        for item in v:
            _encode_into(out, item)
    elif isinstance(v, dict):
        out.append(_T_MAP)
        _write_varint(out, len(v))
        for k, val in v.items():
            _encode_into(out, k)
            _encode_into(out, val)
    else:
        from yugabyte_db_tpu.models.encoding import encode_component_value

        comp = encode_component_value(v)
        if comp is None:
            raise TypeError(f"codec cannot encode {type(v).__name__}")
        out.append(_T_EXT)
        _write_varint(out, len(comp))
        out += comp


def _py_encode(v) -> bytes:
    out = bytearray()
    _encode_into(out, v)
    return bytes(out)


def encode(v) -> bytes:
    if _native is not None:
        try:
            return _native.encode(v)
        except OverflowError:
            pass  # >64-bit int somewhere in v: arbitrary-precision path
    return _py_encode(v)


def _decode_from(buf: bytes, pos: int):
    tag = buf[pos]
    pos += 1
    if tag == _T_NONE:
        return None, pos
    if tag == _T_TRUE:
        return True, pos
    if tag == _T_FALSE:
        return False, pos
    if tag == _T_INT:
        raw, pos = _read_varint(buf, pos)
        return _unzigzag(raw), pos
    if tag == _T_F64:
        return struct.unpack_from("<d", buf, pos)[0], pos + 8
    if tag == _T_STR:
        n, pos = _read_varint(buf, pos)
        return buf[pos:pos + n].decode("utf-8", "surrogateescape"), pos + n
    if tag == _T_BYTES:
        n, pos = _read_varint(buf, pos)
        return bytes(buf[pos:pos + n]), pos + n
    if tag == _T_LIST:
        n, pos = _read_varint(buf, pos)
        items = []
        for _ in range(n):
            item, pos = _decode_from(buf, pos)
            items.append(item)
        return items, pos
    if tag == _T_EXT:
        from yugabyte_db_tpu.models.encoding import decode_component_value

        n, pos = _read_varint(buf, pos)
        return decode_component_value(buf[pos:pos + n]), pos + n
    if tag == _T_MAP:
        n, pos = _read_varint(buf, pos)
        d = {}
        for _ in range(n):
            k, pos = _decode_from(buf, pos)
            val, pos = _decode_from(buf, pos)
            d[k] = val
        return d, pos
    raise ValueError(f"codec: bad tag 0x{tag:02x} at {pos - 1}")


def _py_decode(buf: bytes):
    v, pos = _decode_from(buf, 0)
    if pos != len(buf):
        raise ValueError(f"codec: {len(buf) - pos} trailing bytes")
    return v


def decode(buf: bytes):
    if _native is not None:
        try:
            return _native.decode(buf)
        except OverflowError:
            pass  # varint beyond uint64: arbitrary-precision path
    return _py_decode(buf)


# Resolved LAST: yugabyte_db_tpu.native may build the extension on first
# import, and its fallback path needs this module fully defined.
try:
    from yugabyte_db_tpu.native import yb_codec as _native
except Exception:  # noqa: BLE001 — pure-Python fallback
    _native = None
