"""Master: the control plane — catalog, placement, liveness, balancing.

Reference analog: src/yb/master/ — CatalogManager (catalog_manager.cc,
CreateTable at :2015, CreateTabletsFromTable at :2274), the sys catalog
persisted through a Raft-replicated tablet (sys_catalog.h:75), TSManager
liveness from heartbeats (ts_manager.h), and ClusterLoadBalancer
(cluster_balance.cc). Masters form their own Raft group; only the leader
mutates the catalog, and every mutation is a replicated sys-catalog entry.
"""

from yugabyte_db_tpu.master.master import Master

__all__ = ["Master"]
