"""TSManager: tserver liveness + soft cluster state from heartbeats.

Reference analog: src/yb/master/ts_manager.{h,cc} + TSDescriptor — last
heartbeat time, reported tablets, and the per-tablet leader hints the
location cache serves. Soft state: NOT replicated, rebuilt from heartbeats
after master failover (exactly the reference's design).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


@dataclass
class TSDescriptor:
    uuid: str
    addr: object = None
    last_heartbeat: float = 0.0
    num_live_tablets: int = 0
    tablet_roles: dict = field(default_factory=dict)  # tablet_id -> role
    # Topology labels (reference: CloudInfoPB, master.proto:172):
    # {"cloud", "region", "zone"} — empty for unlabeled tservers.
    cloud_info: dict = field(default_factory=dict)


class TSManager:
    def __init__(self, unresponsive_timeout_s: float | None = None):
        if unresponsive_timeout_s is None:
            from yugabyte_db_tpu.utils.flags import FLAGS

            unresponsive_timeout_s = FLAGS.get(
                "follower_unavailable_considered_failed_sec")
        self._lock = threading.Lock()
        self._descs: dict[str, TSDescriptor] = {}
        # tablet_id -> (leader uuid, term): freshest leadership seen.
        self._tablet_leaders: dict[str, tuple[str, int]] = {}
        # tablet_id -> (raft config peers, term) as reported by the
        # freshest leader replica — the authoritative membership view the
        # repair paths compare against the catalog.
        self._tablet_configs: dict[str, tuple[tuple, int]] = {}
        # Split-manager inputs, from the LEADER replica's heartbeat
        # stats: on-disk size, and the raw data-op counter differentiated
        # across successive samples into an ops/s rate (soft state, like
        # everything else here).
        self._tablet_sizes: dict[str, int] = {}
        self._tablet_ops: dict[str, tuple[int, float]] = {}
        self._tablet_rates: dict[str, float] = {}
        self.unresponsive_timeout_s = unresponsive_timeout_s

    def heartbeat(self, req: dict) -> None:
        now = time.monotonic()
        with self._lock:
            d = self._descs.get(req["ts_uuid"])
            if d is None:
                d = TSDescriptor(req["ts_uuid"])
                self._descs[d.uuid] = d
            d.addr = req.get("addr")
            d.cloud_info = req.get("cloud_info") or {}
            d.last_heartbeat = now
            d.num_live_tablets = req.get("num_live_tablets", 0)
            # Normalize roles at the ingestion boundary: raft reports
            # "LEADER"/"FOLLOWER" (Role enum values) while every
            # consumer here compares lowercase.
            d.tablet_roles = {t["tablet_id"]: str(t.get("role", "")).lower()
                              for t in req.get("tablets", [])}
            for t in req.get("tablets", []):
                role = str(t.get("role", "")).lower()
                leader, term = t.get("leader"), t.get("term", 0)
                if leader:
                    cur = self._tablet_leaders.get(t["tablet_id"])
                    if cur is None or term >= cur[1]:
                        self._tablet_leaders[t["tablet_id"]] = (leader, term)
                if role == "leader" and t.get("peers"):
                    cur = self._tablet_configs.get(t["tablet_id"])
                    if cur is None or term >= cur[1]:
                        self._tablet_configs[t["tablet_id"]] = (
                            tuple(t["peers"]), term)
                st = t.get("stats")
                if st and role == "leader":
                    tid = t["tablet_id"]
                    self._tablet_sizes[tid] = st.get("size_bytes", 0)
                    ops = st.get("ops_seen", 0)
                    prev = self._tablet_ops.get(tid)
                    self._tablet_ops[tid] = (ops, now)
                    if prev is not None and now > prev[1]:
                        delta = ops - prev[0]
                        if delta < 0:
                            # counter restarted (tserver bounce or
                            # leadership moved to a fresh replica)
                            delta = ops
                        self._tablet_rates[tid] = \
                            delta / (now - prev[1])

    def live_tservers(self) -> list[TSDescriptor]:
        cutoff = time.monotonic() - self.unresponsive_timeout_s
        with self._lock:
            return [d for d in self._descs.values()
                    if d.last_heartbeat >= cutoff]

    def dead_tservers(self) -> list[TSDescriptor]:
        cutoff = time.monotonic() - self.unresponsive_timeout_s
        with self._lock:
            return [d for d in self._descs.values()
                    if d.last_heartbeat < cutoff]

    def all_tservers(self) -> list[TSDescriptor]:
        with self._lock:
            return list(self._descs.values())

    def leader_of(self, tablet_id: str) -> str | None:
        with self._lock:
            v = self._tablet_leaders.get(tablet_id)
            return v[0] if v else None

    def config_of(self, tablet_id: str) -> tuple | None:
        """Raft config peers as last reported by the tablet's leader."""
        with self._lock:
            v = self._tablet_configs.get(tablet_id)
            return v[0] if v else None

    def addr_of(self, uuid: str):
        with self._lock:
            d = self._descs.get(uuid)
            return d.addr if d else None

    def cloud_info_of(self, uuid: str) -> dict:
        with self._lock:
            d = self._descs.get(uuid)
            return dict(d.cloud_info) if d else {}

    def tablet_load(self, tablet_id: str) -> tuple[int, float]:
        """(size_bytes, ops_per_sec) from the leader's latest heartbeat
        stats — the split manager's trigger inputs."""
        with self._lock:
            return (self._tablet_sizes.get(tablet_id, 0),
                    self._tablet_rates.get(tablet_id, 0.0))

    def forget_tablet(self, tablet_id: str) -> None:
        """Drop soft per-tablet state after a split removes the tablet
        (stale rate samples must not re-trigger on a reused id)."""
        with self._lock:
            self._tablet_sizes.pop(tablet_id, None)
            self._tablet_ops.pop(tablet_id, None)
            self._tablet_rates.pop(tablet_id, None)
            self._tablet_leaders.pop(tablet_id, None)
            self._tablet_configs.pop(tablet_id, None)

    def leader_counts(self) -> dict[str, int]:
        """LIVE tserver uuid -> number of tablet leaders it hosts (the
        leader balancer's skew input). Every live tserver appears, even
        with zero leaders — an idle node is the balancer's best target."""
        cutoff = time.monotonic() - self.unresponsive_timeout_s
        with self._lock:
            return {d.uuid: sum(1 for r in d.tablet_roles.values()
                                if r == "leader")
                    for d in self._descs.values()
                    if d.last_heartbeat >= cutoff}
