"""Catalog state: tables and tablets, mutated only by replicated entries.

Reference analog: the sys-catalog row types (src/yb/master/catalog_manager.h
TableInfo/TabletInfo, master.proto SysTablesEntryPB/SysTabletsEntryPB).
Every mutation is an op dict replicated through the masters' Raft group and
applied here deterministically on each master.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from yugabyte_db_tpu.utils.metrics import count_swallowed


@dataclass
class TabletInfo:
    tablet_id: str
    table_id: str
    partition_start: int
    partition_end: int
    replicas: list[str] = field(default_factory=list)  # intended node uuids


@dataclass
class TableInfo:
    table_id: str
    name: str
    schema: dict                       # Schema.to_dict()
    num_tablets: int
    tablet_ids: list[str] = field(default_factory=list)
    state: str = "RUNNING"
    engine: str = "cpu"
    # Secondary indexes ON this table: [{"name", "column", "index_table"}]
    # (reference: IndexInfo entries in SysTablesEntryPB, common/index.h).
    indexes: list[dict] = field(default_factory=list)


class CatalogState:
    """Deterministic state machine over replicated catalog ops."""

    def __init__(self):
        from yugabyte_db_tpu.auth import RoleStore

        self._lock = threading.RLock()
        self.tables: dict[str, TableInfo] = {}
        self.tables_by_name: dict[str, str] = {}
        self.tablets: dict[str, TabletInfo] = {}
        # Roles/permissions ride the same replicated catalog pipeline
        # (reference: role records in the sys catalog, master.proto:1383).
        self.auth = RoleStore()
        # User-defined types: name -> [(field, dtype int)] (reference:
        # UDTypeInfo records in the sys catalog, pt_create_type.cc).
        self.types: dict[str, list] = {}
        # SQL views (name -> defining query) and sequences (name -> next
        # value) — replicated catalog records (reference: pg_rewrite /
        # sequence relations in the PG fork's catalog).
        self.views: dict[str, str] = {}
        self.sequences: dict[str, int] = {}
        # CQL keyspaces (reference: SysNamespaceEntryPB records in the
        # sys catalog) — shared across every connection/session.
        self.user_keyspaces: set[str] = set()
        # Cluster snapshots: id -> {"table", "state", "tablets"} —
        # master-coordinated registry over the per-tablet snapshot ops
        # (reference: SysSnapshotEntryPB states driven by
        # src/yb/tserver/backup.proto TabletSnapshotOp).
        self.snapshots: dict[str, dict] = {}
        # Tablet-split lineage: parent tablet_id -> {"table_id",
        # "split_hash", "children": [low_id, high_id], "state"
        # ("SPLITTING" until split_commit, then "COMMITTED")}. Kept
        # after commit for the /dashboards/tablets lineage view
        # (reference: the split_parent_tablet_id back-links of
        # SysTabletsEntryPB).
        self.splits: dict[str, dict] = {}

    def apply(self, op: dict) -> None:
        kind = op["op"]
        if kind.startswith("auth_"):
            # Replicas hold identical state at each log index, so a
            # validation failure here is the SAME no-op on every replica
            # (the leader pre-validates; this guards races + replays).
            try:
                self.auth.apply(op)
            except Exception as e:  # noqa: BLE001
                count_swallowed("catalog.auth_apply", e)
            return
        with self._lock:
            if kind == "create_view":
                self.views[op["name"]] = op["query"]
                return
            if kind == "drop_view":
                self.views.pop(op["name"], None)
                return
            if kind == "create_keyspace":
                self.user_keyspaces.add(op["name"])
                return
            if kind == "drop_keyspace":
                self.user_keyspaces.discard(op["name"])
                return
            if kind == "create_sequence":
                self.sequences.setdefault(op["name"], 1)
                return
            if kind == "drop_sequence":
                self.sequences.pop(op["name"], None)
                return
            if kind == "sequence_alloc":
                self.sequences[op["name"]] = \
                    self.sequences.get(op["name"], 1) + op["n"]
                return
            if kind == "snapshot_record":
                self.snapshots[op["snapshot_id"]] = {
                    "table": op["table"], "state": op["state"],
                    "tablets": list(op.get("tablets", ()))}
                return
            if kind == "snapshot_remove":
                self.snapshots.pop(op["snapshot_id"], None)
                return
            if kind == "create_type":
                self.types[op["name"]] = [tuple(f) for f in op["fields"]]
                return
            if kind == "drop_type":
                self.types.pop(op["name"], None)
                return
            if kind == "create_table":
                t = TableInfo(op["table_id"], op["name"], op["schema"],
                              op["num_tablets"], engine=op.get("engine", "cpu"))
                for td in op["tablets"]:
                    info = TabletInfo(td["tablet_id"], t.table_id,
                                      td["partition_start"],
                                      td["partition_end"],
                                      list(td["replicas"]))
                    self.tablets[info.tablet_id] = info
                    t.tablet_ids.append(info.tablet_id)
                self.tables[t.table_id] = t
                self.tables_by_name[t.name] = t.table_id
            elif kind == "delete_table":
                t = self.tables.pop(op["table_id"], None)
                if t is not None:
                    self.tables_by_name.pop(t.name, None)
                    for tid in t.tablet_ids:
                        self.tablets.pop(tid, None)
            elif kind == "set_tablet_replicas":
                info = self.tablets.get(op["tablet_id"])
                if info is not None:
                    info.replicas = list(op["replicas"])
            elif kind == "create_index":
                t = self.tables.get(op["table_id"])
                if t is not None and not any(
                        i["name"] == op["index"]["name"]
                        for i in t.indexes):
                    t.indexes.append(dict(op["index"]))
            elif kind == "drop_index":
                t = self.tables.get(op["table_id"])
                if t is not None:
                    t.indexes = [i for i in t.indexes
                                 if i["name"] != op["name"]]
            elif kind == "split_tablet":
                # Phase 2 of a tablet split: register BOTH children (with
                # their intended replica sets) and the lineage BEFORE any
                # child replica exists, so the heartbeat orphan-GC never
                # mistakes a freshly created child for a deleted tablet.
                # Children are NOT yet in table.tablet_ids: lookups keep
                # resolving to the parent until split_commit swaps them.
                t = self.tables.get(op["table_id"])
                if t is None or op["tablet_id"] not in self.tablets:
                    return  # replay after delete_table / double apply
                for cd in op["children"]:
                    if cd["tablet_id"] not in self.tablets:
                        self.tablets[cd["tablet_id"]] = TabletInfo(
                            cd["tablet_id"], t.table_id,
                            cd["partition_start"], cd["partition_end"],
                            list(cd["replicas"]))
                self.splits[op["tablet_id"]] = {
                    "table_id": t.table_id,
                    "split_hash": op["split_hash"],
                    "children": [cd["tablet_id"]
                                 for cd in op["children"]],
                    "state": "SPLITTING"}
            elif kind == "split_commit":
                # Phase 6: atomically swap parent -> children in the
                # table's serving list and drop the parent TabletInfo —
                # the next heartbeat's orphan-GC tombstones its replicas.
                t = self.tables.get(op["table_id"])
                parent_id = op["tablet_id"]
                if t is not None and parent_id in t.tablet_ids:
                    idx = t.tablet_ids.index(parent_id)
                    t.tablet_ids[idx:idx + 1] = [
                        c for c in op["children"]
                        if c not in t.tablet_ids]
                self.tablets.pop(parent_id, None)
                s = self.splits.get(parent_id)
                if s is not None:
                    s["state"] = "COMMITTED"
            elif kind == "alter_table":
                t = self.tables.get(op["table_id"])
                # versions only move forward (idempotent across replays)
                if t is not None and op["schema"].get("version", 0) > \
                        t.schema.get("version", 0):
                    t.schema = op["schema"]
            else:
                raise ValueError(f"unknown catalog op {kind!r}")

    # -- reads (soft, lock-protected) ---------------------------------------
    def table_by_name(self, name: str) -> TableInfo | None:
        with self._lock:
            tid = self.tables_by_name.get(name)
            return self.tables.get(tid) if tid else None

    def list_tables(self) -> list[TableInfo]:
        with self._lock:
            return list(self.tables.values())

    def tablets_of(self, table_id: str) -> list[TabletInfo]:
        with self._lock:
            t = self.tables.get(table_id)
            if t is None:
                return []
            return [self.tablets[tid] for tid in t.tablet_ids
                    if tid in self.tablets]

    def known_tablet_ids(self) -> set[str]:
        with self._lock:
            return set(self.tablets)

    def split_lineage(self) -> list[dict]:
        """Parent -> children rows for the tablets dashboard."""
        with self._lock:
            return [{"parent": pid, "table_id": s["table_id"],
                     "split_hash": s["split_hash"],
                     "children": list(s["children"]),
                     "state": s["state"]}
                    for pid, s in self.splits.items()]
