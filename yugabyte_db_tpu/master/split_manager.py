"""SplitManager: master-driven tablet splitting.

Reference analog: src/yb/master/tablet_split_manager.cc — the background
pass over heartbeat-reported tablet stats that picks oversized / overloaded
tablets and drives the split state machine, plus the manual SplitTablet
admin RPC entry point.

Split protocol (each numbered phase is restartable — the replicated
lineage record in CatalogState.splits is the recovery point):

  1. ts.get_split_key     parent leader flushes and returns the median
                          resident key hash (split point).
  2. split_tablet op      children + lineage registered in the replicated
                          catalog BEFORE any child replica exists, so the
                          heartbeat orphan-GC never deletes a half-created
                          child. Lookups still resolve to the parent.
  3. ts.create_tablet     empty children dispatched to the parent's
                          replica set; wait for each child to elect a
                          leader (heartbeat-fed ts_manager).
  4. ts.split_seal        parent stops admitting writes by replicating a
                          seal entry through its OWN Raft log — every
                          acked write sits below the seal.
  5. ts.split_fork/seed   frozen parent rows, range-clamped per child,
                          replicated through each CHILD leader's Raft log
                          with their original hybrid times (identical
                          state on every child replica).
  6. split_commit op      parent -> children swapped in the table's
                          serving list; the parent's replicas are
                          tombstoned (explicit delete + heartbeat GC).

Clients addressing the parent after phase 4 get the "tablet_split" wire
code and re-plan from fresh locations at TABLET granularity.
"""

from __future__ import annotations

import threading
import time
import uuid as uuid_mod

from yugabyte_db_tpu.utils.flags import FLAGS
from yugabyte_db_tpu.utils.metrics import count_swallowed, count_tablet_split


class SplitError(Exception):
    pass


class SplitManager:
    def __init__(self, master):
        self.m = master
        self._lock = threading.Lock()
        self._splitting: set[str] = set()  # parent ids with a split driving
        self.splits_done = 0  # observability / tests

    # -- automatic pass (called from the master's balancer loop) -------------
    def run_pass(self) -> None:
        size_thr = FLAGS.get("tablet_split_size_bytes")
        rate_thr = FLAGS.get("tablet_split_ops_per_sec")
        if not self.m.raft.leader_ready():
            return
        # Resume any split interrupted mid-protocol (master failover /
        # crashed pass): the lineage record is the durable to-do item.
        for rec in self.m.catalog.split_lineage():
            if rec["state"] == "SPLITTING":
                self._try_split(rec["parent"])
                return  # one split per pass
        if not size_thr and not rate_thr:
            return  # automatic splitting disabled
        for t in self.m.catalog.list_tables():
            for info in self.m.catalog.tablets_of(t.table_id):
                if info.partition_end - info.partition_start < 2:
                    continue  # single-hash range: nothing to split
                size, rate = self.m.ts_manager.tablet_load(info.tablet_id)
                if (size_thr and size >= size_thr) or \
                        (rate_thr and rate >= rate_thr):
                    self._try_split(info.tablet_id)
                    return  # one split per pass (bounded churn)

    def _try_split(self, tablet_id: str) -> None:
        try:
            self.split(tablet_id)
        except Exception as e:  # noqa: BLE001 — next pass retries
            count_swallowed("master.split_tablet", e)

    # -- the split state machine ---------------------------------------------
    def split(self, tablet_id: str, timeout: float = 30.0) -> dict:
        """Drive one tablet split end to end (synchronous). Safe to call
        again after a partial failure: every phase is idempotent and the
        lineage record carries the chosen children across retries."""
        with self._lock:
            if tablet_id in self._splitting:
                raise SplitError(f"split of {tablet_id} already running")
            self._splitting.add(tablet_id)
        try:
            return self._split_locked(tablet_id, timeout)
        finally:
            with self._lock:
                self._splitting.discard(tablet_id)

    def _split_locked(self, tablet_id: str, timeout: float) -> dict:
        deadline = time.monotonic() + timeout
        cat = self.m.catalog
        info = cat.tablets.get(tablet_id)
        if info is None:
            raise SplitError(f"tablet {tablet_id} not in catalog")
        table = cat.tables.get(info.table_id)
        if table is None:
            raise SplitError(f"table of {tablet_id} not in catalog")

        rec = cat.splits.get(tablet_id)
        if rec is None:
            # Phase 1: the parent leader's median resident key hash.
            resp = self._leader_rpc(tablet_id, info.replicas,
                                    "ts.get_split_key",
                                    {"tablet_id": tablet_id}, deadline)
            h = resp["split_hash"]
            if not (info.partition_start < h < info.partition_end):
                raise SplitError(
                    f"split hash {h} outside ({info.partition_start}, "
                    f"{info.partition_end})")
            # Phase 2: replicate children + lineage. Low child first so
            # the committed tablet_ids list stays partition-ordered.
            children = [
                {"tablet_id": f"{table.table_id}-s{uuid_mod.uuid4().hex[:8]}",
                 "partition_start": info.partition_start,
                 "partition_end": h,
                 "replicas": list(info.replicas)},
                {"tablet_id": f"{table.table_id}-s{uuid_mod.uuid4().hex[:8]}",
                 "partition_start": h,
                 "partition_end": info.partition_end,
                 "replicas": list(info.replicas)},
            ]
            self.m.raft.replicate("catalog", {
                "op": "split_tablet", "table_id": table.table_id,
                "tablet_id": tablet_id, "split_hash": h,
                "children": children})
            rec = cat.splits.get(tablet_id)
            if rec is None:
                raise SplitError(f"lineage for {tablet_id} did not apply")

        child_ids = list(rec["children"])
        child_infos = [cat.tablets[c] for c in child_ids]

        # Phase 3: empty child replicas on the parent's replica set.
        for ci in child_infos:
            for replica in ci.replicas:
                try:
                    resp = self.m.transport.send(
                        replica, "ts.create_tablet",
                        self.m._create_tablet_req(
                            ci.tablet_id, table.name, table.schema,
                            ci.partition_start, ci.partition_end,
                            table.engine, list(ci.replicas),
                            indexes=table.indexes),
                        timeout=5.0)
                    if resp.get("code") != "ok":
                        count_swallowed("master.split_create_child",
                                        resp.get("code"))
                except Exception as e:  # noqa: BLE001 — leader wait gates
                    count_swallowed("master.split_create_child", e)
        for ci in child_infos:
            self._wait_child_leader(ci.tablet_id, deadline)

        # Phase 4: seal the parent (idempotent on the peer).
        self._leader_rpc(tablet_id, info.replicas, "ts.split_seal",
                         {"tablet_id": tablet_id}, deadline)

        # Phase 5: fork the frozen rows per child range and seed each
        # child through its own leader.
        for ci in child_infos:
            fork = self._leader_rpc(
                tablet_id, info.replicas, "ts.split_fork",
                {"tablet_id": tablet_id, "lower": ci.partition_start,
                 "upper": ci.partition_end}, deadline)
            self._leader_rpc(
                ci.tablet_id, ci.replicas, "ts.split_seed",
                {"tablet_id": ci.tablet_id, "rows": fork["rows"]},
                deadline, timeout_each=30.0)

        # Phase 6: commit the swap; the parent leaves the serving list.
        self.m.raft.replicate("catalog", {
            "op": "split_commit", "table_id": table.table_id,
            "tablet_id": tablet_id, "children": child_ids})
        count_tablet_split()
        self.splits_done += 1
        self.m.ts_manager.forget_tablet(tablet_id)
        # Prompt tombstone; the heartbeat orphan-GC is the backstop.
        for replica in info.replicas:
            try:
                resp = self.m.transport.send(replica, "ts.delete_tablet",
                                             {"tablet_id": tablet_id},
                                             timeout=5.0)
                if resp.get("code") != "ok":
                    count_swallowed("master.split_delete_parent",
                                    resp.get("code"))
            except Exception as e:  # noqa: BLE001 — GC retries
                count_swallowed("master.split_delete_parent", e)
        return {"tablet_id": tablet_id, "split_hash": rec["split_hash"],
                "children": child_ids}

    # -- helpers -------------------------------------------------------------
    def _wait_child_leader(self, tablet_id: str, deadline: float) -> str:
        while time.monotonic() < deadline:
            leader = self.m.ts_manager.leader_of(tablet_id)
            if leader is not None:
                return leader
            time.sleep(0.05)
        raise SplitError(f"child {tablet_id} elected no leader in time")

    def _leader_rpc(self, tablet_id: str, replicas, method: str,
                    payload: dict, deadline: float,
                    timeout_each: float = 10.0) -> dict:
        """Send one RPC to the tablet's leader, following not_leader
        hints and re-resolving through heartbeats until the deadline."""
        last = "no attempt"
        while time.monotonic() < deadline:
            candidates = []
            hinted = self.m.ts_manager.leader_of(tablet_id)
            if hinted:
                candidates.append(hinted)
            candidates.extend(r for r in replicas if r not in candidates)
            for dst in candidates:
                try:
                    resp = self.m.transport.send(
                        dst, method, payload,
                        timeout=min(timeout_each,
                                    max(0.1, deadline - time.monotonic())))
                except Exception as e:  # noqa: BLE001 — try the next
                    last = str(e)
                    continue
                if resp.get("code") == "ok":
                    return resp
                last = f"{dst}: {resp.get('message', resp.get('code'))}"
                if resp.get("code") == "error":
                    # definitive refusal (e.g. no interior split point):
                    # retrying cannot help within this attempt
                    raise SplitError(
                        f"{method} on {tablet_id} failed: {last}")
                hint = resp.get("leader_hint")
                if hint and hint not in candidates:
                    candidates.append(hint)
            time.sleep(0.05)
        raise SplitError(f"{method} on {tablet_id} failed: {last}")
