"""LeaderBalancer: the leader-balancing half of the cluster balancer.

Reference analog: the leader-move side of src/yb/master/cluster_balance.cc
(HandleLeaderMoves): compute per-tserver leader counts from heartbeat soft
state, and when the spread between the most- and least-loaded live
tservers reaches 2, step ONE leader down toward the least-loaded tserver.
One move per pass bounds churn — leadership transfer costs an election
round and a client re-route, so the balancer walks toward even rather
than jumping.
"""

from __future__ import annotations

import time

from yugabyte_db_tpu.utils.flags import FLAGS
from yugabyte_db_tpu.utils.metrics import count_leader_move, count_swallowed


class LeaderBalancer:
    def __init__(self, master, min_move_interval_s: float = 1.0):
        self.m = master
        self.moves_done = 0  # observability / tests
        # Debounce between moves: the skew input is heartbeat-fed soft
        # state, so a transfer needs a heartbeat round to show up in the
        # counts — moving again before that re-fixes stale skew.
        self.min_move_interval_s = min_move_interval_s
        self._last_move = 0.0

    def run_pass(self, force: bool = False) -> dict | None:
        """One balancing pass; returns the move made (or None). ``force``
        (the master.rebalance admin RPC) ignores the enable flag."""
        if not force and not FLAGS.get("enable_leader_balancing"):
            return None
        if not self.m.raft.leader_ready():
            return None
        if time.monotonic() - self._last_move < self.min_move_interval_s:
            return None
        counts = self.m.ts_manager.leader_counts()
        if len(counts) < 2:
            return None
        hi = max(counts, key=lambda u: counts[u])
        lo = min(counts, key=lambda u: counts[u])
        if counts[hi] - counts[lo] < 2:
            return None  # balanced enough; a 1-leader spread is parity
        # Find a tablet the loaded tserver leads whose replica set
        # includes the underloaded one (the target must hold a replica to
        # be electable).
        for t in self.m.catalog.list_tables():
            for info in self.m.catalog.tablets_of(t.table_id):
                if lo not in info.replicas:
                    continue
                if self.m.ts_manager.leader_of(info.tablet_id) != hi:
                    continue
                try:
                    resp = self.m.transport.send(
                        hi, "ts.transfer_leadership",
                        {"tablet_id": info.tablet_id, "target": lo},
                        timeout=5.0)
                except Exception as e:  # noqa: BLE001 — next pass retries
                    count_swallowed("master.leader_move", e)
                    return None
                if resp.get("code") != "ok":
                    count_swallowed("master.leader_move",
                                    resp.get("code"))
                    return None
                count_leader_move()
                self.moves_done += 1
                self._last_move = time.monotonic()
                return {"tablet_id": info.tablet_id,
                        "from": hi, "to": lo}
        return None
