"""Master daemon: Raft-replicated catalog + control-plane services.

Reference analog: src/yb/master/master.cc + catalog_manager.cc. The sys
catalog is itself a Raft group over the master set (sys_catalog.h:75 "the
sys catalog is a tablet"); CreateTable picks placements over live tservers
and async-creates replicas on them (CreateTabletsFromTable,
catalog_manager.cc:2274, async_rpc_tasks.cc); TS liveness and tablet
leadership are soft state from heartbeats; a background loop re-replicates
tablets off dead tservers (ClusterLoadBalancer's remove/add logic,
cluster_balance.cc).
"""

from __future__ import annotations

import os
import threading
import time
import uuid as uuid_mod

from yugabyte_db_tpu.consensus.metadata import ConsensusMetadata, RaftConfig
from yugabyte_db_tpu.consensus.raft import NotLeader, RaftConsensus, RaftOptions
from yugabyte_db_tpu.master.catalog import CatalogState
from yugabyte_db_tpu.master.load_balancer import LeaderBalancer
from yugabyte_db_tpu.master.split_manager import SplitError, SplitManager
from yugabyte_db_tpu.master.ts_manager import TSManager
from yugabyte_db_tpu.models.partition import PartitionSchema
from yugabyte_db_tpu.models.schema import Schema
from yugabyte_db_tpu.tablet.wal import Log
from yugabyte_db_tpu.utils.hybrid_time import HybridClock
from yugabyte_db_tpu.utils.metrics import count_swallowed
from yugabyte_db_tpu.utils.retry import Deadline
from yugabyte_db_tpu.utils.trace import RpczStore, trace_request

SYS_CATALOG_ID = "sys.catalog"


class Master:
    def __init__(self, uuid: str, fs_root: str, transport,
                 master_uuids: list[str],
                 raft_opts: RaftOptions | None = None,
                 fsync: bool = True,
                 ts_unresponsive_timeout_s: float | None = None,
                 balance_interval_s: float = 1.0,
                 missing_replica_grace_s: float = 10.0,
                 advertised_addr=None, options=None):
        # Structured options (server.options.MasterOptions) override the
        # loose kwargs when provided.
        if options is not None:
            fsync = options.fsync
            ts_unresponsive_timeout_s = options.resolved_ts_timeout()
            balance_interval_s = options.balance_interval_s
            missing_replica_grace_s = options.missing_replica_grace_s
        self.options = options
        self.uuid = uuid
        self.transport = transport
        self.advertised_addr = advertised_addr
        from yugabyte_db_tpu import fs as _fs

        self.instance = _fs.format_or_open(fs_root, uuid)
        self.catalog = CatalogState()
        self.ts_manager = TSManager(ts_unresponsive_timeout_s)
        self.split_manager = SplitManager(self)
        self.load_balancer = LeaderBalancer(self)
        self.balance_interval_s = balance_interval_s
        self.clock = HybridClock()
        sys_dir = os.path.join(fs_root, "sys-catalog")
        os.makedirs(sys_dir, exist_ok=True)
        self._log = Log(os.path.join(sys_dir, "wal"), fsync=fsync)
        cmeta = ConsensusMetadata(
            os.path.join(sys_dir, "consensus-meta.json"), uuid,
            RaftConfig(list(master_uuids)))
        self.raft = RaftConsensus(SYS_CATALOG_ID, cmeta, self._log, transport,
                                  self.clock, self._apply_catalog, raft_opts)
        self._running = False
        self._balancer_thread: threading.Thread | None = None
        self._fixing: dict[str, float] = {}  # tablet_id -> fix start time
        # (tablet_id, replica) creates that FAILED to dispatch: the balancer
        # retries exactly these directly. Recreating any other missing
        # replica in place would be unsafe — a voter that lost its disk must
        # not be handed a fresh empty log while still counted in the config
        # (it could elect a leader without committed entries). Missing
        # replicas NOT tracked here (e.g. the set was lost to a master
        # restart) are repaired through a config cycle instead
        # (_repair_live_missing_replicas).
        self._failed_creates: set[tuple[str, str]] = set()
        self._seq_lock = threading.Lock()  # serializes sequence allocs
        # (table_id, tablet_id) whose leaders haven't adopted the latest
        # catalog schema yet; the balancer retries delivery.
        self._pending_alters: set[tuple[str, str]] = set()
        self.missing_replica_grace_s = missing_replica_grace_s
        # (tablet_id, replica) -> first time a live tserver's heartbeat was
        # seen not reporting a replica the catalog assigns to it.
        self._missing_seen: dict[tuple[str, str], float] = {}
        from yugabyte_db_tpu.utils.metrics import MetricRegistry

        self.metrics = MetricRegistry()
        self._rpc_entities: dict = {}
        self._rpc_lock = threading.Lock()
        ent = self.metrics.entity(daemon="master", uuid=uuid)
        ent.gauge("master_is_leader", lambda: int(self.is_leader()))
        ent.gauge("master_num_tables",
                  lambda: len(self.catalog.list_tables()))
        ent.gauge("master_num_tablets",
                  lambda: len(self.catalog.known_tablet_ids()))
        ent.gauge("master_live_tservers",
                  lambda: len(self.ts_manager.live_tservers()))
        self.webserver = None
        self.rpcz = RpczStore()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        self._running = True
        if self.options is not None and self.options.webserver:
            self.start_webserver(self.options.webserver_host,
                                 self.options.webserver_port)
        self.raft.start()
        self._balancer_thread = threading.Thread(
            target=self._balancer_loop, name=f"balancer-{self.uuid}",
            daemon=True)
        self._balancer_thread.start()

    def shutdown(self) -> None:
        self._running = False
        if self.webserver is not None:
            self.webserver.stop()
        self.raft.shutdown()
        if self._balancer_thread is not None:
            self._balancer_thread.join(timeout=5.0)
        self._log.close()

    def is_leader(self) -> bool:
        return self.raft.is_leader()

    def _apply_catalog(self, entry) -> None:
        if entry.op_type == "catalog":
            self.catalog.apply(entry.body)

    def start_webserver(self, host: str = "127.0.0.1", port: int = 0):
        from yugabyte_db_tpu.server.webserver import Webserver

        self.webserver = Webserver(self.metrics, f"master-{self.uuid}")

        # single row builders per entity: JSON API and dashboards agree
        def _tables_rows():
            return [{"name": t.name, "table_id": t.table_id,
                     "state": t.state, "num_tablets": t.num_tablets,
                     "schema_version": t.schema.get("version", 0),
                     "indexes": [i["name"] for i in t.indexes]}
                    for t in self.catalog.list_tables()]

        def _tablets_rows():
            # split lineage annotations: a serving child links back to
            # its parent; lineage records themselves are separate rows.
            child_of = {c: pid
                        for pid, s in self.catalog.splits.items()
                        for c in s["children"]}
            return [{"tablet_id": i.tablet_id, "table_id": i.table_id,
                     "leader": self.ts_manager.leader_of(i.tablet_id),
                     "replicas": i.replicas,
                     "split_parent": child_of.get(i.tablet_id)}
                    for t in self.catalog.list_tables()
                    for i in self.catalog.tablets_of(t.table_id)]

        def _splits_rows():
            return [{"parent": r["parent"],
                     "children": " ".join(r["children"]),
                     "split_hash": r["split_hash"],
                     "state": r["state"]}
                    for r in self.catalog.split_lineage()]

        self.webserver.add_json_handler("/tables", _tables_rows)
        self.webserver.add_json_handler("/tablets", _tablets_rows)
        self.webserver.add_json_handler("/tablet-splits", _splits_rows)
        self.webserver.add_json_handler("/rpcz", self.rpcz.dump)

        def _tservers_rows():
            import time as _t

            live = {d.uuid for d in self.ts_manager.live_tservers()}
            return [{"uuid": d.uuid, "live": d.uuid in live,
                     "tablets": d.num_live_tablets,
                     # balancer skew input: leaders this tserver hosts
                     "leaders": sum(1 for r in d.tablet_roles.values()
                                    if r == "leader"),
                     "last_heartbeat_age_s": round(
                         _t.monotonic() - d.last_heartbeat, 1)}
                    for d in self.ts_manager.all_tservers()]

        self.webserver.add_dashboard("/dashboards/tables", "Tables",
                                     _tables_rows)
        self.webserver.add_dashboard("/dashboards/tablets", "Tablets",
                                     _tablets_rows)
        self.webserver.add_dashboard("/dashboards/tablet-splits",
                                     "Tablet splits", _splits_rows)
        self.webserver.add_dashboard("/dashboards/tablet-servers",
                                     "Tablet servers", _tservers_rows)
        return self.webserver.start(host, port)

    def _rpc_entity(self, method: str):
        ent = self._rpc_entities.get(method)
        if ent is None:
            with self._rpc_lock:
                ent = self._rpc_entities.get(method)
                if ent is None:
                    ent = self.metrics.entity(daemon="master",
                                              uuid=self.uuid,
                                              method=method)
                    self._rpc_entities[method] = ent
        return ent

    # -- rpc dispatch --------------------------------------------------------
    def handle(self, method: str, payload: dict):
        start = time.monotonic()
        with trace_request(method) as t:
            try:
                return self._dispatch(method, payload)
            finally:
                ent = self._rpc_entity(method)
                ent.counter("rpc_requests_total").increment()
                ent.histogram("rpc_latency_us").observe_duration_us(start)
                t.finish()  # duration must be final before sampling
                self.rpcz.record(t)

    def _dispatch(self, method: str, payload: dict):
        if method.startswith("raft."):
            return self.raft.handle(method, payload)
        handler = getattr(self, "_h_" + method.replace(".", "_"), None)
        if handler is None:
            raise ValueError(f"unknown method {method}")
        return handler(payload)

    def _not_leader(self) -> dict:
        return {"code": "not_leader", "leader_hint": self.raft.leader_uuid()}

    @staticmethod
    def _op_deadline(p: dict) -> Deadline:
        """The client's remaining budget for a replicated catalog op
        (PR-7 deadline propagation): the append backpressure wait and
        the apply wait debit this ONE deadline instead of restarting a
        hardcoded 10 s at each layer."""
        return Deadline.after(float(p.get("timeout", 10.0)))

    # -- ddl ----------------------------------------------------------------
    def _h_master_create_table(self, p: dict):
        if not self.raft.is_leader():
            return self._not_leader()
        name = p["name"]
        if self.catalog.table_by_name(name) is not None:
            return {"code": "already_present", "table_id":
                    self.catalog.table_by_name(name).table_id}
        schema = Schema.from_dict(p["schema"])
        num_tablets = p.get("num_tablets", 4)
        rf = p.get("replication_factor", 3)
        engine = p.get("engine", "cpu")
        live = sorted(self.ts_manager.live_tservers(),
                      key=lambda d: d.num_live_tablets)
        if len(live) < rf:
            return {"code": "error",
                    "message": f"{len(live)} live tservers < RF {rf}"}
        table_id = uuid_mod.uuid4().hex[:16]
        parts = PartitionSchema(
            num_tablets, hash_partitioned=schema.num_hash > 0
        ).create_partitions()
        tablets = []
        # Topology-aware placement: spread each tablet's replicas across
        # the fewest-used (cloud, region, zone) groups, least-loaded
        # tserver within a group; load counts include this table's own
        # placements so tablets spread too (reference:
        # CatalogManager::SelectReplicas honoring PlacementInfoPB,
        # src/yb/master/master.proto:186-197).
        load = {d.uuid: d.num_live_tablets for d in live}
        for i, part in enumerate(parts):
            replicas = self._select_replicas(live, rf, load)
            for r in replicas:
                load[r] += 1
            tablets.append({
                "tablet_id": f"{table_id}-t{i:04d}",
                "partition_start": part.start,
                "partition_end": part.end,
                "replicas": replicas,
            })
        op = {"op": "create_table", "table_id": table_id, "name": name,
              "schema": schema.to_dict(), "num_tablets": len(parts),
              "engine": engine, "tablets": tablets}
        try:
            self.raft.replicate("catalog", op, timeout=self._op_deadline(p))
        except NotLeader:
            return self._not_leader()
        errors = self._dispatch_tablet_creates(op)
        if errors:
            return {"code": "partial", "table_id": table_id, "errors": errors}
        return {"code": "ok", "table_id": table_id}

    @staticmethod
    def _zone_of(desc) -> tuple:
        ci = desc.cloud_info or {}
        return (ci.get("cloud", ""), ci.get("region", ""),
                ci.get("zone", ""))

    def _select_replicas(self, live, rf: int, load: dict,
                         exclude=(), existing_zones=()) -> list[str]:
        """Pick up to ``rf`` tservers spreading across availability
        zones: each pick takes the least-used zone (counting
        ``existing_zones`` — the zones of replicas the tablet already
        has), then the least-loaded tserver within it. Falls back to
        packing zones once every zone is used (small clusters)."""
        import collections as _c

        by_zone: dict[tuple, list] = {}
        for d in live:
            if d.uuid in exclude:
                continue
            by_zone.setdefault(self._zone_of(d), []).append(d)
        for descs in by_zone.values():
            descs.sort(key=lambda d: load.get(d.uuid, 0))
        used = _c.Counter(existing_zones)
        picks: list[str] = []
        for _ in range(rf):
            candidates = [(used[z], load.get(descs[0].uuid, 0), z)
                          for z, descs in by_zone.items() if descs]
            if not candidates:
                break
            _u, _l, z = min(candidates)
            d = by_zone[z].pop(0)
            picks.append(d.uuid)
            used[z] += 1
        return picks

    @staticmethod
    def _create_tablet_req(tablet_id: str, table_name: str, schema,
                           partition_start, partition_end, engine: str,
                           peers: list[str],
                           indexes: list | None = None) -> dict:
        """The one canonical ts.create_tablet payload (built in three
        places: initial dispatch, dead-TS re-replication, create retry)."""
        return {"tablet_id": tablet_id, "table_name": table_name,
                "schema": schema, "partition_start": partition_start,
                "partition_end": partition_end, "engine": engine,
                "peers": peers, "indexes": list(indexes or [])}

    def _dispatch_tablet_creates(self, op: dict) -> list[str]:
        errors = []
        for td in op["tablets"]:
            for replica in td["replicas"]:
                req = self._create_tablet_req(
                    td["tablet_id"], op["name"], op["schema"],
                    td["partition_start"], td["partition_end"],
                    op.get("engine", "cpu"), td["replicas"])
                try:
                    resp = self.transport.send(replica, "ts.create_tablet",
                                               req, timeout=5.0)
                    if resp.get("code") != "ok":
                        self._failed_creates.add((td["tablet_id"], replica))
                        errors.append(f"{td['tablet_id']}@{replica}: "
                                      f"{resp.get('code')}")
                except Exception as e:  # noqa: BLE001 — balancer retries
                    self._failed_creates.add((td["tablet_id"], replica))
                    errors.append(f"{td['tablet_id']}@{replica}: {e}")
        return errors

    def _h_master_alter_table(self, p: dict):
        """ALTER TABLE: replicate the new schema into the sys catalog,
        then push it to every tablet leader (reference:
        CatalogManager::AlterTable + async AlterTable RPCs to tservers).
        Tablet leaders replicate the change through their own Raft log."""
        if not self.raft.is_leader():
            return self._not_leader()
        t = self.catalog.table_by_name(p["name"])
        if t is None:
            return {"code": "not_found"}
        new_schema = p["schema"]
        cur = t.schema.get("version", 0)
        if new_schema.get("version", 0) <= cur:
            # A client retry of the SAME ALTER is idempotent success; a
            # DIFFERENT schema at a consumed version lost a concurrent
            # DDL race and must re-plan from the current schema.
            if new_schema.get("version", 0) == cur and \
                    new_schema.get("columns") == t.schema.get("columns"):
                return {"code": "ok", "version": cur}
            return {"code": "version_mismatch", "current_version": cur}
        if new_schema.get("version", 0) != cur + 1:
            return {"code": "version_mismatch",
                    "current_version": cur}
        try:
            self.raft.replicate("catalog", {
                "op": "alter_table", "table_id": t.table_id,
                "schema": new_schema}, timeout=self._op_deadline(p))
        except NotLeader:
            return self._not_leader()
        errors = []
        for info in self.catalog.tablets_of(t.table_id):
            if not self._deliver_schema(info, new_schema):
                errors.append(info.tablet_id)
        if errors:
            # The catalog already holds the new schema: the balancer loop
            # retries delivery until every tablet leader replicated it.
            self._pending_alters.update(
                (t.table_id, tid) for tid in errors)
            return {"code": "partial", "tablets": errors}
        return {"code": "ok", "version": new_schema.get("version", 0)}

    def _h_master_create_index(self, p: dict):
        """Create a secondary index: an index TABLE (hash = the indexed
        column, range = the base PK) plus an IndexInfo record on the base
        table; base-tablet leaders learn the index set via ts.set_indexes
        and maintain it in their write path (reference:
        CatalogManager::CreateTable's index branch + Tablet::UpdateQLIndexes)."""
        if not self.raft.is_leader():
            return self._not_leader()
        from yugabyte_db_tpu.index import index_schema, index_table_name

        base = self.catalog.table_by_name(p["table"])
        if base is None:
            return {"code": "not_found"}
        columns = list(p.get("columns") or
                       ([p["column"]] if p.get("column") else []))
        include = list(p.get("include") or [])
        if not columns:
            return {"code": "error", "message": "no indexed columns"}
        name = p.get("index_name") or \
            f"{p['table']}_{'_'.join(columns)}_idx"
        if any(i["name"] == name for i in base.indexes):
            return {"code": "already_present", "index_table":
                    next(i["index_table"] for i in base.indexes
                         if i["name"] == name)}
        base_schema = Schema.from_dict(base.schema)
        itable = index_table_name(p["table"], columns, p.get("index_name"))
        try:
            ischema = index_schema(base_schema, columns, itable, include)
        except (ValueError, KeyError) as e:
            return {"code": "error", "message": str(e)}
        # Inherit the base table's replication factor (its tablets'
        # replica count) unless the caller overrides it.
        base_tablets = self.catalog.tablets_of(base.table_id)
        base_rf = (len(base_tablets[0].replicas) if base_tablets else 3)
        create = self._h_master_create_table({
            "name": itable, "schema": ischema.to_dict(),
            "num_tablets": p.get("num_tablets", base.num_tablets),
            "replication_factor": p.get("replication_factor", base_rf),
            "engine": base.engine,
        })
        if create["code"] not in ("ok", "partial", "already_present"):
            return create
        op = {"op": "create_index", "table_id": base.table_id,
              "index": {"name": name, "column": columns[0],
                        "columns": columns, "include": include,
                        "index_table": itable}}
        try:
            self.raft.replicate("catalog", op, timeout=self._op_deadline(p))
        except NotLeader:
            return self._not_leader()
        self._push_index_sets(base.table_id)
        return {"code": "ok", "index_table": itable}

    def _push_index_sets(self, table_id: str) -> None:
        """Tell every replica of the base table its current index set."""
        t = self.catalog.tables.get(table_id)
        if t is None:
            return
        for info in self.catalog.tablets_of(table_id):
            for replica in info.replicas:
                # Best effort: replicas recover the index set from
                # ts.create_tablet on restart, but a refused push should
                # still be visible somewhere.
                try:
                    resp = self.transport.send(replica, "ts.set_indexes", {
                        "tablet_id": info.tablet_id,
                        "indexes": list(t.indexes),
                    }, timeout=5.0)
                    if resp.get("code") != "ok":
                        count_swallowed("master.push_index_sets",
                                        resp.get("code"))
                except Exception as e:  # noqa: BLE001
                    count_swallowed("master.push_index_sets", e)

    def _h_master_drop_index(self, p: dict):
        if not self.raft.is_leader():
            return self._not_leader()
        base = self.catalog.table_by_name(p["table"])
        if base is None:
            return {"code": "not_found"}
        idx = next((i for i in base.indexes if i["name"] == p["name"]),
                   None)
        if idx is None:
            return {"code": "not_found"}
        try:
            self.raft.replicate("catalog", {
                "op": "drop_index", "table_id": base.table_id,
                "name": p["name"]}, timeout=self._op_deadline(p))
        except NotLeader:
            return self._not_leader()
        self._push_index_sets(base.table_id)
        self._h_master_delete_table({"name": idx["index_table"]})
        return {"code": "ok"}

    def _h_master_delete_table(self, p: dict):
        if not self.raft.is_leader():
            return self._not_leader()
        t = self.catalog.table_by_name(p["name"])
        if t is None:
            return {"code": "not_found"}
        tablets = self.catalog.tablets_of(t.table_id)
        try:
            self.raft.replicate("catalog",
                                {"op": "delete_table", "table_id": t.table_id},
                                timeout=self._op_deadline(p))
        except NotLeader:
            return self._not_leader()
        for info in tablets:
            for replica in info.replicas:
                try:
                    resp = self.transport.send(replica, "ts.delete_tablet",
                                               {"tablet_id": info.tablet_id},
                                               timeout=5.0)
                    if resp.get("code") not in ("ok", "not_found"):
                        count_swallowed("master.delete_tablet",
                                        resp.get("code"))
                except Exception as e:  # noqa: BLE001 — heartbeat GC retries
                    count_swallowed("master.delete_tablet", e)
        return {"code": "ok"}

    # -- tablet splitting / leader balancing (admin RPCs) --------------------
    def _h_master_split_tablet(self, p: dict):
        """Manually split one tablet (yb_admin split_tablet). Works
        regardless of the automatic-splitting flags — the thresholds
        gate the background pass, not the protocol."""
        if not self.raft.is_leader():
            return self._not_leader()
        tid = p["tablet_id"]
        info = self.catalog.tablets.get(tid)
        if info is None:
            return {"code": "not_found"}
        if p.get("table"):
            t = self.catalog.table_by_name(p["table"])
            if t is None or info.table_id != t.table_id:
                return {"code": "not_found",
                        "message": f"tablet {tid} is not in table "
                                   f"{p['table']}"}
        try:
            res = self.split_manager.split(
                tid, timeout=float(p.get("timeout", 30.0)))
        except NotLeader:
            return self._not_leader()
        except SplitError as e:
            return {"code": "error", "message": str(e)}
        return {"code": "ok", **res}

    def _h_master_rebalance(self, p: dict):
        """Run one forced leader-balancing pass (yb_admin rebalance);
        returns the move made, or move=None when already balanced."""
        if not self.raft.is_leader():
            return self._not_leader()
        move = self.load_balancer.run_pass(force=True)
        return {"code": "ok", "move": move,
                "leader_counts": self.ts_manager.leader_counts()}

    # -- lookups ------------------------------------------------------------
    def _h_master_get_table(self, p: dict):
        t = self.catalog.table_by_name(p["name"])
        if t is None:
            return {"code": "not_found"}
        return {"code": "ok", "table_id": t.table_id, "name": t.name,
                "schema": t.schema, "num_tablets": t.num_tablets,
                "engine": t.engine, "indexes": list(t.indexes)}

    def _h_master_get_table_locations(self, p: dict):
        t = self.catalog.table_by_name(p["name"])
        if t is None:
            return {"code": "not_found"}
        out = []
        for info in self.catalog.tablets_of(t.table_id):
            out.append({
                "tablet_id": info.tablet_id,
                "partition_start": info.partition_start,
                "partition_end": info.partition_end,
                "replicas": [
                    {"uuid": r, "addr": self.ts_manager.addr_of(r),
                     "cloud_info": self.ts_manager.cloud_info_of(r)}
                    for r in info.replicas
                ],
                "leader": self.ts_manager.leader_of(info.tablet_id),
            })
        out.sort(key=lambda d: d["partition_start"])
        return {"code": "ok", "table_id": t.table_id, "schema": t.schema,
                "tablets": out}

    def _h_master_locate_tablet(self, p: dict):
        """Replica set + freshest known leader of one tablet (used by the
        transaction notifier/resolvers to route per-tablet RPCs)."""
        info = self.catalog.tablets.get(p["tablet_id"])
        if info is None:
            return {"code": "not_found"}
        return {"code": "ok", "tablet_id": info.tablet_id,
                "replicas": list(info.replicas),
                "leader": self.ts_manager.leader_of(info.tablet_id)}

    def _h_master_list_tables(self, p: dict):
        return {"code": "ok", "tables": [
            {"table_id": t.table_id, "name": t.name, "state": t.state,
             "num_tablets": t.num_tablets}
            for t in self.catalog.list_tables()
        ]}

    # -- auth/roles (reference: CreateRole/GrantRevokeRole/
    # GrantRevokePermission, master.proto:1383-1388) ------------------------
    def _h_master_auth_op(self, p: dict):
        """Replicate one role/permission mutation through the catalog.
        The op is validated against current state first so obvious
        errors (duplicate role, unknown role) fail without a Raft round;
        apply-time errors surface as error responses."""
        if not self.raft.is_leader():
            return self._not_leader()
        op = dict(p["auth"])
        try:
            # Dry-run validation against a copy keeps apply() (the
            # replicated path) deterministic and non-throwing.
            from yugabyte_db_tpu.auth import RoleStore

            RoleStore.from_dict(self.catalog.auth.to_dict()).apply(op)
        except Exception as e:  # noqa: BLE001
            return {"code": "error", "message": str(e)}
        try:
            self.raft.replicate("catalog", op, timeout=self._op_deadline(p))
        except NotLeader:
            return self._not_leader()
        return {"code": "ok"}

    def _h_master_get_auth(self, p: dict):
        # Leader-only: a follower may lag the latest role DDL and a
        # stale mirror would let a just-revoked permission keep working.
        if not self.raft.is_leader():
            return self._not_leader()
        return {"code": "ok", "auth": self.catalog.auth.to_dict()}

    def _h_master_type_op(self, p: dict):
        """CREATE/DROP TYPE through the replicated catalog (reference:
        CatalogManager::CreateUDType/DeleteUDType)."""
        if not self.raft.is_leader():
            return self._not_leader()
        action = p["action"]
        name = p["name"]
        if action == "create":
            if name in self.catalog.types:
                return {"code": "already_present"}
            op = {"op": "create_type", "name": name,
                  "fields": [list(f) for f in p["fields"]]}
        else:
            if name not in self.catalog.types:
                return {"code": "not_found"}
            for t in self.catalog.list_tables():
                for c in t.schema.get("columns", []):
                    if c.get("udt") == name:
                        return {"code": "error", "message":
                                f"type {name} in use by table {t.name}"}
            op = {"op": "drop_type", "name": name}
        try:
            self.raft.replicate("catalog", op, timeout=self._op_deadline(p))
        except NotLeader:
            return self._not_leader()
        return {"code": "ok"}

    def _h_master_misc_op(self, p: dict):
        """Views + sequences through the replicated catalog; sequence
        allocation is serialized here so every allocation returns a
        distinct base (holes on crash/retry are allowed — PG nextval's
        own contract)."""
        action = p["action"]
        if action == "get_view":
            q = self.catalog.views.get(p["name"])
            return ({"code": "ok", "query": q} if q is not None
                    else {"code": "not_found"})
        if action == "list_keyspaces":
            return {"code": "ok",
                    "keyspaces": sorted(self.catalog.user_keyspaces)}
        if not self.raft.is_leader():
            return self._not_leader()
        if action == "create_view":
            if p["name"] in self.catalog.views and not p.get("replace"):
                return {"code": "already_present"}
            op = {"op": "create_view", "name": p["name"],
                  "query": p["query"]}
        elif action == "drop_view":
            if p["name"] not in self.catalog.views:
                return {"code": "not_found"}
            op = {"op": "drop_view", "name": p["name"]}
        elif action == "create_keyspace":
            if p["name"] in self.catalog.user_keyspaces:
                return {"code": "already_present"}
            op = {"op": "create_keyspace", "name": p["name"]}
        elif action == "drop_keyspace":
            if p["name"] not in self.catalog.user_keyspaces:
                return {"code": "not_found"}
            op = {"op": "drop_keyspace", "name": p["name"]}
        elif action == "create_sequence":
            if p["name"] in self.catalog.sequences:
                return {"code": "already_present"}
            op = {"op": "create_sequence", "name": p["name"]}
        elif action == "drop_sequence":
            if p["name"] not in self.catalog.sequences:
                return {"code": "not_found"}
            op = {"op": "drop_sequence", "name": p["name"]}
        elif action == "sequence_next":
            if p["name"] not in self.catalog.sequences:
                return {"code": "not_found"}
            n = int(p.get("n", 1))
            with self._seq_lock:
                base = self.catalog.sequences[p["name"]]
                try:
                    # Justified hold: the read of `base` must be atomic
                    # with the alloc's position in the Raft log — two
                    # racing nexts reading the same base would both hand
                    # out [base, base+n). _seq_lock serializes only
                    # sequence allocation, never the general catalog path.
                    # yb-lint: disable=iholds/lock-across-blocking
                    self.raft.replicate("catalog", {
                        "op": "sequence_alloc", "name": p["name"],
                        "n": n}, timeout=self._op_deadline(p))
                except NotLeader:
                    return self._not_leader()
            return {"code": "ok", "base": base}
        else:
            return {"code": "error", "message": f"bad action {action}"}
        try:
            self.raft.replicate("catalog", op, timeout=self._op_deadline(p))
        except NotLeader:
            return self._not_leader()
        return {"code": "ok"}

    def _h_master_snapshot_op(self, p: dict):
        """Master-coordinated cluster snapshots (reference: the
        CreateSnapshot/RestoreSnapshot master RPCs fanning
        backup.proto TabletSnapshotOp to every tablet, tracked as
        SysSnapshotEntryPB states in the sys catalog). States:
        CREATING -> COMPLETE | FAILED; restore/delete require
        COMPLETE. The registry rides the replicated catalog, so it
        survives master failover and restarts."""
        action = p.get("action")
        if action == "list":
            return {"code": "ok", "snapshots": {
                sid: dict(rec)
                for sid, rec in self.catalog.snapshots.items()}}
        if not self.raft.is_leader():
            return self._not_leader()
        sid = p.get("snapshot_id") or ""
        if not sid:
            return {"code": "error", "message": "missing snapshot_id"}
        if action == "create":
            if not p.get("table"):
                return {"code": "error", "message": "missing table"}
            t = self.catalog.table_by_name(p["table"])
            if t is None:
                return {"code": "not_found"}
            if sid in self.catalog.snapshots:
                return {"code": "already_present"}
            tablets = self.catalog.tablets_of(t.table_id)
            try:
                self.raft.replicate("catalog", {
                    "op": "snapshot_record", "snapshot_id": sid,
                    "table": p["table"], "state": "CREATING",
                    "tablets": [ti.tablet_id for ti in tablets]},
                    timeout=self._op_deadline(p))
            except NotLeader:
                return self._not_leader()
            errs = self._snapshot_fanout(tablets, sid, "create_snapshot")
            state = "FAILED" if errs else "COMPLETE"
            try:
                self.raft.replicate("catalog", {
                    "op": "snapshot_record", "snapshot_id": sid,
                    "table": p["table"], "state": state,
                    "tablets": [ti.tablet_id for ti in tablets]},
                    timeout=self._op_deadline(p))
            except NotLeader:
                return self._not_leader()
            if errs:
                return {"code": "error",
                        "message": f"snapshot {sid}: {errs[0]}"}
            return {"code": "ok", "tablets": len(tablets)}
        rec = self.catalog.snapshots.get(sid)
        if rec is None:
            return {"code": "not_found"}
        t = self.catalog.table_by_name(rec["table"])
        if t is None:
            return {"code": "not_found",
                    "message": f"table {rec['table']} gone"}
        tablets = self.catalog.tablets_of(t.table_id)
        if action == "restore":
            if rec["state"] != "COMPLETE":
                return {"code": "error",
                        "message": f"snapshot {sid} is {rec['state']}"}
            errs = self._snapshot_fanout(tablets, sid,
                                         "restore_snapshot")
            if errs:
                return {"code": "error",
                        "message": f"restore {sid}: {errs[0]}"}
            return {"code": "ok", "tablets": len(tablets)}
        if action == "delete":
            errs = self._snapshot_fanout(tablets, sid, "delete_snapshot")
            if errs:
                # Keep the registry entry so the delete is retryable;
                # removing it would orphan per-tablet snapshot data on
                # the replicas that did not get the op.
                return {"code": "error",
                        "message": f"delete {sid}: {errs[0]}"}
            try:
                self.raft.replicate("catalog", {
                    "op": "snapshot_remove", "snapshot_id": sid},
                    timeout=self._op_deadline(p))
            except NotLeader:
                return self._not_leader()
            return {"code": "ok"}
        return {"code": "error", "message": f"bad action {action!r}"}

    def _snapshot_fanout(self, tablets, sid: str, op: str) -> list[str]:
        """Run one snapshot op on every tablet's LEADER (follow
        not_leader hints); returns error strings (empty = success)."""
        errs = []
        for ti in tablets:
            payload = {"tablet_id": ti.tablet_id, "snapshot_id": sid,
                       "op": op}
            last = "no replicas"
            done = False
            tried = set()
            candidates = list(ti.replicas)
            while candidates:
                dst = candidates.pop(0)
                if dst in tried:
                    continue
                tried.add(dst)
                try:
                    resp = self.transport.send(dst, "ts.snapshot_op",
                                               payload, timeout=10.0)
                except Exception as e:  # noqa: BLE001 — try the next
                    last = str(e)
                    continue
                if resp.get("code") == "ok":
                    done = True
                    break
                last = resp.get("message", resp.get("code"))
                hint = resp.get("leader_hint")
                if hint and hint not in tried:
                    candidates.insert(0, hint)
            if not done:
                errs.append(f"{ti.tablet_id}: {last}")
        return errs

    def _h_master_list_types(self, p: dict):
        return {"code": "ok", "types": {
            n: [list(f) for f in fs]
            for n, fs in self.catalog.types.items()}}

    def _h_master_list_tservers(self, p: dict):
        now_dead = {d.uuid for d in self.ts_manager.dead_tservers()}
        return {"code": "ok", "tservers": [
            {"uuid": d.uuid, "addr": d.addr, "alive": d.uuid not in now_dead,
             "num_live_tablets": d.num_live_tablets,
             "cloud_info": dict(d.cloud_info)}
            for d in self.ts_manager.all_tservers()
        ]}

    # -- heartbeats ----------------------------------------------------------
    def _h_master_ts_heartbeat(self, p: dict):
        if not self.raft.is_leader():
            return self._not_leader()
        self.ts_manager.heartbeat(p)
        resp = {"code": "ok", "master_uuid": self.uuid}
        st = self.raft.stats()
        # Orphan GC is destructive: a new leader's LOCAL watermarks can lag
        # the true cluster commit until its own-term no_op is applied, so a
        # just-committed table could look absent from the catalog. Gate on
        # leader_ready() (own-term entry applied) AND fully-applied.
        if self.raft.leader_ready() and \
                st["applied_index"] >= st["commit_index"]:
            # Catalog fully applied: safe to identify orphaned replicas
            # (reference: master orders deletion of tablets not in catalog,
            # and of replicas no longer in the tablet's config).
            known = self.catalog.known_tablet_ids()
            now = time.monotonic()
            to_delete = []
            for t in p.get("tablets", []):
                tid = t["tablet_id"]
                if tid not in known:
                    to_delete.append(tid)
                    continue
                if now - self._fixing.get(tid, 0) < 30.0:
                    continue  # re-replication in flight; don't race it
                info = self.catalog.tablets.get(tid)
                if info is not None and p["ts_uuid"] not in info.replicas:
                    to_delete.append(tid)
                # Index-set reconciliation: a lost ts.set_indexes push
                # must not leave a replica maintaining a stale index set.
                if info is not None and "index_names" in t:
                    table = self.catalog.tables.get(info.table_id)
                    if table is not None:
                        want = sorted(i["name"] for i in table.indexes)
                        if want != t["index_names"]:
                            try:
                                r = self.transport.send(
                                    p["ts_uuid"], "ts.set_indexes", {
                                        "tablet_id": tid,
                                        "indexes": list(table.indexes),
                                    }, timeout=2.0)
                                if r.get("code") != "ok":
                                    count_swallowed("master.hb_set_indexes",
                                                    r.get("code"))
                            except Exception as e:  # noqa: BLE001 — next beat
                                count_swallowed("master.hb_set_indexes", e)
            resp["tablets_to_delete"] = sorted(to_delete)
        return resp

    def _rpc_ok(self, dst: str, method: str, payload: dict,
                timeout: float = 5.0) -> dict:
        resp = self.transport.send(dst, method, payload, timeout=timeout)
        if resp.get("code") != "ok":
            raise RuntimeError(f"{method} to {dst}: {resp}")
        return resp

    # -- re-replication (ClusterLoadBalancer's failure-recovery half) --------
    def _balancer_loop(self) -> None:
        while self._running:
            time.sleep(self.balance_interval_s)
            if not self._running or not self.raft.is_leader():
                continue
            try:
                self._rereplicate_once()
            except Exception as e:  # noqa: BLE001 — next tick retries
                count_swallowed("master.rereplicate_tick", e)
            try:
                self._retry_pending_alters()
            except Exception as e:  # noqa: BLE001 — next tick retries
                count_swallowed("master.retry_alters_tick", e)
            try:
                self.split_manager.run_pass()
            except Exception as e:  # noqa: BLE001 — next tick retries
                count_swallowed("master.split_tick", e)
            try:
                self.load_balancer.run_pass()
            except Exception as e:  # noqa: BLE001 — next tick retries
                count_swallowed("master.balance_tick", e)

    def _deliver_schema(self, info, schema_dict: dict) -> bool:
        """Push a schema version to one tablet's leader (whichever
        replica that is); True once a leader replicated it."""
        for replica in info.replicas:
            try:
                resp = self.transport.send(
                    replica, "ts.alter_schema",
                    {"tablet_id": info.tablet_id, "schema": schema_dict},
                    timeout=5.0)
                if resp.get("code") == "ok":
                    return True
            except Exception as e:  # noqa: BLE001 — try other replicas
                count_swallowed("master.alter_schema", e)
                continue
        return False

    def _retry_pending_alters(self) -> None:
        if not self.raft.leader_ready() or not self._pending_alters:
            return
        for table_id, tablet_id in list(self._pending_alters):
            t = self.catalog.tables.get(table_id)
            info = self.catalog.tablets.get(tablet_id)
            if t is None or info is None or \
                    self._deliver_schema(info, t.schema):
                self._pending_alters.discard((table_id, tablet_id))

    def _rereplicate_once(self) -> None:
        live = sorted(self.ts_manager.live_tservers(),
                      key=lambda d: d.num_live_tablets)
        if not live:
            return
        self._recreate_missing_replicas(live)
        self._repair_live_missing_replicas(live)
        dead = {d.uuid for d in self.ts_manager.dead_tservers()}
        if not dead:
            return
        now = time.monotonic()
        for t in self.catalog.list_tables():
            for info in self.catalog.tablets_of(t.table_id):
                bad = [r for r in info.replicas if r in dead]
                if not bad:
                    continue
                if now - self._fixing.get(info.tablet_id, 0) < 10.0:
                    continue  # a fix is already in flight
                without_dead = [r for r in info.replicas if r != bad[0]]
                # Zone-aware replacement: avoid the zones the surviving
                # replicas already occupy when another zone has capacity.
                live_by_uuid = {d.uuid: d for d in live}
                existing_zones = [self._zone_of(live_by_uuid[r])
                                  for r in without_dead if r in live_by_uuid]
                picks = self._select_replicas(
                    live, 1, {d.uuid: d.num_live_tablets for d in live},
                    exclude=set(info.replicas), existing_zones=existing_zones)
                if not picks:
                    continue
                self._fixing[info.tablet_id] = now
                replacement = picks[0]
                with_new = without_dead + [replacement]
                leader = self.ts_manager.leader_of(info.tablet_id)
                if leader is None or leader in dead or leader not in \
                        without_dead:
                    continue  # wait for the group to elect a live leader
                try:
                    # Raft membership changes are one server at a time:
                    # REMOVE the dead replica, then ADD the replacement
                    # (reference: ChangeConfig REMOVE_SERVER/ADD_SERVER).
                    self._rpc_ok(leader, "ts.change_config", {
                        "tablet_id": info.tablet_id,
                        "peers": without_dead,
                    }, timeout=10.0)
                    # Not a voter yet: the leader's change_config adds it.
                    self._rpc_ok(replacement, "ts.create_tablet",
                                 self._create_tablet_req(
                                     info.tablet_id, t.name, t.schema,
                                     info.partition_start, info.partition_end,
                                     t.engine, without_dead,
                                     indexes=t.indexes), timeout=5.0)
                    self._rpc_ok(leader, "ts.change_config", {
                        "tablet_id": info.tablet_id,
                        "peers": with_new,
                    }, timeout=10.0)
                    self.raft.replicate("catalog", {
                        "op": "set_tablet_replicas",
                        "tablet_id": info.tablet_id,
                        "replicas": with_new,
                    })
                except Exception:  # noqa: BLE001 — retried next tick
                    self._fixing.pop(info.tablet_id, None)

    def _repair_live_missing_replicas(self, live) -> None:
        """A live, heartbeating tserver that persistently does NOT report a
        replica the catalog assigns to it either never created it (the
        dispatch failure was lost with a master restart/failover, so
        _failed_creates can't retry it) or lost its disk. Both repair
        safely through a config cycle: REMOVE the replica from the group,
        hand the tserver a fresh one, ADD it back — it rejoins as a new
        member and catches up from the leader, never voting on the
        strength of an empty log (reference: the load balancer's
        remove-then-add path, src/yb/master/cluster_balance.cc)."""
        if not self.raft.leader_ready():
            return
        now = time.monotonic()
        live_by_uuid = {d.uuid: d for d in live}
        tracked = set()
        for t in self.catalog.list_tables():
            for info in self.catalog.tablets_of(t.table_id):
                for r in info.replicas:
                    key = (info.tablet_id, r)
                    d = live_by_uuid.get(r)
                    if d is None or key in self._failed_creates:
                        continue  # dead-TS / direct-retry paths own these
                    if info.tablet_id in d.tablet_roles:
                        # Hosted — but is it a MEMBER? If a previous repair
                        # cycle crashed between its create and add-back
                        # steps, the replica hosts an orphan copy outside
                        # the group config; finish the add-back (the raft
                        # config arrives with the leader's heartbeat).
                        cfg = self.ts_manager.config_of(info.tablet_id)
                        if cfg is None or r in cfg:
                            continue
                        tracked.add(key)
                        first = self._missing_seen.setdefault(key, now)
                        if now - first < self.missing_replica_grace_s:
                            continue
                        if now - self._fixing.get(info.tablet_id, 0) < 10.0:
                            continue
                        leader = self.ts_manager.leader_of(info.tablet_id)
                        if leader is None or leader not in live_by_uuid:
                            continue
                        self._fixing[info.tablet_id] = now
                        try:
                            self._rpc_ok(leader, "ts.change_config", {
                                "tablet_id": info.tablet_id,
                                "peers": sorted(set(cfg) | {r}),
                            }, timeout=10.0)
                            self._missing_seen.pop(key, None)
                            tracked.discard(key)
                        except Exception:  # noqa: BLE001 — next tick
                            self._fixing.pop(info.tablet_id, None)
                        continue
                    tracked.add(key)
                    first = self._missing_seen.setdefault(key, now)
                    if now - first < self.missing_replica_grace_s:
                        continue
                    if now - self._fixing.get(info.tablet_id, 0) < 10.0:
                        continue
                    others = [x for x in info.replicas if x != r]
                    leader = self.ts_manager.leader_of(info.tablet_id)
                    if not others or leader is None or leader not in others \
                            or leader not in live_by_uuid:
                        continue  # RF=1 or no live leader: cannot cycle
                    self._fixing[info.tablet_id] = now
                    try:
                        self._rpc_ok(leader, "ts.change_config", {
                            "tablet_id": info.tablet_id, "peers": others,
                        }, timeout=10.0)
                        self._rpc_ok(r, "ts.create_tablet",
                                     self._create_tablet_req(
                                         info.tablet_id, t.name, t.schema,
                                         info.partition_start,
                                         info.partition_end, t.engine,
                                         others, indexes=t.indexes),
                                     timeout=5.0)
                        self._rpc_ok(leader, "ts.change_config", {
                            "tablet_id": info.tablet_id,
                            "peers": info.replicas,
                        }, timeout=10.0)
                        self._missing_seen.pop(key, None)
                        tracked.discard(key)
                    except Exception:  # noqa: BLE001 — next tick retries
                        self._fixing.pop(info.tablet_id, None)
        # Forget pairs that are no longer missing (reported again, table
        # dropped, or replica re-placed).
        for key in list(self._missing_seen):
            if key not in tracked:
                self._missing_seen.pop(key, None)

    def _recreate_missing_replicas(self, live) -> None:
        """Retry ts.create_tablet for replicas whose ORIGINAL create failed
        (tracked in _failed_creates — create_table returned 'partial').
        Restricted to tracked failures on purpose: a live tserver merely not
        reporting a tablet may have lost its disk, and handing a still-voting
        replica a fresh empty log could elect a leader without committed
        entries. Those are repaired by remote bootstrap, not re-creation."""
        if not self.raft.leader_ready() or not self._failed_creates:
            return  # local catalog view may lag; don't act on it
        now = time.monotonic()
        live_uuids = {d.uuid for d in live}
        for tablet_id, replica in list(self._failed_creates):
            info = self.catalog.tablets.get(tablet_id)
            if info is None or replica not in info.replicas:
                self._failed_creates.discard((tablet_id, replica))
                continue  # table dropped or replica re-placed meanwhile
            if replica not in live_uuids:
                continue  # dead-TS path handles it
            if now - self._fixing.get(tablet_id, 0) < 10.0:
                continue
            t = self.catalog.tables.get(info.table_id)
            if t is None:
                continue
            self._fixing[tablet_id] = now
            try:
                resp = self.transport.send(replica, "ts.create_tablet",
                                           self._create_tablet_req(
                                               tablet_id, t.name, t.schema,
                                               info.partition_start,
                                               info.partition_end, t.engine,
                                               info.replicas,
                                               indexes=t.indexes),
                                           timeout=5.0)
                if resp.get("code") == "ok":
                    self._failed_creates.discard((tablet_id, replica))
                else:
                    count_swallowed("master.recreate_replica",
                                    resp.get("code"))
            except Exception as e:  # noqa: BLE001 — next tick retries
                count_swallowed("master.recreate_replica", e)
