"""Daemon entry point: run one master or tserver as a real OS process.

Reference analog: src/yb/master/master_main.cc and
src/yb/tserver/tablet_server_main.cc:107 — the production processes
yb-ctl spawns. Each process owns a Messenger listening on its RPC port,
a SocketTransport with the cluster's address book, and an embedded
webserver.

Usage (normally via tools.yb_ctl, not by hand):
  python -m yugabyte_db_tpu.server.daemon_main --role tserver \
      --uuid ts-0 --data-dir /data/ts-0 \
      --topology m-0=127.0.0.1:7100,ts-0=127.0.0.1:9100,... \
      --masters m-0 --web-port 9200
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading


def parse_topology(spec: str) -> dict[str, tuple[str, int]]:
    out = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        uuid, addr = part.split("=", 1)
        host, port = addr.rsplit(":", 1)
        out[uuid] = (host, int(port))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="yb-daemon")
    ap.add_argument("--role", choices=("master", "tserver"), required=True)
    ap.add_argument("--uuid", required=True)
    ap.add_argument("--data-dir", required=True)
    ap.add_argument("--topology", required=True,
                    help="uuid=host:port,... for every daemon")
    ap.add_argument("--masters", required=True,
                    help="comma-separated master uuids")
    ap.add_argument("--web-port", type=int, default=0)
    ap.add_argument("--no-fsync", action="store_true")
    args = ap.parse_args(argv)

    from yugabyte_db_tpu.rpc import Messenger, SocketTransport

    topology = parse_topology(args.topology)
    if args.uuid not in topology:
        ap.error(f"--topology lacks own uuid {args.uuid}")
    host, port = topology[args.uuid]
    master_uuids = [u.strip() for u in args.masters.split(",") if u.strip()]

    transport = SocketTransport()
    for uuid, (h, p) in topology.items():
        transport.set_address(uuid, h, p)

    if args.role == "master":
        from yugabyte_db_tpu.master.master import Master

        daemon = Master(args.uuid, args.data_dir, transport, master_uuids,
                        fsync=not args.no_fsync)
    else:
        from yugabyte_db_tpu.tserver.tablet_server import TabletServer

        daemon = TabletServer(args.uuid, args.data_dir, transport,
                              master_uuids, fsync=not args.no_fsync,
                              engine_options=None)
    messenger = Messenger(args.uuid, num_workers=16)
    # Consensus traffic rides a dedicated pool: user writes block their
    # workers on majority replication, and the raft RPCs that complete
    # that majority must never queue behind them (reference: separate
    # ServicePools per service, src/yb/rpc/service_pool.cc).
    messenger.add_service_pool("raft.", 8)
    bound = messenger.listen(host, port, daemon.handle)
    daemon.advertised_addr = bound
    daemon.start()
    web_addr = daemon.start_webserver("127.0.0.1", args.web_port)
    print(f"{args.role} {args.uuid} rpc={bound[0]}:{bound[1]} "
          f"web={web_addr[0]}:{web_addr[1]}", flush=True)

    stop = threading.Event()

    def _sig(_signum, _frame):
        stop.set()

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    stop.wait()
    daemon.shutdown()
    messenger.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
