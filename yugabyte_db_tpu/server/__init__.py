"""Shared daemon scaffolding: webserver, options, daemon metrics.

Reference analog: src/yb/server/ — RpcAndWebServerBase (server_base.cc),
the embedded Webserver with its path handlers (/metrics, /varz,
/tablets, default-path-handlers.cc), and the structured option objects
(server_base_options.h) layered over flags.
"""

from yugabyte_db_tpu.server.options import (MasterOptions, ServerOptions,
                                            TabletServerOptions)
from yugabyte_db_tpu.server.webserver import Webserver

__all__ = ["MasterOptions", "ServerOptions", "TabletServerOptions",
           "Webserver"]
