"""Embedded HTTP server: /metrics (Prometheus), /varz, /healthz, /tablets.

Reference analog: src/yb/server/webserver.cc + the path handlers
(default-path-handlers.cc, tserver-path-handlers.cc): every daemon
exposes its metrics registry and flag table over HTTP for scraping and
debugging.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from yugabyte_db_tpu.utils.flags import FLAGS
from yugabyte_db_tpu.utils.metrics import MetricRegistry


class Webserver:
    def __init__(self, registry: MetricRegistry, daemon_name: str = ""):
        self.registry = registry
        self.daemon_name = daemon_name
        self._handlers = {}
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self.add_json_handler("/healthz", lambda: {"status": "ok"})
        self.add_json_handler("/varz", lambda: {
            f.name: {"value": f.value, "default": f.default,
                     "help": f.help, "tags": sorted(f.tags)}
            for f in FLAGS.all()})

    def add_handler(self, path: str, fn, content_type="text/plain"):
        """fn() -> str served at ``path``."""
        self._handlers[path] = (fn, content_type)

    def add_json_handler(self, path: str, fn):
        self.add_handler(path, lambda: json.dumps(fn(), indent=1,
                                                  default=str),
                         content_type="application/json")

    def start(self, host: str = "127.0.0.1",
              port: int = 0) -> tuple[str, int]:
        ws = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = ws.registry.prometheus_text()
                    ctype = "text/plain; version=0.0.4"
                elif path in ws._handlers:
                    fn, ctype = ws._handlers[path]
                    try:
                        body = fn()
                    except Exception as e:  # noqa: BLE001
                        self.send_error(500, str(e))
                        return
                else:
                    self.send_error(404)
                    return
                data = body.encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *args):  # quiet
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"webserver-{self.daemon_name}", daemon=True)
        self._thread.start()
        return self._httpd.server_address[:2]

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
