"""Embedded HTTP server: /metrics (Prometheus), /varz, /healthz, /memz,
JSON endpoints, and HTML dashboards.

Reference analog: src/yb/server/webserver.cc + the path handlers
(default-path-handlers.cc, master/tserver-path-handlers.cc, assets in
www/): every daemon exposes its metrics registry, flag table, memory
stats, and per-daemon dashboards over HTTP.
"""

from __future__ import annotations

import html as _html
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from yugabyte_db_tpu.utils.flags import FLAGS
from yugabyte_db_tpu.utils.metrics import MetricRegistry

_STYLE = """<style>
body{font-family:system-ui,sans-serif;margin:2em;color:#222}
table{border-collapse:collapse;margin:1em 0}
th,td{border:1px solid #ccc;padding:4px 10px;text-align:left;
      font-size:14px}
th{background:#f0f3f7}
h1{font-size:20px} a{color:#2459a8}
nav a{margin-right:1em}
</style>"""


def _healthz() -> dict:
    """"ok" when every circuit breaker in the process is closed;
    otherwise "degraded" plus one entry per quarantined path (e.g. a
    TPU engine serving from the host while its device path recovers) —
    the JSON twin of the ``yb_engine_degraded`` gauge."""
    try:
        from yugabyte_db_tpu.storage.breaker import health_report

        return health_report()
    except ImportError:
        return {"status": "ok"}


def _memz() -> dict:
    import resource

    ru = resource.getrusage(resource.RUSAGE_SELF)
    out = {"max_rss_kb": ru.ru_maxrss, "user_time_s": ru.ru_utime,
           "system_time_s": ru.ru_stime}
    try:
        from yugabyte_db_tpu.utils.memtracker import root_tracker

        out["trackers"] = root_tracker().dump()
    except ImportError:
        pass
    try:
        from yugabyte_db_tpu.storage.residency import hbm_cache

        # budget / resident / pinned / pool breakdown for the HBM
        # residency cache (the device-subtree numbers above are the
        # MemTracker view of the same bytes).
        out["hbm_cache"] = hbm_cache().stats()
    except ImportError:
        pass
    try:
        from yugabyte_db_tpu.utils.metrics import plane_stats_snapshot

        # Compressed-plane accounting (--tpu_plane_encoding): per-tablet
        # stored vs logical plane bytes, broken down by encoding kind —
        # the host-side twin of hbm_cache's by_encoding residency split.
        out["plane_encoding"] = plane_stats_snapshot()
    except ImportError:
        pass
    return out


class Webserver:
    def __init__(self, registry: MetricRegistry, daemon_name: str = ""):
        self.registry = registry
        self.daemon_name = daemon_name
        self._handlers = {}
        self._dashboards: list[tuple[str, str]] = []  # (path, title)
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self.add_json_handler("/healthz", _healthz)
        self.add_json_handler("/varz", lambda: {
            f.name: {"value": f.value, "default": f.default,
                     "help": f.help, "tags": sorted(f.tags)}
            for f in FLAGS.all()})
        self.add_json_handler("/memz", _memz)
        from yugabyte_db_tpu.utils.trace import TRACE_EVENTS, dump_stacks

        self.add_json_handler("/tracing.json", TRACE_EVENTS.dump)
        self.add_handler("/stacks", dump_stacks)
        self.add_handler("/", self._home, content_type="text/html")

    def add_handler(self, path: str, fn, content_type="text/plain"):
        """fn() -> str served at ``path``."""
        self._handlers[path] = (fn, content_type)

    def add_json_handler(self, path: str, fn):
        self.add_handler(path, lambda: json.dumps(fn(), indent=1,
                                                  default=str),
                         content_type="application/json")

    def add_dashboard(self, path: str, title: str, fn):
        """Register an HTML table dashboard at ``path`` rendering
        fn() -> list[dict] (the JSON shape the API endpoints serve);
        reference: the master/tserver path-handler dashboards."""
        self._dashboards.append((path, title))
        self.add_handler(path, lambda: self._render_table(title, fn()),
                         content_type="text/html")

    def _nav(self) -> str:
        links = [("/", "home"), ("/metrics", "metrics"),
                 ("/varz", "varz"), ("/memz", "memz")]
        links += [(p, t) for p, t in self._dashboards]
        extra = [(p, p.strip("/")) for p in self._handlers
                 if p not in {x[0] for x in links} and p != "/"]
        return "<nav>" + "".join(
            f'<a href="{p}">{_html.escape(t)}</a>'
            for p, t in links + sorted(extra)) + "</nav>"

    def _home(self) -> str:
        return (f"<html><head><title>{_html.escape(self.daemon_name)}"
                f"</title>{_STYLE}</head><body>"
                f"<h1>{_html.escape(self.daemon_name)}</h1>"
                f"{self._nav()}</body></html>")

    def _render_table(self, title: str, rows: list[dict]) -> str:
        cols: list[str] = []
        for r in rows:
            for k in r:
                if k not in cols:
                    cols.append(k)
        body = "".join(
            "<tr>" + "".join(
                f"<td>{_html.escape(str(r.get(c, '')))}</td>"
                for c in cols) + "</tr>"
            for r in rows)
        head = "".join(f"<th>{_html.escape(c)}</th>" for c in cols)
        return (f"<html><head><title>{_html.escape(title)}</title>{_STYLE}"
                f"</head><body><h1>{_html.escape(title)} — "
                f"{_html.escape(self.daemon_name)}</h1>{self._nav()}"
                f"<table><tr>{head}</tr>{body}</table>"
                f"<p>{len(rows)} row(s)</p></body></html>")

    def start(self, host: str = "127.0.0.1",
              port: int = 0) -> tuple[str, int]:
        ws = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = ws.registry.prometheus_text()
                    # Cross-cutting process-wide series (swallowed
                    # errors, serving-path batch histograms) render on
                    # every daemon's scrape — they have no daemon
                    # registry of their own.
                    from yugabyte_db_tpu.utils.metrics import \
                        process_registry

                    if process_registry() is not ws.registry:
                        body += process_registry().prometheus_text()
                    ctype = "text/plain; version=0.0.4"
                elif path in ws._handlers:
                    fn, ctype = ws._handlers[path]
                    try:
                        body = fn()
                    except Exception as e:  # noqa: BLE001
                        self.send_error(500, str(e))
                        return
                else:
                    self.send_error(404)
                    return
                data = body.encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *args):  # quiet
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"webserver-{self.daemon_name}", daemon=True)
        self._thread.start()
        return self._httpd.server_address[:2]

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
