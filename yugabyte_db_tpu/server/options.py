"""Structured daemon options layered over flags.

Reference analog: src/yb/server/server_base_options.h
(ServerBaseOptions) and the per-daemon TabletServerOptions /
MasterOptions — a typed bag of knobs constructed once at daemon start,
with defaults drawn from the flag registry.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from yugabyte_db_tpu.utils.flags import FLAGS


@dataclass
class ServerOptions:
    fsync: bool = True
    webserver: bool = False          # start the embedded HTTP server
    webserver_host: str = "127.0.0.1"
    webserver_port: int = 0          # 0 = ephemeral
    engine_options: dict = field(default_factory=dict)


@dataclass
class TabletServerOptions(ServerOptions):
    heartbeat_interval_s: float = 0.5
    tablet_storage_engine: str = "cpu"
    # Topology labels for zone-aware placement (reference: CloudInfoPB,
    # src/yb/master/master.proto:172): {"cloud", "region", "zone"}.
    cloud_info: dict | None = None


@dataclass
class MasterOptions(ServerOptions):
    # None -> resolved from the follower_unavailable flag at construction
    # (not frozen at import time).
    ts_unresponsive_timeout_s: float | None = None
    balance_interval_s: float = 1.0
    missing_replica_grace_s: float = 10.0

    def resolved_ts_timeout(self) -> float:
        if self.ts_unresponsive_timeout_s is not None:
            return self.ts_unresponsive_timeout_s
        return FLAGS.get("follower_unavailable_considered_failed_sec")
