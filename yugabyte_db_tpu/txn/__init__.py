"""Distributed transactions: coordinator, participant, client API.

Reference analog: the transaction stack of src/yb/tablet/
transaction_coordinator.cc (status-tablet state machine),
transaction_participant.cc (per-tablet intents + apply), and
src/yb/docdb/conflict_resolution.cc — redesigned for the TPU-first
engine split: provisional writes (intents) live in a small host-side
store, committed data lives in the device-resident columnar engine, and
commit moves intents into the engine at the coordinator-chosen commit
hybrid time (the IntentAwareIterator merge of intent_aware_iterator.h:81
becomes a read-side gate + status resolution instead of a merge, because
applies are local Raft ops that land promptly).
"""

from yugabyte_db_tpu.txn.coordinator import (TXN_STATUS_TABLE,
                                             TransactionCoordinator)
from yugabyte_db_tpu.txn.errors import (TransactionAborted,
                                        TransactionConflict)
from yugabyte_db_tpu.txn.participant import (IntentConflict,
                                             TransactionParticipant)


def __getattr__(name):
    # Lazy re-export of the client-side session API, which moved to
    # yugabyte_db_tpu.client.transaction. Loading it eagerly here would
    # recurse: client.transaction imports txn.coordinator, which runs
    # this package __init__ first.
    if name in ("TransactionManager", "YBTransaction"):
        # yb-lint: disable=layering/upward-import
        from yugabyte_db_tpu.client import transaction
        return getattr(transaction, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "IntentConflict",
    "TransactionAborted",
    "TransactionConflict",
    "TransactionCoordinator",
    "TransactionManager",
    "TransactionParticipant",
    "TXN_STATUS_TABLE",
    "YBTransaction",
]
