"""TransactionParticipant: per-tablet provisional writes (intents).

Reference analog: src/yb/tablet/transaction_participant.cc and the
intents RocksDB of src/yb/tablet/tablet.h:644-646. Here intents are a
small host-side store (dict keyed by txn and by row key) whose mutations
ride the tablet's Raft log as dedicated op types:

    "intents"         txn writes its provisional rows
    "apply_intents"   commit: move the txn's rows into the engine at the
                      coordinator-chosen commit hybrid time
    "remove_intents"  abort cleanup

State is rebuilt from the log on bootstrap; flush() snapshots it to a
sidecar (intents.json) before the WAL replay frontier advances, exactly
like the engine's flushed runs.

Conflict rules (src/yb/docdb/conflict_resolution.cc):
- write-write against a COMMITTED version newer than the writer's read
  point -> conflict (first committer wins; snapshot isolation);
- against another txn's PENDING intent -> priority duel: the would-be
  writer loses unless its priority is strictly higher (the caller then
  aborts the other txn through the coordinator and retries).
"""

from __future__ import annotations

import os
import threading

from yugabyte_db_tpu.storage.row_version import RowVersion
from yugabyte_db_tpu.storage.wire import decode_rows, encode_rows


class IntentConflict(Exception):
    """Write-write conflict. .conflicting carries (txn_id, status_tablet,
    priority) triples of pending foreign intents on the contested keys
    (empty when the conflict is against committed data)."""

    def __init__(self, message: str, conflicting=()):
        super().__init__(message)
        self.conflicting = tuple(conflicting)


class TransactionParticipant:
    """Host-side intent store of one tablet."""

    def __init__(self, tablet_dir: str):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self.path = os.path.join(tablet_dir, "intents.bin")
        # txn_id -> {"rows": [RowVersion...], "status_tablet": str,
        #            "priority": int, "read_ht": int}
        self.txns: dict[str, dict] = {}
        # row key -> set of txn ids holding intents on it
        self.by_key: dict[bytes, set[str]] = {}
        self.load()

    # -- persistence (sidecar snapshot at flush) ----------------------------
    def load(self) -> None:
        from yugabyte_db_tpu.utils import codec

        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            d = codec.decode(f.read())
        for txn_id, rec in d.items():
            rows = decode_rows(rec["rows"])
            self._add_locked(txn_id, rec["status_tablet"], rec["priority"],
                             rec["read_ht"], rows)

    def dump(self) -> dict:
        """Serializable snapshot of every txn's intents (sidecar format,
        also the remote-bootstrap payload)."""
        with self._lock:
            return {
                txn_id: {
                    "rows": encode_rows(rec["rows"]),
                    "status_tablet": rec["status_tablet"],
                    "priority": rec["priority"],
                    "read_ht": rec["read_ht"],
                }
                for txn_id, rec in self.txns.items()
            }

    def snapshot(self) -> None:
        """Durably snapshot current intents (called under the tablet's
        write lock by flush(), before the WAL frontier advances)."""
        from yugabyte_db_tpu.utils import codec

        d = self.dump()
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(codec.encode(d))
            f.flush()
            # Justified hold: runs under the tablet's flush barrier (see
            # docstring) — intents must be durable before the WAL frontier
            # advances past the segments they replay from.
            # yb-lint: disable=iholds/lock-across-blocking
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    # -- log-applied mutations ----------------------------------------------
    def _add_locked(self, txn_id, status_tablet, priority, read_ht, rows):
        rec = self.txns.setdefault(txn_id, {
            "rows": [], "status_tablet": status_tablet,
            "priority": priority, "read_ht": read_ht,
        })
        rec["rows"].extend(rows)
        for r in rows:
            self.by_key.setdefault(r.key, set()).add(txn_id)

    def apply_intents_op(self, body: dict) -> None:
        """Raft-apply of an "intents" entry."""
        rows = decode_rows(body["rows"])
        with self._lock:
            self._add_locked(body["txn_id"], body["status_tablet"],
                             body["priority"], body["read_ht"], rows)

    def apply_commit_op(self, body: dict, engine_apply) -> None:
        """Raft-apply of "apply_intents": move rows to the engine at the
        commit hybrid time. Idempotent: a retried notification finds no
        intents and is a no-op. The engine apply happens BEFORE the
        intents disappear / waiters wake — a reader released by wait_gone
        must find the rows already in the engine."""
        txn_id = body["txn_id"]
        commit_ht = body["commit_ht"]
        with self._lock:
            rec = self.txns.get(txn_id)
            if rec is None:
                return
            rows = [
                RowVersion(r.key, ht=commit_ht, tombstone=r.tombstone,
                           liveness=r.liveness, columns=r.columns,
                           expire_ht=r.resolve_ttl(commit_ht))
                for r in rec["rows"]
            ]
        engine_apply(rows)
        with self._lock:
            rec = self.txns.pop(txn_id, None)
            if rec is not None:
                self._unindex_locked(txn_id, rec)
                self._cond.notify_all()

    def apply_remove_op(self, body: dict) -> None:
        """Raft-apply of "remove_intents" (abort cleanup). Idempotent."""
        with self._lock:
            rec = self.txns.pop(body["txn_id"], None)
            if rec is not None:
                self._unindex_locked(body["txn_id"], rec)
                self._cond.notify_all()

    def _unindex_locked(self, txn_id, rec) -> None:
        for r in rec["rows"]:
            s = self.by_key.get(r.key)
            if s is not None:
                s.discard(txn_id)
                if not s:
                    del self.by_key[r.key]

    # -- conflict detection (leader-side, before replication) ---------------
    def check_conflicts(self, txn_id: str, keys: list[bytes],
                        read_ht: int, latest_committed_ht) -> None:
        """Raise IntentConflict if writing ``keys`` conflicts.

        ``latest_committed_ht(key)`` -> newest committed version ht (0 if
        none) — supplied by the tablet so the store stays engine-agnostic.
        """
        pending = {}
        with self._lock:
            for key in keys:
                for other in self.by_key.get(key, ()):  # foreign intents
                    if other != txn_id:
                        rec = self.txns[other]
                        pending[other] = (rec["status_tablet"],
                                          rec["priority"])
        for key in keys:
            ht = latest_committed_ht(key)
            if ht > read_ht:
                raise IntentConflict(
                    f"committed write at ht {ht} is newer than txn read "
                    f"point {read_ht} (first committer wins)")
        if pending:
            raise IntentConflict(
                "pending intents held by other transactions",
                conflicting=[(t, st, pr)
                             for t, (st, pr) in pending.items()])

    def pending_on_keys(self, keys: list[bytes],
                        exclude: str | None = None) -> list[tuple]:
        """(txn_id, status_tablet, priority) of foreign intents on keys."""
        out = {}
        with self._lock:
            for key in keys:
                for t in self.by_key.get(key, ()):
                    if t != exclude:
                        rec = self.txns[t]
                        out[t] = (rec["status_tablet"], rec["priority"])
        return [(t, st, pr) for t, (st, pr) in out.items()]

    # -- read-side ----------------------------------------------------------
    def txns_overlapping(self, lower: bytes, upper: bytes) -> dict[str, dict]:
        """Foreign-intent metadata for txns with intents in [lower, upper)."""
        out = {}
        with self._lock:
            for key, txn_ids in self.by_key.items():
                if key < lower or (upper and key >= upper):
                    continue
                for t in txn_ids:
                    rec = self.txns[t]
                    out[t] = {"status_tablet": rec["status_tablet"]}
        return out

    def wait_gone(self, txn_id: str, timeout: float) -> bool:
        """Wait until a txn's intents are applied or removed locally."""
        import time

        deadline = time.monotonic() + timeout
        with self._lock:
            while txn_id in self.txns:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
        return True

    def has_intents(self, txn_id: str) -> bool:
        with self._lock:
            return txn_id in self.txns

    def stats(self) -> dict:
        with self._lock:
            return {"txns_with_intents": len(self.txns),
                    "intent_keys": len(self.by_key)}
