"""TransactionCoordinator: the status-tablet state machine.

Reference analog: src/yb/tablet/transaction_coordinator.cc — transaction
status records live in a dedicated status tablet and every state change
is Raft-replicated through that tablet's log (op type "txn_status"), so
the record survives leader failover. States:

    PENDING ──commit──> COMMITTED(commit_ht)   (terminal)
        └────abort────> ABORTED                (terminal)

The commit hybrid time is chosen by the coordinator AT REPLICATION of the
COMMITTED record. Status queries carry the asker's read time and the
coordinator ratchets its clock past it first — so a "pending" answer is a
guarantee: if the txn commits later, its commit_ht will exceed the
asker's read time, and the asker may safely ignore the intents
(the reference's StatusRequest serving_ht contract).

After commit/abort the leader pushes apply/remove notifications to every
participant tablet until each acknowledges (resumed from scratch by a new
leader — notifications are idempotent on the participant).
"""

from __future__ import annotations

import json
import os
import threading
import time

TXN_STATUS_TABLE = "sys.transactions"

# Txns whose client stops heartbeating are presumed dead and aborted by
# the coordinator so conflicting writers / waiting readers make progress
# (reference: FLAGS_transaction_check_interval_ms + expiration); the
# default expiry comes from the txn_expiry_s runtime flag.


class TransactionCoordinator:
    """State machine + notifier for one status tablet."""

    def __init__(self, tablet_dir: str, expiry_s: float | None = None):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # Leader-side soft state: commits whose Raft entry is in flight.
        # A status query must NOT answer "pending" while one of these
        # exists — the entry may commit with commit_ht below the asker's
        # read time, breaking the "pending means any future commit lands
        # above your read time" promise.
        self._committing: dict[str, int] = {}
        from yugabyte_db_tpu.utils.flags import FLAGS

        if expiry_s is None:
            expiry_s = FLAGS.get("txn_expiry_s")
        self.path = os.path.join(tablet_dir, "txn_state.json")
        # txn_id -> local time its record became fully applied (soft
        # state driving the replicated GC after the retention window).
        self._done_seen: dict[str, float] = {}
        self.done_retention_s = 15.0
        # txn_id -> {"status": "pending"|"committed"|"aborted",
        #            "commit_ht": int,
        #            "participants": [[tablet_id, leader_hint]...],
        #            "unacked": [[tablet_id, leader_hint]...]}
        self.txns: dict[str, dict] = {}
        self._heartbeats: dict[str, float] = {}  # local soft state
        self.expiry_s = expiry_s
        self.load()

    # -- persistence --------------------------------------------------------
    def load(self) -> None:
        if os.path.exists(self.path):
            with open(self.path) as f:
                loaded = json.load(f)
            with self._lock:
                self.txns = loaded

    def dump(self) -> dict:
        with self._lock:
            return {k: {**v,
                        "participants": [list(p) for p in v["participants"]],
                        "unacked": [list(u) for u in v["unacked"]]}
                    for k, v in self.txns.items()}

    def snapshot(self) -> None:
        d = self.dump()
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(d, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    # -- Raft-applied state changes -----------------------------------------
    def apply_status_op(self, body: dict) -> None:
        action = body["action"]
        txn_id = body["txn_id"]
        with self._lock:
            rec = self.txns.get(txn_id)
            if action == "create":
                if rec is None:
                    self.txns[txn_id] = {"status": "pending", "commit_ht": 0,
                                         "participants": [], "unacked": []}
                    self._heartbeats[txn_id] = time.monotonic()
            elif action == "commit":
                # Commit applies ONLY onto an existing pending record: a
                # missing record means the txn was aborted (record dropped
                # by a participant-less abort) or already fully applied —
                # committing onto None would resurrect an aborted txn whose
                # intents a wounding writer already removed (partial
                # commit). The ordered log arbitrates commit-vs-abort.
                if rec is not None and rec["status"] == "pending":
                    parts = list(body.get("participants", []))
                    self.txns[txn_id] = {
                        "status": "committed",
                        "commit_ht": body["commit_ht"],
                        "participants": parts,
                        "unacked": list(parts),
                    }
            elif action == "abort":
                if rec is None or rec["status"] == "pending":
                    parts = list(body.get("participants", []))
                    if parts:
                        self.txns[txn_id] = {
                            "status": "aborted", "commit_ht": 0,
                            "participants": parts, "unacked": list(parts),
                        }
                    else:
                        # No known participants: drop the record — an
                        # unknown txn reads as aborted, and stray intents
                        # are cleaned lazily on conflict/read resolution.
                        self.txns.pop(txn_id, None)
                        self._heartbeats.pop(txn_id, None)
            elif action == "ack":
                if rec is not None:
                    rec["unacked"] = [u for u in rec["unacked"]
                                      if u[0] != body["tablet_id"]]
                    # Fully-applied records are NOT dropped here: a client
                    # retrying a commit whose response was lost must still
                    # read "committed". The notifier GCs them after a
                    # retention window via a replicated "gc" op.
            elif action == "gc":
                if rec is not None and rec["status"] != "pending" and \
                        not rec["unacked"]:
                    del self.txns[txn_id]
                    self._heartbeats.pop(txn_id, None)
                    self._done_seen.pop(txn_id, None)

    # -- commit-time choreography -------------------------------------------
    def choose_commit_ht(self, txn_id: str, clock) -> int:
        """Pick the commit hybrid time and mark the commit in flight —
        atomically with respect to resolve_status()'s clock ratchet, so
        a status query either sees the in-flight commit or has already
        ratcheted the clock above its own read time."""
        with self._lock:
            ht = clock.now().value
            self._committing[txn_id] = ht
            return ht

    def finish_commit_attempt(self, txn_id: str) -> None:
        with self._lock:
            self._committing.pop(txn_id, None)
            self._cond.notify_all()

    def resolve_status(self, txn_id: str, read_ht: int, clock,
                       timeout: float = 3.0) -> dict | None:
        """Status at the asker's read time. Ratchets the clock past
        read_ht first (the promise), then waits out any in-flight commit
        of this txn. None = could not resolve within the timeout."""
        from yugabyte_db_tpu.utils.hybrid_time import HybridTime

        deadline = time.monotonic() + timeout
        with self._lock:
            clock.update(HybridTime(read_ht))
            while txn_id in self._committing:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._cond.wait(remaining)
        return self.status(txn_id)

    # -- queries ------------------------------------------------------------
    def status(self, txn_id: str) -> dict:
        with self._lock:
            rec = self.txns.get(txn_id)
            if rec is None:
                # Unknown: never created, or committed+fully applied, or
                # aborted+cleaned. For a reader this is indistinguishable
                # from "aborted" EXCEPT that a fully-applied commit's rows
                # are already in the engines — both answers read correctly.
                return {"status": "aborted", "commit_ht": 0}
            return {"status": rec["status"], "commit_ht": rec["commit_ht"]}

    def heartbeat(self, txn_id: str) -> bool:
        with self._lock:
            rec = self.txns.get(txn_id)
            if rec is None or rec["status"] != "pending":
                return False
            self._heartbeats[txn_id] = time.monotonic()
            return True

    def expired_txns(self) -> list[str]:
        """Pending txns whose client went silent (leader-side soft check;
        the abort itself is replicated like any other)."""
        now = time.monotonic()
        out = []
        with self._lock:
            for txn_id, rec in self.txns.items():
                if rec["status"] != "pending":
                    continue
                hb = self._heartbeats.get(txn_id)
                if hb is None:
                    # Seen via replay/failover with no local heartbeat yet:
                    # start the clock now.
                    self._heartbeats[txn_id] = now
                elif now - hb > self.expiry_s:
                    out.append(txn_id)
        return out

    def pending_notifications(self) -> list[tuple[str, str, int, list[str]]]:
        """(txn_id, action, commit_ht, unacked tablets) for resolved txns
        whose participants haven't all acknowledged."""
        out = []
        with self._lock:
            for txn_id, rec in self.txns.items():
                if rec["status"] == "committed" and rec["unacked"]:
                    out.append((txn_id, "apply", rec["commit_ht"],
                                list(rec["unacked"])))
                elif rec["status"] == "aborted" and rec["unacked"]:
                    out.append((txn_id, "remove", 0, list(rec["unacked"])))
        return out

    def gc_candidates(self) -> list[str]:
        """Fully-applied records past the retention window (kept that
        long so commit retries stay answerable)."""
        now = time.monotonic()
        out = []
        with self._lock:
            for txn_id, rec in self.txns.items():
                if rec["status"] == "pending" or rec["unacked"]:
                    self._done_seen.pop(txn_id, None)
                    continue
                first = self._done_seen.setdefault(txn_id, now)
                if now - first > self.done_retention_s:
                    out.append(txn_id)
        return out

    def stats(self) -> dict:
        with self._lock:
            by_status: dict[str, int] = {}
            for rec in self.txns.values():
                by_status[rec["status"]] = by_status.get(rec["status"], 0) + 1
            return {"txn_records": len(self.txns), **by_status}
