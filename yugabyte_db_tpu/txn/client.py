"""Compatibility shim: the client-side transaction API moved to
``yugabyte_db_tpu.client.transaction``.

TransactionManager/YBTransaction are client code — they drive YBClient
RPCs — so they belong in the client layer; keeping them here put a
client import inside the txn layer (4 layering/upward-import findings).
The server-side machinery (coordinator, participant) stays in this
package, and the shared exception types live in ``txn/errors.py``.

New code should import from ``yugabyte_db_tpu.client.transaction`` (or
``yugabyte_db_tpu.client``); this module re-exports the old names so
existing callers keep working. The one remaining upward import below is
the deliberate, suppressed price of backward compatibility.
"""

from __future__ import annotations

# yb-lint: disable=layering/upward-import
from yugabyte_db_tpu.client.transaction import (TransactionManager,
                                                YBTransaction)
from yugabyte_db_tpu.txn.errors import (TransactionAborted,
                                        TransactionConflict)

__all__ = ["TransactionAborted", "TransactionConflict",
           "TransactionManager", "YBTransaction"]
