"""Transaction error types.

These live at the bottom of the txn package so both sides of the stack
can raise/catch them without layering violations: the coordinator and
participant (txn layer) raise them upward, and the client-side session
API (yugabyte_db_tpu.client.transaction) imports them downward.
"""

from __future__ import annotations


class TransactionConflict(Exception):
    """The transaction lost a conflict and must be retried by the app."""


class TransactionAborted(Exception):
    """The transaction was aborted (expiry, wound, or explicit)."""
