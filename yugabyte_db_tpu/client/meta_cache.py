"""MetaCache: table -> tablet locations + leader tracking.

Reference analog: src/yb/client/meta_cache.cc — the client-side cache of
tablet partition ranges, replica sets, and last-known leaders; refreshed
from the master on miss and corrected by NOT_THE_LEADER responses.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from yugabyte_db_tpu.utils.locking import guarded_by
from yugabyte_db_tpu.utils.retry import RetryPolicy

# A location lookup retries only transient master-side failures; a
# missing table ("not_found") is terminal here — unlike the tablet-RPC
# loop, where not_found means a replica is mid-move.
_LOOKUP_RETRIABLE = frozenset({"timed_out", "service_unavailable",
                               "try_again"})


@dataclass
class TabletLocation:
    tablet_id: str
    partition_start: int
    partition_end: int
    replicas: list[str] = field(default_factory=list)
    leader: str | None = None
    # replica uuid -> {"cloud", "region", "zone"} (zone-aware routing)
    replica_clouds: dict = field(default_factory=dict)

    def contains(self, hash_code: int) -> bool:
        return self.partition_start <= hash_code < self.partition_end


@dataclass
class TableLocations:
    table_id: str
    schema_dict: dict
    tablets: list[TabletLocation] = field(default_factory=list)  # sorted


@guarded_by("_lock", "_tables")
class MetaCache:
    def __init__(self, client):
        self._client = client
        self._lock = threading.Lock()
        self._tables: dict[str, TableLocations] = {}
        self.retry_policy = RetryPolicy(
            timeout_s=5.0, initial_backoff_s=0.05, max_backoff_s=0.5,
            retriable_wire_codes=_LOOKUP_RETRIABLE)

    def locations(self, table_name: str,
                  refresh: bool = False) -> TableLocations:
        with self._lock:
            locs = self._tables.get(table_name)
        if locs is not None and not refresh:
            return locs
        resp = None
        for attempt in self.retry_policy.attempts():
            resp = self._client.master_rpc("master.get_table_locations",
                                           {"name": table_name})
            if not self.retry_policy.retriable(resp):
                break
            attempt.note(resp)
        if resp is None or resp.get("code") != "ok":
            raise KeyError(f"table {table_name!r}: {resp}")
        locs = TableLocations(resp["table_id"], resp["schema"])
        for t in resp["tablets"]:
            locs.tablets.append(TabletLocation(
                t["tablet_id"], t["partition_start"], t["partition_end"],
                [r["uuid"] for r in t["replicas"]], t.get("leader"),
                {r["uuid"]: r.get("cloud_info") or {}
                 for r in t["replicas"]}))
        with self._lock:
            self._tables[table_name] = locs
        return locs

    def lookup_by_hash(self, table_name: str, hash_code: int) -> TabletLocation:
        """Route a key's hash code to its tablet (the EP-routing analog).
        A miss inside the table's range (invalidate_tablet punched the
        owning tablet out after a split) does ONE refreshing lookup."""
        locs = self.locations(table_name)
        for t in locs.tablets:
            if t.contains(hash_code):
                return t
        locs = self.locations(table_name, refresh=True)
        for t in locs.tablets:
            if t.contains(hash_code):
                return t
        raise KeyError(f"no tablet for hash {hash_code} in {table_name}")

    def mark_leader(self, table_name: str, tablet_id: str,
                    leader: str | None) -> None:
        with self._lock:
            locs = self._tables.get(table_name)
            if locs is None:
                return
            for t in locs.tablets:
                if t.tablet_id == tablet_id:
                    t.leader = leader

    def invalidate(self, table_name: str | None = None) -> None:
        with self._lock:
            if table_name is None:
                self._tables.clear()
            else:
                self._tables.pop(table_name, None)

    def invalidate_tablet(self, table_name: str, tablet_id: str) -> None:
        """Per-TABLET invalidation (the tablet_split wire code's
        contract): punch just the split tablet out of the cached
        location list so the next lookup touching its range re-fetches,
        while every sibling's cached location — and its learned leader
        hint — survives (reference: meta_cache.cc marking one
        RemoteTablet stale on TABLET_SPLIT instead of dropping the
        table)."""
        with self._lock:
            locs = self._tables.get(table_name)
            if locs is None:
                return
            kept = [t for t in locs.tablets if t.tablet_id != tablet_id]
            if len(kept) == len(locs.tablets):
                return  # unknown tablet: nothing cached to punch out
            if kept:
                locs.tablets = kept
            else:
                self._tables.pop(table_name, None)

    def covers(self, table_name: str, hash_code: int) -> bool:
        """True when the cached location list has a tablet owning
        ``hash_code`` (False after invalidate_tablet punched its range
        out — the caller should do a refreshing lookup)."""
        with self._lock:
            locs = self._tables.get(table_name)
            if locs is None:
                return False
            return any(t.contains(hash_code) for t in locs.tablets)
