"""YBSession: buffered ops, per-tablet batching, scans with merge.

Reference analog: src/yb/client/session.cc (YBSession::Apply/FlushAsync)
+ batcher.cc (group ops per tablet, one RPC per tablet per flush) + the
frontend-side result merging the reference does for multi-tablet reads
(CQL executor page merging; aggregate combine as in
PgsqlReadOperation partials, src/yb/docdb/pgsql_operation.cc:473).

Aggregate fan-out: avg is decomposed into sum+count partials per tablet
and recombined here — the cross-shard combine (CP analog) of SURVEY §2.4.
"""

from __future__ import annotations

from yugabyte_db_tpu.client.client import YBClient, YBTable
from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.storage import rowblock, wire
from yugabyte_db_tpu.storage.row_version import MAX_HT, RowVersion
from yugabyte_db_tpu.storage.scan_spec import (AggSpec, Predicate, ScanResult,
                                               ScanSpec)
from yugabyte_db_tpu.utils.metrics import count_swallowed
from yugabyte_db_tpu.utils.status import TabletSplit

# Key-column dtype codes for the native batch encoder (writeplane.cc).
_KEY_DTYPE_CODE = {DataType.BOOL: 0, DataType.FLOAT: 2, DataType.DOUBLE: 2,
                   DataType.STRING: 3, DataType.BINARY: 4}


def _row_hash_code(key: bytes) -> int:
    """Partition hash of an encoded doc key (TAG_HASH + 2-byte code) —
    re-routing a materialized row after a tablet split."""
    from yugabyte_db_tpu.models.encoding import TAG_HASH

    if len(key) >= 3 and key[0] == TAG_HASH:
        return int.from_bytes(key[1:3], "big")
    return 0


def _table_block_desc(table: YBTable):
    """The (hash_cols, range_cols, value_cols, valmap) descriptor the
    native encoder takes, cached on the table handle; None when any key
    column's type is not key-encodable natively."""
    desc = getattr(table, "_block_desc", False)
    if desc is not False:
        return desc

    def code(dtype: DataType):
        if dtype.is_integer:
            return 1
        return _KEY_DTYPE_CODE.get(dtype)

    schema = table.schema
    hash_cols = tuple((c.name, code(c.dtype)) for c in schema.hash_columns)
    range_cols = tuple((c.name, code(c.dtype)) for c in schema.range_columns)
    if any(c[1] is None for c in hash_cols + range_cols):
        desc = None
    else:
        desc = (hash_cols, range_cols,
                tuple((c.name, c.col_id) for c in schema.value_columns),
                {c.name: c.col_id for c in schema.value_columns})
    table._block_desc = desc
    return desc


class YBSession:
    # One process-wide batcher pool shared by every session: bounded at 16
    # threads total (instead of 16 per session) and alive for the process
    # lifetime — flush() never nests another flush, so sharing can't
    # deadlock.
    _shared_pool = None
    _shared_pool_lock = __import__("threading").Lock()

    def __init__(self, client: YBClient):
        self.client = client
        # Unified write buffer, in op order. Entries are either
        #   ("b", table, kind, key_src, cols_src, expire_ht, ttl_us)
        # (block-eligible: encoded natively at flush, zero per-row
        # Python work — the native write plane) or
        #   ("r", table, hash_code, row)
        # (a materialized RowVersion: counters, processor-built rows).
        # A table whose flush contains ANY "r" op takes the row path for
        # ALL its ops, preserving same-key ordering within the flush.
        self._ops: list[tuple] = []

    # -- write ops -----------------------------------------------------------
    def insert(self, table: YBTable, values: dict,
               ttl_expire_ht: int = MAX_HT,
               ttl_us: int | None = None) -> None:
        names = getattr(table, "_key_names", None)
        if names is None:
            names = table._key_names = tuple(
                c.name for c in table.schema.key_columns)
        for n in names:
            if n not in values:
                raise KeyError(n)
        # Copy: the op encodes at flush time, and callers may legally
        # reuse/mutate their dict between ops (the old eager-encoding
        # API allowed it).
        self._ops.append(("b", table, 0, dict(values), None,
                          ttl_expire_ht, ttl_us))

    def update(self, table: YBTable, key_values: dict, set_values: dict,
               ttl_expire_ht: int = MAX_HT) -> None:
        value_ids = getattr(table, "_value_ids", None)
        if value_ids is None:
            value_ids = table._value_ids = {
                c.name for c in table.schema.value_columns}
        for name in set_values:
            if name not in table.col_id:
                raise KeyError(name)
        self._check_key_values(table, key_values)
        if all(n in value_ids for n in set_values):
            self._ops.append(("b", table, 1, dict(key_values),
                              dict(set_values), ttl_expire_ht, None))
            return
        # SET of a key column: historical behavior stores it under the
        # key column's id (a no-op for reads); the native encoder's
        # valmap has value columns only, so take the row path.
        cols = {table.col_id[n]: v for n, v in set_values.items()}
        row = RowVersion(table.encode_key(key_values), ht=0, liveness=False,
                         columns=cols, expire_ht=ttl_expire_ht)
        self._ops.append(("r", table, table.hash_code(key_values), row))

    def delete(self, table: YBTable, key_values: dict) -> None:
        self._check_key_values(table, key_values)
        self._ops.append(("b", table, 2, dict(key_values), None,
                          MAX_HT, None))

    @staticmethod
    def _check_key_values(table: YBTable, key_values: dict) -> None:
        """Eager missing-key validation — errors must surface at the op
        call (the old eager-encoding behavior), never mid-flush where
        the buffer is already popped."""
        names = getattr(table, "_key_names", None)
        if names is None:
            names = table._key_names = tuple(
                c.name for c in table.schema.key_columns)
        for n in names:
            if n not in key_values:
                raise KeyError(n)

    def apply_row(self, table: YBTable, hash_code: int, row: RowVersion) -> None:
        self._ops.append(("r", table, hash_code, row))

    @property
    def pending_ops(self) -> int:
        return len(self._ops)

    def _op_to_row(self, op) -> tuple[YBTable, int, RowVersion]:
        """Materialize one buffered op as (table, hash_code, RowVersion)
        — the row-path fallback."""
        if op[0] == "r":
            return op[1], op[2], op[3]
        _tag, table, kind, key_src, cols_src, expire_ht, ttl_us = op
        key_values = {c.name: key_src[c.name]
                      for c in table.schema.key_columns}
        if kind == 0:
            cols = {table.col_id[c.name]: key_src[c.name]
                    for c in table.schema.value_columns
                    if c.name in key_src}
            row = RowVersion(table.encode_key(key_values), ht=0,
                             liveness=True, columns=cols,
                             expire_ht=expire_ht, ttl_us=ttl_us)
        elif kind == 1:
            cols = {table.col_id[n]: v for n, v in cols_src.items()}
            row = RowVersion(table.encode_key(key_values), ht=0,
                             liveness=False, columns=cols,
                             expire_ht=expire_ht)
        else:
            row = RowVersion(table.encode_key(key_values), ht=0,
                             tombstone=True)
        return table, table.hash_code(key_values), row

    def flush(self, timeout_s: float = 15.0) -> int:
        """Group buffered ops per tablet and issue the per-tablet write
        RPCs IN PARALLEL (the Batcher: each write waits a full Raft
        commit round, so serializing them would multiply flush latency by
        the tablet count — the reference's Batcher/AsyncRpc issues them
        concurrently, src/yb/client/batcher.h:80). Returns the number of
        rows written. Raises on any tablet failure (ops for OTHER tablets
        may have applied — same per-tablet atomicity as the reference
        without transactions).

        Block-eligible tables encode through the native write plane: ONE
        native call builds every tablet's row block (doc keys, partition
        hashes, per-tablet split), and the RPC payload is the block —
        rowblock.py / native/writeplane.cc."""
        ops, self._ops = self._ops, []
        # Partition ops per table; decide block vs row path per table.
        per_table: dict[str, list] = {}
        tables: dict[str, YBTable] = {}
        for op in ops:
            t = op[1]
            per_table.setdefault(t.name, []).append(op)
            tables[t.name] = t

        # (table, loc, rows) row groups / (table, loc, block, n) blocks
        row_groups: dict[str, tuple[YBTable, object, list]] = {}
        block_groups: list[tuple[YBTable, object, bytes, int]] = []

        def row_path(table, table_ops):
            for op in table_ops:
                _t, hash_code, row = self._op_to_row(op)
                loc = self.client.meta_cache.lookup_by_hash(table.name,
                                                            hash_code)
                g = row_groups.get(loc.tablet_id)
                if g is None:
                    g = row_groups[loc.tablet_id] = (table, loc, [])
                g[2].append((hash_code, row))

        errors = []
        for name, table_ops in per_table.items():
            table = tables[name]
            # One table's bad op must not drop OTHER tables' buffered
            # writes (the buffer is already popped): isolate per table,
            # surface the first error after everything else sent.
            try:
                desc = (_table_block_desc(table)
                        if rowblock.HAVE_NATIVE and
                        all(op[0] == "b" for op in table_ops) else None)
                if desc is None:
                    row_path(table, table_ops)
                    continue
                locs = self.client.meta_cache.locations(table.name)
                tablets = sorted(locs.tablets,
                                 key=lambda t: t.partition_start)
                try:
                    from yugabyte_db_tpu.native import yb_wp

                    parts = yb_wp.encode_ops(
                        desc, [op[2:] for op in table_ops],
                        [t.partition_start for t in tablets])
                except Exception:  # noqa: BLE001 — value shape the
                    row_path(table, table_ops)  # native encoder rejects:
                    continue                    # row path (canonical error)
                for t_loc, part in zip(tablets, parts):
                    if part is not None:
                        block_groups.append((table, t_loc, part[1],
                                             part[0]))
            except Exception as e:  # noqa: BLE001 — surfaced after sends
                errors.append(e)

        def send_rows(table, loc, hrows):
            """Write one tablet group of (hash_code, row) pairs. A
            tablet_split reply means the target was sealed by a split
            mid-flush: re-route every row by its hash through a fresh
            location lookup and keep going until the writes land (the
            split-commit window bounds how long the re-plan loop spins;
            the flush deadline bounds it absolutely)."""
            import time as _time

            deadline = _time.monotonic() + timeout_s
            pending = [(loc, hrows)]
            written = 0
            while pending:
                l, hr = pending.pop()
                try:
                    self.client.tablet_rpc(
                        table.name, l, "ts.write",
                        {"rows": wire.encode_rows([r for _h, r in hr]),
                         # Exactly-once across retries: tablet_rpc resends
                         # the SAME payload, so the id survives every
                         # retry attempt.
                         "client_id": self.client.client_id,
                         "request_id": self.client.next_request_id()},
                        timeout_s=timeout_s)
                    written += len(hr)
                except TabletSplit:
                    if _time.monotonic() >= deadline:
                        raise
                    _time.sleep(0.05)
                    regrouped: dict = {}
                    for h, r in hr:
                        nl = self.client.meta_cache.lookup_by_hash(
                            table.name, h)
                        regrouped.setdefault(
                            nl.tablet_id, (nl, []))[1].append((h, r))
                    pending.extend(regrouped.values())
            return written

        def block_hrows(block):
            # split re-plan fallback for a native block: materialize the
            # rows and re-route them down the row path
            return [(_row_hash_code(r.key), r)
                    for r in rowblock.rows_from_block(block)]

        written = 0
        # Row groups replicate in parallel on the batcher pool while the
        # caller's own thread pipelines the block groups.
        futs = [self._pool().submit(send_rows, *g)
                for g in row_groups.values()]
        # Block groups: two-phase pipeline from THIS thread — admit every
        # tablet's block (returns at append, before commit), then collect
        # the outcomes. One thread drives N tablets' replication rounds
        # concurrently with zero pool hops (reference: the async client
        # write pipeline, src/yb/client/async_rpc.cc).
        cid = self.client.client_id
        pending = []
        for table, loc, block, n in block_groups:
            rid = self.client.next_request_id()
            try:
                resp = self.client.tablet_rpc(
                    table.name, loc, "ts.write_admit",
                    {"rows": block, "client_id": cid, "request_id": rid},
                    timeout_s=timeout_s)
            except TabletSplit:
                try:
                    written += send_rows(table, loc, block_hrows(block))
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                continue
            except Exception as e:  # noqa: BLE001 — surfaced after joins
                errors.append(e)
                continue
            if resp.get("admitted"):
                pending.append((table, loc, block, n, rid))
            else:
                written += n  # completed synchronously (dup / slow path)
        for table, loc, block, n, rid in pending:
            try:
                resp = self.client.tablet_rpc(
                    table.name, loc, "ts.write_sync",
                    {"client_id": cid, "request_id": rid},
                    timeout_s=timeout_s)
                if resp.get("retry_write"):
                    # The admitted entry was lost to a leader change
                    # before commit: re-send the full write under the
                    # SAME id (dedup keeps it exactly-once).
                    self.client.tablet_rpc(
                        table.name, loc, "ts.write",
                        {"rows": block, "client_id": cid,
                         "request_id": rid}, timeout_s=timeout_s)
                written += n
            except TabletSplit:
                # Sealed mid-pipeline: the admitted entry either landed
                # below the seal (value-identical re-apply on the child)
                # or was never admitted — re-route down the row path.
                try:
                    written += send_rows(table, loc, block_hrows(block))
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
            except Exception as e:  # noqa: BLE001
                errors.append(e)
        for f in futs:
            try:
                written += f.result()
            except Exception as e:
                errors.append(e)
        if errors:
            raise errors[0]
        return written

    @classmethod
    def _pool(cls):
        with cls._shared_pool_lock:
            if cls._shared_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                cls._shared_pool = ThreadPoolExecutor(
                    max_workers=16, thread_name_prefix="session-batcher")
            return cls._shared_pool

    # -- point read ----------------------------------------------------------
    def get(self, table: YBTable, key_values: dict) -> tuple | None:
        """Point read by full primary key."""
        from yugabyte_db_tpu.models.encoding import prefix_successor
        key = table.encode_key(key_values)
        spec = ScanSpec(lower=key, upper=prefix_successor(key), limit=1)
        res = self.scan(table, spec)
        return res.rows[0] if res.rows else None

    def get_many(self, table: YBTable, kv_list: list[dict],
                 timeout_s: float = 30.0) -> list[tuple | None]:
        """Batched point reads: keys group by tablet and each tablet
        serves its whole group in ONE scan-batch RPC (reference: the
        batcher packing many ops per tserver call,
        src/yb/client/batcher.h:80). Results align with kv_list.
        Re-plans from refreshed locations when a tablet splits
        mid-batch (reads are idempotent: a full replay is safe)."""
        return self._split_replan(
            table, timeout_s,
            lambda: self._get_many_once(table, kv_list, timeout_s))

    def _get_many_once(self, table: YBTable, kv_list: list[dict],
                       timeout_s: float) -> list[tuple | None]:
        from yugabyte_db_tpu.models.encoding import prefix_successor

        groups: dict = {}
        for i, kv in enumerate(kv_list):
            key = table.encode_key(kv)
            hc = table.hash_code(kv)
            loc = self.client.meta_cache.lookup_by_hash(table.name, hc)
            spec = ScanSpec(lower=key, upper=prefix_successor(key),
                            limit=1)
            g = groups.get(loc.tablet_id)
            if g is None:
                g = groups[loc.tablet_id] = (loc, [])
            g[1].append((i, spec))
        out: list = [None] * len(kv_list)
        for loc, items in groups.values():
            resp = self.client.tablet_rpc(
                table.name, loc, "ts.scan_batch",
                {"specs": [wire.encode_spec(s) for _i, s in items]},
                timeout_s=timeout_s)
            for (i, _s), enc in zip(items, resp["results"]):
                res = wire.decode_result(enc)
                out[i] = res.rows[0] if res.rows else None
        return out

    # -- scans ---------------------------------------------------------------
    def _stale_prefer(self, loc) -> str | None:
        """Same-zone replica for a stale read (read-replica routing):
        prefer a replica matching the client's locality labels."""
        ci = self.client.cloud_info
        if not ci:
            return None
        for r in loc.replicas:
            if loc.replica_clouds.get(r) == ci:
                return r
        return None

    def _split_replan(self, table: YBTable, timeout_s: float, fn):
        """Run an idempotent read ``fn``, restarting it from refreshed
        locations whenever a tablet splits underneath it. During the
        seal->commit window the refreshed list still names the sealed
        parent, so the loop keeps re-trying (bounded by timeout_s)
        until the children start serving."""
        import time as _time

        deadline = _time.monotonic() + timeout_s
        while True:
            try:
                return fn()
            except TabletSplit as e:
                if _time.monotonic() >= deadline:
                    raise
                _time.sleep(0.05)
                try:
                    self.client.meta_cache.locations(table.name,
                                                     refresh=True)
                except Exception as err:  # noqa: BLE001 — retry decides
                    count_swallowed("session.split_replan", err)
                del e

    def scan(self, table: YBTable, spec: ScanSpec,
             timeout_s: float = 30.0, stale_ok: bool = False) -> ScanResult:
        """Split-aware scan entry point: the fan-out restarts from
        refreshed locations when a tablet splits mid-scan (scans are
        idempotent; a full replay cannot duplicate side effects)."""
        return self._split_replan(
            table, timeout_s,
            lambda: self._scan_once(table, spec, timeout_s, stale_ok))

    def _scan_once(self, table: YBTable, spec: ScanSpec,
                   timeout_s: float = 30.0,
                   stale_ok: bool = False) -> ScanResult:
        """Fan a scan out over the table's tablets and merge.

        Row scans: tablets are visited in partition order, honoring
        spec.limit across tablets with per-tablet paging. Aggregates:
        per-tablet partials combined client-side (avg via sum+count).

        ``stale_ok``: serve from ANY replica at its applied state
        (bounded-staleness read-replica reads) — same-zone replicas are
        preferred when the client carries locality labels (reference:
        follower reads / read replicas, master.proto read_replicas)."""
        if spec.is_aggregate:
            return self._scan_aggregate(table, spec, timeout_s, stale_ok)
        locs = self.client.meta_cache.locations(table.name)
        # Snapshot consistency across pages/tablets: the first sub-scan's
        # server-chosen read time is pinned for every subsequent request
        # (the reference's ConsistentReadPoint contract — the server returns
        # the chosen read_ht precisely so the client can pin it). The
        # mutable scan state is shared with the mesh-group helper.
        state = {"rows": [], "columns": [], "scanned": 0,
                 "read_ht": spec.read_ht}
        # Mesh path first: CONSECUTIVE tablets led by the same tserver
        # page as ONE ts.multi_row_scan group — the tserver runs them as
        # one device program (tserver.mesh_scan) and the cross-tablet
        # resume token stays opaque here. Consecutive-only keeps rows in
        # partition (key) order; singleton or ineligible groups take the
        # per-tablet path below.
        groups: list[tuple[str | None, list]] = []
        for loc in locs.tablets:
            leader = (loc.leader if (not stale_ok and not spec.group_by
                                     and table.engine == "tpu") else None)
            if groups and leader is not None and groups[-1][0] == leader:
                groups[-1][1].append(loc)
            else:
                groups.append((leader, [loc]))
        for leader, group in groups:
            if spec.limit is not None and len(state["rows"]) >= spec.limit:
                break
            if leader is not None and len(group) >= 2 and \
                    self._mesh_row_pages(leader, group, spec, state,
                                         timeout_s):
                continue
            for loc in group:
                resume = spec.lower
                while True:
                    remaining = (None if spec.limit is None
                                 else spec.limit - len(state["rows"]))
                    if remaining is not None and remaining <= 0:
                        return ScanResult(state["columns"], state["rows"],
                                          None, state["scanned"])
                    sub = ScanSpec(lower=resume, upper=spec.upper,
                                   read_ht=state["read_ht"],
                                   predicates=spec.predicates,
                                   projection=spec.projection,
                                   limit=remaining,
                                   group_by=spec.group_by)
                    payload = {"spec": wire.encode_spec(sub)}
                    if stale_ok:
                        payload["allow_stale"] = True
                    resp = self.client.tablet_rpc(
                        table.name, loc, "ts.scan", payload,
                        timeout_s=timeout_s,
                        prefer=self._stale_prefer(loc) if stale_ok else None,
                        mark_leader=not stale_ok)
                    if "read_ht" in resp:
                        state["read_ht"] = resp["read_ht"]
                    res = wire.decode_result(resp)
                    state["columns"] = res.columns
                    state["rows"].extend(res.rows)
                    state["scanned"] += res.rows_scanned
                    if res.resume_key is None:
                        break
                    resume = res.resume_key
        return ScanResult(state["columns"], state["rows"], None,
                          state["scanned"])

    def _mesh_row_pages(self, leader: str, group: list, spec: ScanSpec,
                        state: dict, timeout_s: float) -> bool:
        """Page one leader's consecutive-tablet group through
        ts.multi_row_scan (the whole group served per page by ONE mesh
        device program). Returns True when the group was fully served
        (or the global limit filled) on the mesh; False rolls back any
        partial mesh pages for the group and sends the caller down the
        per-tablet path — so a mid-stream failure can never duplicate or
        drop rows."""
        mark_rows, mark_scanned = len(state["rows"]), state["scanned"]
        resume = None
        mesh_timeout = min(5.0, timeout_s)
        while True:
            remaining = (None if spec.limit is None
                         else spec.limit - len(state["rows"]))
            if remaining is not None and remaining <= 0:
                return True
            sub = ScanSpec(lower=spec.lower, upper=spec.upper,
                           read_ht=state["read_ht"],
                           predicates=spec.predicates,
                           projection=spec.projection, limit=remaining)
            payload = {"tablet_ids": [g.tablet_id for g in group],
                       "spec": wire.encode_spec(sub),
                       # Budget rides server-side (below the transport
                       # timeout) so a slow pin returns a clean timed_out
                       # and the per-tablet fallback still has time.
                       "timeout": max(0.05, round(mesh_timeout * 0.8, 3))}
            if resume is not None:
                payload["resume"] = resume
            try:
                resp = self.client.transport.send(
                    leader, "ts.multi_row_scan", payload,
                    timeout=mesh_timeout)
            except Exception as e:  # noqa: BLE001 — per-tablet fallback
                count_swallowed("session.multi_row_scan", e)
                resp = {}
            if resp.get("code") != "ok":
                del state["rows"][mark_rows:]
                state["scanned"] = mark_scanned
                return False
            if "read_ht" in resp:
                state["read_ht"] = resp["read_ht"]
            res = wire.decode_result(resp)
            state["columns"] = res.columns
            state["rows"].extend(res.rows)
            state["scanned"] += res.rows_scanned
            if res.resume_key is None:
                return True
            resume = res.resume_key

    def _scan_aggregate(self, table: YBTable, spec: ScanSpec,
                        timeout_s: float,
                        stale_ok: bool = False) -> ScanResult:
        # Decompose avg into sum+count partials (reference: per-tablet
        # EvalAggregate partials recombined above the scan).
        partial_aggs: list[AggSpec] = []
        mapping: list[tuple[str, int, int | None]] = []
        for a in spec.aggregates:
            if a.fn == "avg":
                mapping.append(("avg", len(partial_aggs),
                                len(partial_aggs) + 1))
                partial_aggs.append(AggSpec("sum", a.column, expr=a.expr))
                partial_aggs.append(AggSpec("count", a.column, expr=a.expr))
            else:
                mapping.append((a.fn, len(partial_aggs), None))
                partial_aggs.append(a)
        locs = self.client.meta_cache.locations(table.name)
        gb = spec.group_by or []
        ngb = len(gb)
        # group key -> per-partial-agg accumulators
        groups: dict[tuple, list[list]] = {}
        scanned = 0
        read_ht = spec.read_ht  # pinned after the first sub-scan (see scan())

        def consume(resp):
            nonlocal read_ht, scanned
            if "read_ht" in resp:
                read_ht = resp["read_ht"]
            res = wire.decode_result(resp)
            scanned += res.rows_scanned
            for row in res.rows:
                gkey = tuple(row[:ngb])
                groups.setdefault(gkey, []).append(list(row[ngb:]))

        # Mesh path first: tablets grouped by leading tserver, ONE
        # ts.multi_agg_scan per group — the tserver runs all its tablets
        # as one device program with an ICI collective combine
        # (tserver.mesh_scan). Any non-ok reply demotes that group to the
        # per-tablet path below; the host combine here remains only the
        # cross-tserver (and fallback) merge.
        remaining_tablets = list(locs.tablets)
        if not gb and table.engine == "tpu" and not stale_ok:
            by_leader: dict[str, list] = {}
            for loc in locs.tablets:
                if loc.leader:
                    by_leader.setdefault(loc.leader, []).append(loc)
            for leader, group in by_leader.items():
                if len(group) < 2:
                    continue
                sub = ScanSpec(lower=spec.lower, upper=spec.upper,
                               read_ht=read_ht, predicates=spec.predicates,
                               aggregates=partial_aggs)
                mesh_timeout = min(5.0, timeout_s)
                try:
                    # Budget rides server-side (below the transport
                    # timeout) so a slow pin returns a clean timed_out
                    # and the per-tablet fallback still has time to run.
                    resp = self.client.transport.send(
                        leader, "ts.multi_agg_scan",
                        {"tablet_ids": [g.tablet_id for g in group],
                         "spec": wire.encode_spec(sub),
                         "timeout": max(0.05,
                                        round(mesh_timeout * 0.8, 3))},
                        timeout=mesh_timeout)
                except Exception as e:  # noqa: BLE001 — per-tablet fallback
                    count_swallowed("session.multi_agg_scan", e)
                    continue
                if resp.get("code") != "ok":
                    continue
                consume(resp)
                served = {g.tablet_id for g in group}
                remaining_tablets = [t for t in remaining_tablets
                                     if t.tablet_id not in served]

        for loc in remaining_tablets:
            sub = ScanSpec(lower=spec.lower, upper=spec.upper,
                           read_ht=read_ht, predicates=spec.predicates,
                           aggregates=partial_aggs, group_by=spec.group_by)
            payload = {"spec": wire.encode_spec(sub)}
            if stale_ok:
                payload["allow_stale"] = True
            resp = self.client.tablet_rpc(
                table.name, loc, "ts.scan", payload, timeout_s=timeout_s,
                prefer=self._stale_prefer(loc) if stale_ok else None,
                mark_leader=not stale_ok)
            consume(resp)
        if not groups and not gb:
            groups[()] = []
        out_rows = []
        for gkey in sorted(groups, key=_group_sort_key):
            partials = groups[gkey]
            combined: list = []
            for i, a in enumerate(partial_aggs):
                vals = [p[i] for p in partials if p[i] is not None]
                if a.fn == "count":
                    combined.append(sum(vals) if vals else 0)
                elif a.fn == "sum":
                    combined.append(sum(vals) if vals else None)
                elif a.fn == "min":
                    combined.append(min(vals) if vals else None)
                elif a.fn == "max":
                    combined.append(max(vals) if vals else None)
            row = list(gkey)
            for fn, i, j in mapping:
                if fn == "avg":
                    s, n = combined[i], combined[j]
                    row.append(s / n if n else None)
                else:
                    row.append(combined[i])
            out_rows.append(tuple(row))
        names = list(gb)
        for a in spec.aggregates:
            names.append(a.output_name)
        return ScanResult(names, out_rows, None, scanned)


def _group_sort_key(gkey: tuple):
    # Matches the engine-side group ordering (cpu_engine._sortable).
    return tuple((v is None, v) for v in gkey)
