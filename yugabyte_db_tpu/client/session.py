"""YBSession: buffered ops, per-tablet batching, scans with merge.

Reference analog: src/yb/client/session.cc (YBSession::Apply/FlushAsync)
+ batcher.cc (group ops per tablet, one RPC per tablet per flush) + the
frontend-side result merging the reference does for multi-tablet reads
(CQL executor page merging; aggregate combine as in
PgsqlReadOperation partials, src/yb/docdb/pgsql_operation.cc:473).

Aggregate fan-out: avg is decomposed into sum+count partials per tablet
and recombined here — the cross-shard combine (CP analog) of SURVEY §2.4.
"""

from __future__ import annotations

from yugabyte_db_tpu.client.client import YBClient, YBTable
from yugabyte_db_tpu.storage import wire
from yugabyte_db_tpu.storage.row_version import MAX_HT, RowVersion
from yugabyte_db_tpu.storage.scan_spec import (AggSpec, Predicate, ScanResult,
                                               ScanSpec)


class YBSession:
    # One process-wide batcher pool shared by every session: bounded at 16
    # threads total (instead of 16 per session) and alive for the process
    # lifetime — flush() never nests another flush, so sharing can't
    # deadlock.
    _shared_pool = None
    _shared_pool_lock = __import__("threading").Lock()

    def __init__(self, client: YBClient):
        self.client = client
        self._ops: list[tuple[YBTable, int, RowVersion]] = []

    # -- write ops -----------------------------------------------------------
    def insert(self, table: YBTable, values: dict,
               ttl_expire_ht: int = MAX_HT,
               ttl_us: int | None = None) -> None:
        key_values = {c.name: values[c.name] for c in table.schema.key_columns}
        cols = {table.col_id[c.name]: values[c.name]
                for c in table.schema.value_columns if c.name in values}
        row = RowVersion(table.encode_key(key_values), ht=0, liveness=True,
                         columns=cols, expire_ht=ttl_expire_ht,
                         ttl_us=ttl_us)
        self._ops.append((table, table.hash_code(key_values), row))

    def update(self, table: YBTable, key_values: dict, set_values: dict,
               ttl_expire_ht: int = MAX_HT) -> None:
        cols = {table.col_id[name]: v for name, v in set_values.items()}
        row = RowVersion(table.encode_key(key_values), ht=0, liveness=False,
                         columns=cols, expire_ht=ttl_expire_ht)
        self._ops.append((table, table.hash_code(key_values), row))

    def delete(self, table: YBTable, key_values: dict) -> None:
        row = RowVersion(table.encode_key(key_values), ht=0, tombstone=True)
        self._ops.append((table, table.hash_code(key_values), row))

    def apply_row(self, table: YBTable, hash_code: int, row: RowVersion) -> None:
        self._ops.append((table, hash_code, row))

    @property
    def pending_ops(self) -> int:
        return len(self._ops)

    def flush(self, timeout_s: float = 15.0) -> int:
        """Group buffered ops per tablet and issue the per-tablet write
        RPCs IN PARALLEL (the Batcher: each write waits a full Raft
        commit round, so serializing them would multiply flush latency by
        the tablet count — the reference's Batcher/AsyncRpc issues them
        concurrently, src/yb/client/batcher.h:80). Returns the number of
        rows written. Raises on any tablet failure (ops for OTHER tablets
        may have applied — same per-tablet atomicity as the reference
        without transactions)."""
        ops, self._ops = self._ops, []
        by_tablet: dict[str, tuple[YBTable, object, list]] = {}
        for table, hash_code, row in ops:
            loc = self.client.meta_cache.lookup_by_hash(table.name, hash_code)
            key = loc.tablet_id
            if key not in by_tablet:
                by_tablet[key] = (table, loc, [])
            by_tablet[key][2].append(row)

        def send(table, loc, rows):
            self.client.tablet_rpc(
                table.name, loc, "ts.write",
                {"rows": wire.encode_rows(rows),
                 # Exactly-once across retries: tablet_rpc resends the
                 # SAME payload, so the id survives every retry attempt.
                 "client_id": self.client.client_id,
                 "request_id": self.client.next_request_id()},
                timeout_s=timeout_s)
            return len(rows)

        groups = list(by_tablet.values())
        if len(groups) == 1:
            return send(*groups[0])
        futs = [self._pool().submit(send, *g) for g in groups]
        written = 0
        errors = []
        for f in futs:
            try:
                written += f.result()
            except Exception as e:
                errors.append(e)
        if errors:
            raise errors[0]
        return written

    @classmethod
    def _pool(cls):
        with cls._shared_pool_lock:
            if cls._shared_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                cls._shared_pool = ThreadPoolExecutor(
                    max_workers=16, thread_name_prefix="session-batcher")
            return cls._shared_pool

    # -- point read ----------------------------------------------------------
    def get(self, table: YBTable, key_values: dict) -> tuple | None:
        """Point read by full primary key."""
        from yugabyte_db_tpu.models.encoding import prefix_successor
        key = table.encode_key(key_values)
        spec = ScanSpec(lower=key, upper=prefix_successor(key), limit=1)
        res = self.scan(table, spec)
        return res.rows[0] if res.rows else None

    # -- scans ---------------------------------------------------------------
    def scan(self, table: YBTable, spec: ScanSpec,
             timeout_s: float = 30.0) -> ScanResult:
        """Fan a scan out over the table's tablets and merge.

        Row scans: tablets are visited in partition order, honoring
        spec.limit across tablets with per-tablet paging. Aggregates:
        per-tablet partials combined client-side (avg via sum+count)."""
        if spec.is_aggregate:
            return self._scan_aggregate(table, spec, timeout_s)
        locs = self.client.meta_cache.locations(table.name)
        out_rows: list[tuple] = []
        columns: list[str] = []
        scanned = 0
        remaining = spec.limit
        # Snapshot consistency across pages/tablets: the first sub-scan's
        # server-chosen read time is pinned for every subsequent request
        # (the reference's ConsistentReadPoint contract — the server returns
        # the chosen read_ht precisely so the client can pin it).
        read_ht = spec.read_ht
        for loc in locs.tablets:
            resume = spec.lower
            while True:
                sub = ScanSpec(lower=resume, upper=spec.upper,
                               read_ht=read_ht,
                               predicates=spec.predicates,
                               projection=spec.projection,
                               limit=remaining,
                               group_by=spec.group_by)
                resp = self.client.tablet_rpc(
                    table.name, loc, "ts.scan",
                    {"spec": wire.encode_spec(sub)}, timeout_s=timeout_s)
                if "read_ht" in resp:
                    read_ht = resp["read_ht"]
                res = wire.decode_result(resp)
                columns = res.columns
                out_rows.extend(res.rows)
                scanned += res.rows_scanned
                if remaining is not None:
                    remaining -= len(res.rows)
                    if remaining <= 0:
                        return ScanResult(columns, out_rows, None, scanned)
                if res.resume_key is None:
                    break
                resume = res.resume_key
        return ScanResult(columns, out_rows, None, scanned)

    def _scan_aggregate(self, table: YBTable, spec: ScanSpec,
                        timeout_s: float) -> ScanResult:
        # Decompose avg into sum+count partials (reference: per-tablet
        # EvalAggregate partials recombined above the scan).
        partial_aggs: list[AggSpec] = []
        mapping: list[tuple[str, int, int | None]] = []
        for a in spec.aggregates:
            if a.fn == "avg":
                mapping.append(("avg", len(partial_aggs),
                                len(partial_aggs) + 1))
                partial_aggs.append(AggSpec("sum", a.column, expr=a.expr))
                partial_aggs.append(AggSpec("count", a.column, expr=a.expr))
            else:
                mapping.append((a.fn, len(partial_aggs), None))
                partial_aggs.append(a)
        locs = self.client.meta_cache.locations(table.name)
        gb = spec.group_by or []
        ngb = len(gb)
        # group key -> per-partial-agg accumulators
        groups: dict[tuple, list[list]] = {}
        scanned = 0
        read_ht = spec.read_ht  # pinned after the first sub-scan (see scan())

        def consume(resp):
            nonlocal read_ht, scanned
            if "read_ht" in resp:
                read_ht = resp["read_ht"]
            res = wire.decode_result(resp)
            scanned += res.rows_scanned
            for row in res.rows:
                gkey = tuple(row[:ngb])
                groups.setdefault(gkey, []).append(list(row[ngb:]))

        # Mesh path first: tablets grouped by leading tserver, ONE
        # ts.multi_agg_scan per group — the tserver runs all its tablets
        # as one device program with an ICI collective combine
        # (tserver.mesh_scan). Any non-ok reply demotes that group to the
        # per-tablet path below; the host combine here remains only the
        # cross-tserver (and fallback) merge.
        remaining_tablets = list(locs.tablets)
        if not gb and table.engine == "tpu":
            by_leader: dict[str, list] = {}
            for loc in locs.tablets:
                if loc.leader:
                    by_leader.setdefault(loc.leader, []).append(loc)
            for leader, group in by_leader.items():
                if len(group) < 2:
                    continue
                sub = ScanSpec(lower=spec.lower, upper=spec.upper,
                               read_ht=read_ht, predicates=spec.predicates,
                               aggregates=partial_aggs)
                try:
                    resp = self.client.transport.send(
                        leader, "ts.multi_agg_scan",
                        {"tablet_ids": [g.tablet_id for g in group],
                         "spec": wire.encode_spec(sub)}, timeout=5.0)
                except Exception:  # noqa: BLE001 — per-tablet fallback
                    continue
                if resp.get("code") != "ok":
                    continue
                consume(resp)
                served = {g.tablet_id for g in group}
                remaining_tablets = [t for t in remaining_tablets
                                     if t.tablet_id not in served]

        for loc in remaining_tablets:
            sub = ScanSpec(lower=spec.lower, upper=spec.upper,
                           read_ht=read_ht, predicates=spec.predicates,
                           aggregates=partial_aggs, group_by=spec.group_by)
            resp = self.client.tablet_rpc(
                table.name, loc, "ts.scan",
                {"spec": wire.encode_spec(sub)}, timeout_s=timeout_s)
            consume(resp)
        if not groups and not gb:
            groups[()] = []
        out_rows = []
        for gkey in sorted(groups, key=_group_sort_key):
            partials = groups[gkey]
            combined: list = []
            for i, a in enumerate(partial_aggs):
                vals = [p[i] for p in partials if p[i] is not None]
                if a.fn == "count":
                    combined.append(sum(vals) if vals else 0)
                elif a.fn == "sum":
                    combined.append(sum(vals) if vals else None)
                elif a.fn == "min":
                    combined.append(min(vals) if vals else None)
                elif a.fn == "max":
                    combined.append(max(vals) if vals else None)
            row = list(gkey)
            for fn, i, j in mapping:
                if fn == "avg":
                    s, n = combined[i], combined[j]
                    row.append(s / n if n else None)
                else:
                    row.append(combined[i])
            out_rows.append(tuple(row))
        names = list(gb)
        for a in spec.aggregates:
            names.append(a.output_name)
        return ScanResult(names, out_rows, None, scanned)


def _group_sort_key(gkey: tuple):
    # Matches the engine-side group ordering (cpu_engine._sortable).
    return tuple((v is None, v) for v in gkey)
