"""Client library: location-aware, batching, retrying cluster access.

Reference analog: src/yb/client/ — YBClient (client.cc), YBSession +
Batcher grouping ops per tablet (batcher.h:80), MetaCache mapping
partition ranges to tablets and leaders (meta_cache.cc), and
TabletInvoker's replica-failover retry policy (tablet_rpc.h:52). The YQL
frontends sit on this API exactly as the reference's CQL/Redis/pggate
frontends sit on the C++ client.
"""

from yugabyte_db_tpu.client.client import YBClient, YBTable
from yugabyte_db_tpu.client.session import YBSession
from yugabyte_db_tpu.client.transaction import (TransactionManager,
                                                YBTransaction)

__all__ = ["TransactionManager", "YBClient", "YBTable", "YBSession",
           "YBTransaction"]
