"""Client-side transactions: TransactionManager + YBTransaction.

Reference analog: src/yb/client/transaction.cc (YBTransaction) and
transaction_manager.cc (status-tablet picker). A transaction:

    txn = manager.begin()
    txn.insert(table, {...}); txn.update(...); txn.delete_row(...)
    txn.flush()                  # intents to participant tablets
    commit_ht = txn.commit()     # coordinator decides; applies push async

Reads inside the transaction use txn.snapshot_spec()/txn.get() — a
snapshot at the txn's read point, with the txn's OWN buffered and
flushed writes overlaid for read-your-writes point lookups.

A read AFTER commit that must observe the transaction (causal
read-your-writes across sessions) passes read_ht >= commit_ht explicitly;
the server pins that read point and waits for the apply (the
ConsistentReadPoint contract).

This module lives in ``client/`` (not ``txn/``) because it is client
code: it drives YBClient RPCs and sits above the tablet/consensus layer
exactly like the reference's YBTransaction sits in src/yb/client/. The
server-side machinery (coordinator, participant) stays in ``txn/``; the
shared exception types live in ``txn/errors.py`` so both layers reach
them downward.
"""

from __future__ import annotations

import random
import threading
import time
import uuid as uuid_mod

from yugabyte_db_tpu.client.client import TabletOpFailed, YBClient, YBTable
from yugabyte_db_tpu.models.datatypes import DataType
from yugabyte_db_tpu.models.schema import ColumnKind, ColumnSchema
from yugabyte_db_tpu.storage import wire
from yugabyte_db_tpu.storage.row_version import MAX_HT, RowVersion
from yugabyte_db_tpu.utils.status import TabletSplit
from yugabyte_db_tpu.txn.coordinator import TXN_STATUS_TABLE
from yugabyte_db_tpu.txn.errors import (TransactionAborted,
                                        TransactionConflict)
from yugabyte_db_tpu.utils.metrics import count_swallowed

__all__ = ["TransactionAborted", "TransactionConflict",
           "TransactionManager", "YBTransaction"]


class TransactionManager:
    """Creates transactions against the shared status table."""

    def __init__(self, client: YBClient, num_status_tablets: int = 2,
                 heartbeat_interval_s: float = 2.0):
        self.client = client
        self.heartbeat_interval_s = heartbeat_interval_s
        self._ensured = False
        self.num_status_tablets = num_status_tablets
        # Background heartbeater: keeps every live txn from being expired
        # by the coordinator while the app reads/thinks between flushes
        # (reference: YBTransaction's heartbeat poller, transaction.cc).
        self._live_lock = threading.Lock()
        self._live: dict[str, "YBTransaction"] = {}
        self._hb_thread: threading.Thread | None = None

    def _register(self, txn: "YBTransaction") -> None:
        with self._live_lock:
            self._live[txn.txn_id] = txn
            if self._hb_thread is None:
                self._hb_thread = threading.Thread(
                    target=self._heartbeat_loop, name="txn-heartbeats",
                    daemon=True)
                self._hb_thread.start()

    def _deregister(self, txn_id: str) -> None:
        with self._live_lock:
            self._live.pop(txn_id, None)

    def _heartbeat_loop(self) -> None:
        while True:
            time.sleep(self.heartbeat_interval_s)
            with self._live_lock:
                txns = list(self._live.values())
            for txn in txns:
                if txn._state != "pending":
                    self._deregister(txn.txn_id)
                    continue
                try:
                    self.client.tablet_rpc(
                        TXN_STATUS_TABLE, txn.status_loc,
                        "ts.txn_heartbeat", {"txn_id": txn.txn_id},
                        timeout_s=3.0)
                except Exception as e:  # noqa: BLE001 — retried next tick
                    if getattr(e, "resp", {}).get("code") == "aborted":
                        txn._state = "aborted"
                        self._deregister(txn.txn_id)

    def ensure_status_table(self) -> None:
        # Lock-free: create_table is idempotent (already_present swallowed),
        # so concurrent first-callers racing the RPC is harmless and nobody
        # waits on a lock held across it. `_ensured` is a monotonic bool —
        # the benign double-set is cheaper than serializing begin().
        if self._ensured:
            return
        cols = [ColumnSchema("txn_id", DataType.STRING, ColumnKind.HASH)]
        try:
            self.client.create_table(
                TXN_STATUS_TABLE, cols,
                num_tablets=self.num_status_tablets)
        except Exception as e:  # noqa: BLE001
            if "already_present" not in str(e):
                raise
        self._ensured = True

    def begin(self) -> "YBTransaction":
        self.ensure_status_table()
        locs = self.client.meta_cache.locations(TXN_STATUS_TABLE)
        loc = random.choice(locs.tablets)
        txn_id = uuid_mod.uuid4().hex
        try:
            resp = self.client.tablet_rpc(
                TXN_STATUS_TABLE, loc, "ts.txn_create",
                {"txn_id": txn_id})
        except TabletSplit:
            # The cached status tablet was superseded (a split committed,
            # or a concurrent first-begin recreated the table): re-resolve
            # once against a fresh listing and retry.
            locs = self.client.meta_cache.locations(
                TXN_STATUS_TABLE, refresh=True)
            loc = random.choice(locs.tablets)
            resp = self.client.tablet_rpc(
                TXN_STATUS_TABLE, loc, "ts.txn_create",
                {"txn_id": txn_id})
        txn = YBTransaction(self, txn_id, loc, resp["read_ht"])
        self._register(txn)
        return txn


class YBTransaction:
    def __init__(self, manager: TransactionManager, txn_id: str,
                 status_loc, read_ht: int):
        self.manager = manager
        self.client = manager.client
        self.txn_id = txn_id
        self.status_loc = status_loc
        self.read_ht = read_ht
        self.priority = random.getrandbits(32)
        self._ops: list[tuple[YBTable, int, RowVersion]] = []
        # tablet_id -> leader hint for every tablet holding our intents
        self._participants: dict[str, str | None] = {}
        # own-writes overlay for read-your-writes point gets: key -> row
        self._own: dict[bytes, RowVersion] = {}
        self._own_tables: dict[bytes, YBTable] = {}
        self._state = "pending"
        # SAVEPOINT marks over the CLIENT-BUFFERED write set (ops flush
        # as intents only at commit, so rolling back to a savepoint is a
        # pure buffer truncation — reference: PG subtransaction aborts).
        self._savepoints: list[tuple[str, tuple]] = []
        self._flush_count = 0
        self._last_heartbeat = time.monotonic()
        # Max hybrid time observed from intent writes; propagated to the
        # coordinator at commit so commit_ht exceeds every intent write.
        self._max_write_ht = 0

    # -- write buffering (mirrors YBSession) ---------------------------------
    def insert(self, table: YBTable, values: dict,
               ttl_expire_ht: int = MAX_HT) -> None:
        key_values = {c.name: values[c.name]
                      for c in table.schema.key_columns}
        cols = {table.col_id[c.name]: values[c.name]
                for c in table.schema.value_columns if c.name in values}
        row = RowVersion(table.encode_key(key_values), ht=0, liveness=True,
                         columns=cols, expire_ht=ttl_expire_ht)
        self._buffer(table, table.hash_code(key_values), row)

    def update(self, table: YBTable, key_values: dict,
               set_values: dict) -> None:
        cols = {table.col_id[n]: v for n, v in set_values.items()}
        row = RowVersion(table.encode_key(key_values), ht=0, liveness=False,
                         columns=cols)
        self._buffer(table, table.hash_code(key_values), row)

    def delete_row(self, table: YBTable, key_values: dict) -> None:
        row = RowVersion(table.encode_key(key_values), ht=0, tombstone=True)
        self._buffer(table, table.hash_code(key_values), row)

    def _buffer(self, table: YBTable, hash_code: int, row: RowVersion) -> None:
        self._check_pending()
        self._ops.append((table, hash_code, row))
        prev = self._own.get(row.key)
        if prev is not None and not row.tombstone:
            merged_cols = dict(prev.columns)
            merged_cols.update(row.columns)
            row = RowVersion(row.key, ht=0,
                             liveness=row.liveness or prev.liveness,
                             columns=merged_cols, expire_ht=row.expire_ht)
        self._own[row.key] = row
        self._own_tables[row.key] = table

    # -- savepoints ----------------------------------------------------------
    def savepoint(self, name: str) -> None:
        self._check_pending()
        self._savepoints.append(
            (name, (len(self._ops), self._flush_count, dict(self._own),
                    dict(self._own_tables))))

    def rollback_to_savepoint(self, name: str) -> None:
        self._check_pending()
        for i in range(len(self._savepoints) - 1, -1, -1):
            if self._savepoints[i][0] == name:
                n_ops, fc, own, own_tables = self._savepoints[i][1]
                if fc != self._flush_count:
                    # Intents sent since the savepoint cannot be
                    # retracted (they live at the participants); refuse
                    # rather than silently committing them.
                    raise KeyError(
                        f"savepoint {name} predates a flush of intents")
                del self._ops[n_ops:]
                self._own = dict(own)
                self._own_tables = dict(own_tables)
                # the savepoint itself survives (PG semantics); later
                # ones are destroyed
                del self._savepoints[i + 1:]
                return
        raise KeyError(f"savepoint {name} does not exist")

    def release_savepoint(self, name: str) -> None:
        self._check_pending()
        for i in range(len(self._savepoints) - 1, -1, -1):
            if self._savepoints[i][0] == name:
                del self._savepoints[i:]
                return
        raise KeyError(f"savepoint {name} does not exist")

    # -- intents flush -------------------------------------------------------
    def flush(self, timeout_s: float = 15.0) -> int:
        """Send buffered rows as intents, one RPC per tablet."""
        self._check_pending()
        ops, self._ops = self._ops, []
        if ops:
            self._flush_count += 1
        by_tablet: dict[str, tuple[YBTable, object, list]] = {}
        for table, hash_code, row in ops:
            loc = self.client.meta_cache.lookup_by_hash(table.name,
                                                        hash_code)
            if loc.tablet_id not in by_tablet:
                by_tablet[loc.tablet_id] = (table, loc, [])
            by_tablet[loc.tablet_id][2].append(row)

        written = 0
        for table, loc, rows in by_tablet.values():
            try:
                resp = self.client.tablet_rpc(
                    table.name, loc, "ts.write_intents", {
                        "txn_id": self.txn_id,
                        "status_tablet": self.status_loc.tablet_id,
                        "priority": self.priority,
                        "read_ht": self.read_ht,
                        "rows": wire.encode_rows(rows),
                    }, timeout_s=timeout_s)
                self._max_write_ht = max(self._max_write_ht,
                                         resp.get("ht", 0))
            except TabletOpFailed as e:
                if getattr(e, "resp", {}).get("code") == "conflict":
                    self.abort()
                    raise TransactionConflict(str(e)) from e
                raise
            self._participants[loc.tablet_id] = loc.leader
            written += len(rows)
        return written

    # -- reads ---------------------------------------------------------------
    def get(self, table: YBTable, key_values: dict):
        """Point read at the txn snapshot with read-your-writes."""
        self._check_pending()
        key = table.encode_key(key_values)
        own = self._own.get(key)
        if own is not None:
            if own.tombstone:
                return None
            # Overlay own write onto the committed snapshot value.
            base = self._snapshot_get(table, key_values)
            merged = list(base) if base is not None else None
            names = [c.name for c in table.schema.columns]
            if merged is None:
                if not own.liveness:
                    return None  # update of a non-existent row
                merged = [key_values.get(n) for n in names]
            rev = {cid: n for n, cid in table.col_id.items()}
            for cid, v in own.columns.items():
                merged[names.index(rev[cid])] = v
            return tuple(merged)
        return self._snapshot_get(table, key_values)

    def _snapshot_get(self, table: YBTable, key_values: dict):
        from yugabyte_db_tpu.client.session import YBSession
        from yugabyte_db_tpu.models.encoding import prefix_successor
        from yugabyte_db_tpu.storage.scan_spec import ScanSpec

        key = table.encode_key(key_values)
        spec = ScanSpec(lower=key, upper=prefix_successor(key),
                        read_ht=self.read_ht, limit=1)
        res = YBSession(self.client).scan(table, spec)
        return res.rows[0] if res.rows else None

    def own_rows(self, table: YBTable) -> dict:
        """This txn's buffered/flushed writes to ``table``, merged per
        key (the _own overlay) — range-reading statements need to see
        earlier statements' effects."""
        return {k: row for k, row in self._own.items()
                if self._own_tables[k].name == table.name}

    def snapshot_spec(self, **kwargs):
        """A ScanSpec pinned to the txn read point (range reads see the
        snapshot; own uncommitted writes are NOT merged into range
        scans — the reference's docdb does that in IntentAwareIterator;
        here apps read-own-writes via get())."""
        from yugabyte_db_tpu.storage.scan_spec import ScanSpec

        kwargs.setdefault("read_ht", self.read_ht)
        return ScanSpec(**kwargs)

    # -- lifecycle -----------------------------------------------------------
    def _check_pending(self) -> None:
        if self._state != "pending":
            raise TransactionAborted(f"transaction is {self._state}")

    def commit(self, timeout_s: float = 15.0) -> int:
        """Flush remaining intents and commit. Returns the commit hybrid
        time (pass as read_ht to later reads that must observe this txn)."""
        self._check_pending()
        if self._ops:
            self.flush(timeout_s=timeout_s)
        participants = [[tid, hint]
                        for tid, hint in self._participants.items()]
        try:
            resp = self.client.tablet_rpc(
                TXN_STATUS_TABLE, self.status_loc, "ts.txn_commit", {
                    "txn_id": self.txn_id, "participants": participants,
                    "propagated_ht": self._max_write_ht,
                }, timeout_s=timeout_s)
        except TabletOpFailed as e:
            self._state = "aborted"
            self.manager._deregister(self.txn_id)
            raise TransactionAborted(str(e)) from e
        self._state = "committed"
        self.manager._deregister(self.txn_id)
        return resp["commit_ht"]

    def abort(self) -> None:
        if self._state != "pending":
            return
        self._state = "aborted"
        self.manager._deregister(self.txn_id)
        participants = [[tid, hint]
                        for tid, hint in self._participants.items()]
        try:
            self.client.tablet_rpc(
                TXN_STATUS_TABLE, self.status_loc, "ts.txn_abort", {
                    "txn_id": self.txn_id, "participants": participants,
                })
        except Exception as e:  # noqa: BLE001 — expiry will abort it anyway
            count_swallowed("txn.abort", e)
