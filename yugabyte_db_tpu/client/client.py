"""YBClient: the cluster entry point.

Reference analog: src/yb/client/client.cc — master RPCs with leader
failover, table handles, and the tablet-RPC retry engine
(TabletInvoker, tablet_rpc.cc): try the known leader, learn from
NOT_THE_LEADER hints, fall back to other replicas, refresh locations.
"""

from __future__ import annotations

import time

from yugabyte_db_tpu.client.meta_cache import MetaCache, TabletLocation
from yugabyte_db_tpu.consensus.transport import TransportError
from yugabyte_db_tpu.models.partition import compute_hash_code
from yugabyte_db_tpu.models.schema import ColumnSchema, Schema
from yugabyte_db_tpu.utils.metrics import count_swallowed
from yugabyte_db_tpu.utils.retry import RetryPolicy
from yugabyte_db_tpu.utils.status import TabletSplit


class MasterUnavailable(Exception):
    pass


# Response codes no retry can change: surface immediately.
TERMINAL_CODES = frozenset(
    {"invalid_read_time", "conflict", "aborted", "committed", "error",
     "duplicate_key"})


class TabletOpFailed(Exception):
    pass


class YBTable:
    """A table handle: schema + key helpers (reference: YBTable)."""

    def __init__(self, name: str, table_id: str, schema: Schema,
                 engine: str = "cpu"):
        self.name = name
        self.table_id = table_id
        self.schema = schema
        self.engine = engine
        self.col_id = {c.name: c.col_id for c in schema.columns}

    def hash_code(self, key_values: dict) -> int:
        hc = compute_hash_code(self.schema, key_values)
        return 0 if hc is None else hc

    def encode_key(self, key_values: dict) -> bytes:
        hc = compute_hash_code(self.schema, key_values)
        return self.schema.encode_primary_key(key_values, hc)


class YBClient:
    def next_request_id(self) -> int:
        """Monotonic per-client write request id (exactly-once dedup:
        retryable_requests.h:34 — retries reuse the SAME id)."""
        with self._req_lock:
            self._req_counter += 1
            return self._req_counter

    def __init__(self, transport, master_uuids: list[str],
                 default_rpc_timeout_s: float = 10.0, cloud_info=None):
        import threading
        import uuid as uuid_mod

        self.transport = transport
        self.master_uuids = list(master_uuids)
        self.default_rpc_timeout_s = default_rpc_timeout_s
        # The client's own locality labels: stale follower reads prefer
        # a replica in the same (cloud, region, zone) — the reference's
        # read-replica / closest-replica selection (TabletInvoker with
        # YBConsistencyLevel + CloudInfoPB proximity).
        self.cloud_info = cloud_info or {}
        self.meta_cache = MetaCache(self)
        self._master_leader_hint: str | None = None
        # Exactly-once write identity: every write carries
        # (client_id, request_id); servers dedup replayed ids.
        self.client_id = uuid_mod.uuid4().hex
        self._req_lock = threading.Lock()
        self._req_counter = 0
        # HLC propagation (the ConsistentReadPoint/session-causality
        # contract): the largest hybrid time this client has OBSERVED in
        # any response; piggybacked on tablet RPCs so every touched
        # server's clock ratchets past it — a read after a write (or
        # after a transaction commit) can never miss it.
        self.last_observed_ht = 0
        # One retry/deadline policy for every blocking loop in this
        # client (utils.retry): jittered exponential backoff between
        # failover sweeps, every attempt debiting the call's one
        # deadline. The reference's RpcRetrier/TabletInvoker shape.
        self.retry_policy = RetryPolicy(
            timeout_s=default_rpc_timeout_s,
            initial_backoff_s=0.05, max_backoff_s=0.5)

    @classmethod
    def connect(cls, master_addrs: str) -> "YBClient":
        """Bootstrap a client over TCP from comma-separated master
        host:port addresses (the driver connection string); tserver
        addresses are learned from the master registry (and refreshed
        whenever a lookup misses)."""
        from yugabyte_db_tpu.rpc import SocketTransport

        transport = SocketTransport()
        uuids = []
        for addr in master_addrs.split(","):
            addr = addr.strip()
            if not addr:
                continue
            host, port = addr.rsplit(":", 1)
            uuid = f"master@{addr}"
            transport.set_address(uuid, host, int(port))
            uuids.append(uuid)
        if not uuids:
            raise ValueError("no master addresses given")
        c = cls(transport, uuids)
        c.refresh_tserver_addresses()
        return c

    def refresh_tserver_addresses(self) -> None:
        """Learn tserver uuid -> address mappings (socket mode only)."""
        if not hasattr(self.transport, "set_address"):
            return
        for d in self.list_tservers():
            addr = d.get("addr")
            if isinstance(addr, (list, tuple)) and len(addr) == 2:
                self.transport.set_address(d["uuid"], addr[0],
                                           int(addr[1]))

    # -- master path ---------------------------------------------------------
    def master_rpc(self, method: str, payload: dict,
                   timeout_s: float | None = None) -> dict:
        """Call the master leader, following NOT_THE_LEADER hints and
        retrying through the master set until the RetryPolicy's deadline
        budget runs out (each failover sweep debits it; backoff between
        sweeps is jittered so clients don't re-converge in lockstep)."""
        last = None
        for attempt in self.retry_policy.attempts(timeout_s=timeout_s):
            targets = ([self._master_leader_hint]
                       if self._master_leader_hint else []) + \
                [u for u in self.master_uuids
                 if u != self._master_leader_hint]
            for target in targets:
                try:
                    resp = self.transport.send(target, method, payload,
                                               timeout=attempt.timeout(2.0))
                except (TransportError, TimeoutError) as e:
                    last = e
                    continue
                if resp.get("code") == "not_leader":
                    self._master_leader_hint = resp.get("leader_hint")
                    last = resp
                    continue
                self._master_leader_hint = target
                return resp
            attempt.note(last)
        raise MasterUnavailable(f"{method}: no master leader ({last})")

    # -- ddl ----------------------------------------------------------------
    def create_table(self, name: str, columns: list[ColumnSchema],
                     num_tablets: int = 4, replication_factor: int = 3,
                     engine: str = "cpu", timeout_s: float = 30.0) -> YBTable:
        schema = Schema(columns, table_id=name)
        resp = self.master_rpc("master.create_table", {
            "name": name, "schema": schema.to_dict(),
            "num_tablets": num_tablets,
            "replication_factor": replication_factor,
            "engine": engine,
        }, timeout_s=timeout_s)
        if resp.get("code") not in ("ok", "partial", "already_present"):
            raise RuntimeError(f"create_table {name}: {resp}")
        return self.open_table(name)

    def create_index(self, table: str, columns,
                     index_name: str | None = None, include=()) -> str:
        """Create a secondary index on one or more columns, optionally
        covering (INCLUDE) extra value columns; returns the index
        table's name."""
        if isinstance(columns, str):
            columns = [columns]
        resp = self.master_rpc("master.create_index", {
            "table": table, "columns": list(columns),
            "include": list(include), "index_name": index_name})
        if resp.get("code") not in ("ok", "already_present"):
            raise RuntimeError(
                f"create_index on {table}{tuple(columns)}: {resp}")
        return resp["index_table"]

    def alter_table(self, name: str, new_schema_dict: dict) -> None:
        """Push an evolved schema (version = current + 1) to the master,
        which replicates it to the catalog and every tablet leader."""
        resp = self.master_rpc("master.alter_table",
                               {"name": name, "schema": new_schema_dict})
        if resp.get("code") not in ("ok", "partial"):
            raise RuntimeError(f"alter_table {name}: {resp}")

    def delete_table(self, name: str) -> None:
        resp = self.master_rpc("master.delete_table", {"name": name})
        if resp.get("code") not in ("ok", "not_found"):
            raise RuntimeError(f"delete_table {name}: {resp}")
        self.meta_cache.invalidate(name)

    def open_table(self, name: str) -> YBTable:
        resp = self.master_rpc("master.get_table", {"name": name})
        if resp.get("code") != "ok":
            raise KeyError(f"table {name!r} not found")
        return YBTable(name, resp["table_id"],
                       Schema.from_dict(resp["schema"]),
                       resp.get("engine", "cpu"))

    def list_tables(self) -> list[dict]:
        return self.master_rpc("master.list_tables", {})["tables"]

    def list_tservers(self) -> list[dict]:
        return self.master_rpc("master.list_tservers", {})["tservers"]

    # -- tablet path (TabletInvoker) -----------------------------------------
    def tablet_rpc(self, table_name: str, loc: TabletLocation, method: str,
                   payload: dict, timeout_s: float | None = None,
                   prefer: str | None = None,
                   mark_leader: bool = True) -> dict:
        """Invoke a tablet RPC against its leader, with hint-following and
        replica fallback (reference: TabletInvoker::Execute). ``prefer``
        puts one replica first in the try order (stale same-zone reads);
        ``mark_leader=False`` suppresses leader learning for responses a
        follower may legitimately serve.

        Deadline propagation: every attempt debits ONE RetryPolicy
        budget, and the remaining budget rides in ``payload["timeout"]``
        so the server's read gate / engine batch give up before the
        client stops waiting (the clean "timed_out" reply reaches the
        caller instead of a transport error)."""
        payload = dict(payload, tablet_id=loc.tablet_id)
        payload.setdefault("propagated_ht", self.last_observed_ht)
        tried_refresh = False
        last = None
        for attempt in self.retry_policy.attempts(timeout_s=timeout_s):
            targets = ([loc.leader] if loc.leader else []) + \
                [r for r in loc.replicas if r != loc.leader]
            if prefer is not None and prefer in loc.replicas:
                targets = [prefer] + [t for t in targets if t != prefer]
            for target in targets:
                transport_timeout = attempt.timeout(5.0)
                # Server-side budget: stay below the transport timeout
                # so the server's own timed_out beats the socket's.
                payload["timeout"] = max(0.05,
                                         round(transport_timeout * 0.8, 3))
                try:
                    resp = self.transport.send(target, method, payload,
                                               timeout=transport_timeout)
                except (TransportError, TimeoutError) as e:
                    last = e
                    continue
                code = resp.get("code")
                if code == "not_leader":
                    hint = resp.get("leader_hint")
                    loc.leader = hint
                    self.meta_cache.mark_leader(table_name, loc.tablet_id,
                                                hint)
                    last = resp
                    continue
                if code == "not_found":
                    last = resp
                    continue  # replica being moved/created: try others
                if code == "tablet_split":
                    # The addressed tablet was split: invalidate exactly
                    # that cache entry (siblings keep their locations +
                    # leader hints) and hand re-planning to the caller —
                    # the key now maps to a child tablet the server
                    # can't name for us.
                    self.meta_cache.invalidate_tablet(
                        table_name, resp.get("tablet_id") or loc.tablet_id)
                    raise TabletSplit(resp.get("tablet_id")
                                      or loc.tablet_id)
                if code == "ok":
                    if mark_leader:
                        self.meta_cache.mark_leader(table_name,
                                                    loc.tablet_id, target)
                        loc.leader = target
                    seen = max(resp.get("ht") or 0,
                               resp.get("read_ht") or 0,
                               resp.get("commit_ht") or 0)
                    if seen > self.last_observed_ht:
                        self.last_observed_ht = seen
                    return resp
                if code in TERMINAL_CODES:
                    # Retrying cannot change these outcomes (conflicts,
                    # terminal txn states, rejected read points).
                    err = TabletOpFailed(
                        f"{method} on {loc.tablet_id}: {resp}")
                    err.resp = resp
                    raise err
                last = resp
            if not tried_refresh:
                # Replica set may have changed (re-replication): refresh
                # locations AND tserver addresses (socket mode: a
                # restarted tserver binds a new port).
                tried_refresh = True
                try:
                    self.refresh_tserver_addresses()
                except Exception as e:  # noqa: BLE001 — best effort
                    count_swallowed("client.refresh_tserver_addresses", e)
                locs = None
                try:
                    locs = self.meta_cache.locations(table_name, refresh=True)
                except Exception as e:  # noqa: BLE001
                    last = e
                if locs is not None:
                    found = False
                    for t in locs.tablets:
                        if t.tablet_id == loc.tablet_id:
                            loc = t
                            found = True
                            break
                    if not found and any(
                            t.contains(loc.partition_start)
                            for t in locs.tablets):
                        # The tablet vanished from the table's location
                        # list AND other tablets now own its range: a
                        # split committed while our cache named the
                        # (now-deleted) parent. Hand re-planning to the
                        # caller, same as the tablet_split wire code. A
                        # listing that does NOT cover the range is a
                        # transient partial view (master catching up) —
                        # keep retrying, don't misreport a split.
                        raise TabletSplit(loc.tablet_id)
            attempt.note(last)
        raise TabletOpFailed(
            f"{method} on {loc.tablet_id} failed before deadline: {last}")
