"""Server-side transaction plumbing: status resolution + apply push.

Reference analogs: the status-resolution clients inside
src/yb/tablet/transaction_participant.cc (StatusRequest to the txn's
status tablet) and the coordinator's poller that pushes apply/cleanup to
participants (transaction_coordinator.cc polling + UpdateTransaction
RPCs, src/yb/tserver/tserver_service.proto:59).
"""

from __future__ import annotations

import threading

from yugabyte_db_tpu.utils.metrics import count_swallowed
from yugabyte_db_tpu.utils.retry import Deadline


class TxnRpcRouter:
    """Leader-following RPC helper for per-tablet transaction RPCs.

    Routes by trying a hint first, following "not_leader" hints, and —
    when candidates run out — asking the master where the tablet lives
    (master.locate_tablet), so notifications survive leader moves and
    re-replication."""

    def __init__(self, transport, master_uuids: list[str]):
        self.transport = transport
        self.master_uuids = list(master_uuids)
        self._lock = threading.Lock()
        self._leader_cache: dict[str, str] = {}     # tablet_id -> uuid
        self._replica_cache: dict[str, list[str]] = {}

    # -- master lookups ------------------------------------------------------
    def _locate(self, tablet_id: str) -> None:
        targets = list(self.master_uuids)
        for target in targets:
            try:
                resp = self.transport.send(
                    target, "master.locate_tablet",
                    {"tablet_id": tablet_id}, timeout=2.0)
            except Exception as e:  # noqa: BLE001 — try next master
                count_swallowed("txn_router.locate", e)
                continue
            if resp.get("code") == "not_leader":
                hint = resp.get("leader_hint")
                if hint and hint not in targets:
                    targets.append(hint)
                continue
            if resp.get("code") != "ok":
                return
            with self._lock:
                if resp.get("leader"):
                    self._leader_cache[tablet_id] = resp["leader"]
                self._replica_cache[tablet_id] = list(resp["replicas"])
            return

    def tablet_rpc(self, tablet_id: str, method: str, payload: dict,
                   hint: str | None = None,
                   timeout: float = 2.0) -> dict | None:
        """Send a per-tablet RPC to its leader. Returns the ok response or
        None when no leader answered."""
        payload = dict(payload, tablet_id=tablet_id)
        # One propagated budget for the whole leader hunt: the hint, the
        # cached leader, every replica, and (once) a master re-locate all
        # debit it; per-send waits are capped at the remainder.
        deadline = Deadline.after(timeout * 3)
        seen = set()
        located = False
        with self._lock:
            cached = self._leader_cache.get(tablet_id)
            replicas = list(self._replica_cache.get(tablet_id, []))
        targets = []
        for t in (hint, cached, *replicas):
            if t and t not in targets:
                targets.append(t)
        while not deadline.expired():
            if not targets:
                if located:
                    return None
                located = True
                self._locate(tablet_id)
                with self._lock:
                    cached = self._leader_cache.get(tablet_id)
                    replicas = list(self._replica_cache.get(tablet_id, []))
                targets = [t for t in (cached, *replicas)
                           if t and t not in seen]
                if not targets:
                    return None
                continue
            target = targets.pop(0)
            if target in seen:
                continue
            seen.add(target)
            try:
                resp = self.transport.send(target, method, payload,
                                           timeout=deadline.timeout(timeout))
            except Exception as e:  # noqa: BLE001 — next candidate
                count_swallowed("txn_router.call", e)
                continue
            if resp.get("code") == "not_leader":
                nxt = resp.get("leader_hint")
                if nxt and nxt not in seen:
                    targets.insert(0, nxt)
                continue
            if resp.get("code") == "ok":
                with self._lock:
                    self._leader_cache[tablet_id] = target
                return resp
            return resp  # terminal non-ok (conflict, aborted, ...)
        return None


class TxnNotifier:
    """Coordinator-side background worker of one tserver: aborts expired
    transactions and pushes apply/remove notifications to participants
    until acknowledged. Runs against every status-tablet peer this server
    currently leads."""

    def __init__(self, server, router: TxnRpcRouter,
                 interval_s: float = 0.25):
        self.server = server
        self.router = router
        self.interval_s = interval_s
        self._running = False
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._running = True
        self._thread = threading.Thread(
            target=self._loop, name=f"txn-notify-{self.server.uuid}",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def trigger(self) -> None:
        self._wake.set()

    def _loop(self) -> None:
        while self._running:
            self._wake.wait(timeout=self.interval_s)
            self._wake.clear()
            if not self._running:
                return
            try:
                self._tick()
            except Exception as e:  # noqa: BLE001 — next tick retries
                count_swallowed("txn_service.tick", e)

    def _tick(self) -> None:
        for peer in self.server.tablet_manager.peers():
            coord = peer.tablet.coordinator
            if coord is None or not peer.raft.is_leader():
                continue
            for txn_id in coord.expired_txns():
                try:
                    peer.replicate_txn_op("txn_status", {
                        "action": "abort", "txn_id": txn_id,
                        "participants": [],
                    })
                except Exception as e:  # noqa: BLE001 — next tick retries
                    count_swallowed("txn_service.expire_abort", e)
            for txn_id, action, commit_ht, unacked in \
                    coord.pending_notifications():
                for tablet_id, hint in unacked:
                    method = ("ts.apply_txn" if action == "apply"
                              else "ts.remove_txn")
                    resp = self.router.tablet_rpc(
                        tablet_id, method,
                        {"txn_id": txn_id, "commit_ht": commit_ht},
                        hint=hint)
                    if resp is not None and resp.get("code") == "ok":
                        try:
                            peer.replicate_txn_op("txn_status", {
                                "action": "ack", "txn_id": txn_id,
                                "tablet_id": tablet_id,
                            })
                        except Exception as e:  # noqa: BLE001 — re-notified
                            count_swallowed("txn_service.ack", e)
            for txn_id in coord.gc_candidates():
                try:
                    peer.replicate_txn_op("txn_status", {
                        "action": "gc", "txn_id": txn_id})
                except Exception as e:  # noqa: BLE001 — next tick retries
                    count_swallowed("txn_service.gc", e)
