"""TabletServer: the data-node daemon and its RPC service.

Reference analog: src/yb/tserver/tablet_server.cc (the daemon) +
tablet_service.cc (TabletServiceImpl::Write at :718, ::Read at :1001 — the
leader checks, tablet lookup, and the NOT_THE_LEADER error protocol that
drives client failover) + the consensus service routing per-tablet RPCs.

Service responses carry {"code": "ok"| "not_leader" | "not_found" | ...};
NOT_LEADER responses include the best leader hint, which the client's
MetaCache uses to re-route (the reference's TabletInvoker contract).
"""

from __future__ import annotations

from yugabyte_db_tpu.consensus.raft import NotLeader, RaftOptions
from yugabyte_db_tpu.models.schema import Schema
from yugabyte_db_tpu.storage import wire
from yugabyte_db_tpu.tablet.tablet import TabletMetadata
from yugabyte_db_tpu.tserver.heartbeater import Heartbeater
from yugabyte_db_tpu.tserver.tablet_manager import (TabletNotFound,
                                                    TSTabletManager)


class TabletServer:
    def __init__(self, uuid: str, fs_root: str, transport,
                 master_uuids: list[str],
                 raft_opts: RaftOptions | None = None,
                 engine_options: dict | None = None,
                 fsync: bool = True,
                 heartbeat_interval_s: float = 0.5,
                 advertised_addr=None):
        self.uuid = uuid
        self.transport = transport
        self.advertised_addr = advertised_addr  # (host, port) when on TCP
        self.tablet_manager = TSTabletManager(
            uuid, fs_root, transport, raft_opts=raft_opts,
            engine_options=engine_options, fsync=fsync)
        self.heartbeater = Heartbeater(self, master_uuids,
                                       interval_s=heartbeat_interval_s)
        from yugabyte_db_tpu.tserver.mesh_scan import MeshScanService

        self.mesh_scan = MeshScanService()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        self.tablet_manager.open_existing()
        self.heartbeater.start()

    def shutdown(self) -> None:
        self.heartbeater.stop()
        self.tablet_manager.shutdown()

    def process_heartbeat_response(self, resp: dict) -> None:
        for tablet_id in resp.get("tablets_to_delete", []):
            try:
                self.tablet_manager.delete_tablet(tablet_id)
            except Exception:  # noqa: BLE001 — deletion retried next beat
                pass

    # -- rpc dispatch --------------------------------------------------------
    def handle(self, method: str, payload: dict):
        if method.startswith("raft."):
            try:
                peer = self.tablet_manager.get(payload["tablet_id"])
            except TabletNotFound:
                return {"code": "not_found", "term": 0, "granted": False,
                        "success": False, "last_index": 0}
            return peer.raft.handle(method, payload)
        handler = getattr(self, "_h_" + method.replace(".", "_"), None)
        if handler is None:
            raise ValueError(f"unknown method {method}")
        return handler(payload)

    # -- service handlers ----------------------------------------------------
    def _h_ts_create_tablet(self, p: dict):
        meta = TabletMetadata(
            p["tablet_id"], p["table_name"], Schema.from_dict(p["schema"]),
            p["partition_start"], p["partition_end"],
            p.get("engine", "cpu"))
        try:
            self.tablet_manager.create_tablet(meta, p["peers"])
        except Exception as e:  # includes TabletAlreadyExists (idempotent)
            if "TabletAlreadyExists" not in type(e).__name__:
                raise
        self.heartbeater.trigger()
        return {"code": "ok"}

    def _h_ts_delete_tablet(self, p: dict):
        self.tablet_manager.delete_tablet(p["tablet_id"])
        return {"code": "ok"}

    def _h_ts_write(self, p: dict):
        try:
            peer = self.tablet_manager.get(p["tablet_id"])
        except TabletNotFound:
            return {"code": "not_found"}
        rows = wire.decode_rows(p["rows"])
        try:
            ht = peer.write(rows, timeout=p.get("timeout", 10.0))
        except NotLeader as e:
            return {"code": "not_leader", "leader_hint": e.leader_hint}
        except TimeoutError:
            return {"code": "timed_out"}
        return {"code": "ok", "ht": ht.value}

    @staticmethod
    def _pin_read_point(peer, read_ht: int, timeout: float) -> dict | None:
        """Pin an explicit client read point on one tablet: advance the
        local clock past it so no later write lands at <= read_ht, then
        wait until every in-flight write below it resolves (reference:
        MvccManager::SafeTime wait in Tablet::DoHandleQLReadRequest).
        Returns an error response dict, or None on success."""
        from yugabyte_db_tpu.utils.hybrid_time import (
            BITS_FOR_LOGICAL, MAX_CLOCK_SKEW_US, HybridTime)
        # Never let a client-supplied read point ratchet the clock
        # beyond the skew bound — an arbitrary far-future read_ht would
        # poison every subsequent write HT on this tablet. (Logical
        # clocks in tests have no wall-clock skew semantics: no bound.)
        bound_fn = getattr(peer.tablet.clock, "max_global_now", None)
        if bound_fn is not None and read_ht > bound_fn().value + (
                MAX_CLOCK_SKEW_US << BITS_FOR_LOGICAL):
            return {"code": "invalid_read_time"}
        peer.tablet.clock.update(HybridTime(read_ht))
        # Default below the client's 5s per-attempt transport timeout
        # (client.py tablet_rpc) so the clean "timed_out" reply reaches
        # the caller instead of a transport error.
        if not peer.tablet.mvcc.wait_for_safe_time(
                HybridTime(read_ht), timeout=timeout):
            return {"code": "timed_out"}
        return None

    def _h_ts_scan(self, p: dict):
        try:
            peer = self.tablet_manager.get(p["tablet_id"])
        except TabletNotFound:
            return {"code": "not_found"}
        spec = wire.decode_spec(p["spec"])
        if spec.read_ht == wire.MAX_HT:
            spec.read_ht = peer.read_time().value
        else:
            err = self._pin_read_point(peer, spec.read_ht,
                                       p.get("timeout", 4.0))
            if err is not None:
                return err
        try:
            res = peer.scan(spec, allow_stale=p.get("allow_stale", False))
        except NotLeader as e:
            return {"code": "not_leader", "leader_hint": e.leader_hint}
        out = wire.encode_result(res)
        out["code"] = "ok"
        out["read_ht"] = spec.read_ht
        return out

    def _h_ts_multi_agg_scan(self, p: dict):
        """Aggregate over MANY tablets this server leads, as ONE device
        program over the mesh (tablets on the "t" axis, blocks on "b",
        psum/pmax combine over ICI — tserver.mesh_scan). The client falls
        back to per-tablet ts.scan + host combine on any non-ok reply."""
        peers = []
        for tid in p["tablet_ids"]:
            try:
                peer = self.tablet_manager.get(tid)
            except TabletNotFound:
                return {"code": "not_found", "tablet_id": tid}
            if not (peer.raft.is_leader() and peer.raft.has_lease()):
                return {"code": "not_leader", "tablet_id": tid,
                        "leader_hint": peer.raft.leader_uuid()}
            peers.append(peer)
        spec = wire.decode_spec(p["spec"])
        if spec.read_ht == wire.MAX_HT:
            # Every tablet can already serve its own safe time; the min is
            # serveable by all without waiting and repeatable everywhere.
            spec.read_ht = min(pr.read_time().value for pr in peers)
        else:
            # One deadline across ALL pins: serial per-peer waits must not
            # sum past the client's single transport timeout.
            import time as _time

            deadline = _time.monotonic() + p.get("timeout", 4.0)
            for peer in peers:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return {"code": "timed_out"}
                err = self._pin_read_point(peer, spec.read_ht, remaining)
                if err is not None:
                    return err
        res = self.mesh_scan.aggregate(peers, spec)
        if res is None:
            return {"code": "ineligible"}
        out = wire.encode_result(res)
        out["code"] = "ok"
        out["read_ht"] = spec.read_ht
        return out

    def _h_ts_flush(self, p: dict):
        self.tablet_manager.get(p["tablet_id"]).flush()
        return {"code": "ok"}

    def _h_ts_compact(self, p: dict):
        self.tablet_manager.get(p["tablet_id"]).compact(
            p.get("history_cutoff_ht", 0))
        return {"code": "ok"}

    def _h_ts_change_config(self, p: dict):
        peer = self.tablet_manager.get(p["tablet_id"])
        try:
            peer.raft.change_config(p["peers"])
        except NotLeader as e:
            return {"code": "not_leader", "leader_hint": e.leader_hint}
        return {"code": "ok"}

    def _h_ts_transfer_leadership(self, p: dict):
        peer = self.tablet_manager.get(p["tablet_id"])
        peer.raft.transfer_leadership(p["target"])
        return {"code": "ok"}

    def _h_ts_status(self, p: dict):
        return {
            "code": "ok",
            "uuid": self.uuid,
            "tablets": {pr.tablet_id: pr.stats()
                        for pr in self.tablet_manager.peers()},
        }
