"""TabletServer: the data-node daemon and its RPC service.

Reference analog: src/yb/tserver/tablet_server.cc (the daemon) +
tablet_service.cc (TabletServiceImpl::Write at :718, ::Read at :1001 — the
leader checks, tablet lookup, and the NOT_THE_LEADER error protocol that
drives client failover) + the consensus service routing per-tablet RPCs.

Service responses carry {"code": "ok"| "not_leader" | "not_found" | ...};
NOT_LEADER responses include the best leader hint, which the client's
MetaCache uses to re-route (the reference's TabletInvoker contract).
"""

from __future__ import annotations

from yugabyte_db_tpu.consensus.raft import NotLeader, RaftOptions
from yugabyte_db_tpu.consensus.transport import send_with_retry
from yugabyte_db_tpu.models.schema import Schema
from yugabyte_db_tpu.storage import wire
from yugabyte_db_tpu.storage.scan_spec import ScanSpec
from yugabyte_db_tpu.tablet.tablet import TabletMetadata
from yugabyte_db_tpu.tserver.heartbeater import Heartbeater
from yugabyte_db_tpu.tserver.tablet_manager import (TabletNotFound,
                                                    TSTabletManager)
from yugabyte_db_tpu.utils.metrics import count_swallowed
from yugabyte_db_tpu.utils.retry import Deadline, DeadlineExpired
from yugabyte_db_tpu.utils.status import TabletSplit
from yugabyte_db_tpu.utils.trace import TRACE, RpczStore, trace_request


class TabletServer:
    def __init__(self, uuid: str, fs_root: str, transport,
                 master_uuids: list[str],
                 raft_opts: RaftOptions | None = None,
                 engine_options: dict | None = None,
                 fsync: bool = True,
                 heartbeat_interval_s: float = 0.5,
                 advertised_addr=None, options=None, cloud_info=None):
        # Structured options (server.options.TabletServerOptions) override
        # the loose kwargs when provided (reference:
        # TabletServerOptions over gflags, server_base_options.h).
        if options is not None:
            fsync = options.fsync
            heartbeat_interval_s = options.heartbeat_interval_s
            engine_options = options.engine_options or engine_options
            cloud_info = getattr(options, "cloud_info", None) or cloud_info
        self.options = options
        self.uuid = uuid
        self.transport = transport
        self.advertised_addr = advertised_addr  # (host, port) when on TCP
        self.cloud_info = cloud_info or {}  # zone-aware placement labels
        # Data-dir identity: formats on first open, refuses a directory
        # owned by another server (reference: FsManager::Open,
        # src/yb/fs/fs_manager.cc).
        from yugabyte_db_tpu import fs as _fs

        self.instance = _fs.format_or_open(fs_root, uuid)
        self.tablet_manager = TSTabletManager(
            uuid, fs_root, transport, raft_opts=raft_opts,
            engine_options=engine_options, fsync=fsync)
        self.heartbeater = Heartbeater(self, master_uuids,
                                       interval_s=heartbeat_interval_s)
        from yugabyte_db_tpu.tserver.mesh_scan import MeshScanService
        from yugabyte_db_tpu.tserver.txn_service import (TxnNotifier,
                                                         TxnRpcRouter)

        import threading as _threading

        self.mesh_scan = MeshScanService()
        self.txn_router = TxnRpcRouter(transport, master_uuids)
        self.txn_notifier = TxnNotifier(self, self.txn_router)
        self._rb_lock = _threading.Lock()
        self._rpc_lock = _threading.Lock()
        self._rb_in_flight: set[str] = set()
        # Observability: per-RPC counters/latency + per-tablet gauges
        # (reference: the protoc-gen-yrpc per-RPC metrics and
        # tablet_metrics.cc), scraped via the embedded webserver.
        from yugabyte_db_tpu.utils.metrics import MetricRegistry

        self.metrics = MetricRegistry()
        self._rpc_entities: dict = {}
        self._tablet_entities: dict = {}
        self._collect_lock = _threading.Lock()
        self.metrics.add_collector(self._collect_tablet_metrics)
        self.webserver = None
        self.rpcz = RpczStore()

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        self.tablet_manager.bootstrap_notifier = \
            self._request_remote_bootstrap
        self.tablet_manager.open_existing()
        self.heartbeater.start()
        self.txn_notifier.start()
        if self.options is not None and self.options.webserver:
            self.start_webserver(self.options.webserver_host,
                                 self.options.webserver_port)

    def shutdown(self) -> None:
        if self.webserver is not None:
            self.webserver.stop()
        self.txn_notifier.stop()
        self.heartbeater.stop()
        self.tablet_manager.shutdown()

    def process_heartbeat_response(self, resp: dict) -> None:
        for tablet_id in resp.get("tablets_to_delete", []):
            try:
                self.tablet_manager.delete_tablet(tablet_id)
            except Exception as e:  # noqa: BLE001 — retried next beat
                count_swallowed("tserver.delete_tablet", e)

    def start_webserver(self, host: str = "127.0.0.1", port: int = 0):
        """Expose /metrics, /varz, /healthz, /tablets over HTTP
        (reference: RpcAndWebServerBase, tserver-path-handlers.cc)."""
        from yugabyte_db_tpu.server.webserver import Webserver

        self.webserver = Webserver(self.metrics, f"tserver-{self.uuid}")

        def _tablet_rows():
            # the ONE row builder: JSON API and HTML dashboard agree
            return [
                {"tablet_id": p.tablet_id,
                 "table": p.tablet.meta.table_name,
                 "role": "leader" if p.is_leader() else "follower",
                 "schema_version": p.tablet.meta.schema.version,
                 **{k: v for k, v in p.stats().items()
                    if not isinstance(v, dict)}}
                for p in self.tablet_manager.peers()]

        self.webserver.add_json_handler("/tablets", _tablet_rows)
        self.webserver.add_json_handler("/rpcz", self.rpcz.dump)
        self.webserver.add_dashboard("/dashboards/tablets", "Tablets",
                                     _tablet_rows)

        def _hbm_device_rows():
            # Per-device residency: /memz's hbm_cache.by_device as a
            # table, one row per mesh device (the labeled-gauge twin).
            from yugabyte_db_tpu.storage.residency import hbm_cache

            stats = hbm_cache().stats()
            return [
                {"device": dev,
                 "resident_bytes": d["resident_bytes"],
                 "budget_bytes": d["budget_bytes"],
                 "pinned_bytes": d["pinned_bytes"],
                 "entries": d["entries"],
                 "utilization": (round(d["resident_bytes"]
                                       / d["budget_bytes"], 3)
                                 if d["budget_bytes"] else None)}
                for dev, d in sorted(stats["by_device"].items())]

        self.webserver.add_json_handler("/hbm-devices", _hbm_device_rows)
        self.webserver.add_dashboard("/dashboards/hbm-devices",
                                     "HBM devices", _hbm_device_rows)
        return self.webserver.start(host, port)

    def _rpc_entity(self, method: str):
        ent = self._rpc_entities.get(method)
        if ent is None:
            with self._rpc_lock:
                ent = self._rpc_entities.get(method)
                if ent is None:
                    ent = self.metrics.entity(daemon="tserver",
                                              uuid=self.uuid,
                                              method=method)
                    self._rpc_entities[method] = ent
        return ent

    def _collect_tablet_metrics(self) -> None:
        """Pre-scrape sync of per-tablet gauge entities with live peers.
        Serialized (concurrent scrapes would race entity registration)
        and snapshot-style: each tablet's stats dicts are built ONCE and
        the plain values stored, instead of callback fan-out re-taking
        the consensus lock per gauge."""
        with self._collect_lock:
            live = {p.tablet_id: p for p in self.tablet_manager.peers()}
            for tid in list(self._tablet_entities):
                if tid not in live:
                    self.metrics.remove_entity(
                        self._tablet_entities.pop(tid))
            for tid, peer in live.items():
                ent = self._tablet_entities.get(tid)
                if ent is None:
                    ent = self.metrics.entity(
                        daemon="tserver", uuid=self.uuid, tablet_id=tid)
                    self._tablet_entities[tid] = ent
                rs = peer.raft.stats()
                es = peer.tablet.engine.stats()
                ent.gauge("tablet_is_leader").set(
                    int(rs["role"] == "LEADER"))
                ent.gauge("tablet_last_index").set(rs["last_index"])
                ent.gauge("tablet_commit_index").set(rs["commit_index"])
                # Pipelined-apply backlog: entries acked at commit but
                # not yet applied into the engine. Nonzero transiently;
                # stuck-nonzero means the apply stage stalled.
                ent.gauge("yb_apply_lag_ops").set(
                    max(0, rs["commit_index"] - rs["applied_index"]))
                ent.gauge("tablet_run_versions").set(
                    es.get("run_versions", 0))
                ent.gauge("tablet_memtable_versions").set(
                    es.get("memtable_versions", 0))
                ent.gauge("tablet_num_runs").set(es.get("num_runs", 0))
                ent.gauge("tablet_intent_txns").set(
                    peer.tablet.participant.stats()["txns_with_intents"])

    # -- rpc dispatch --------------------------------------------------------
    def handle(self, method: str, payload: dict):
        import time as _time

        start = _time.monotonic()
        with trace_request(method) as t:
            try:
                return self._dispatch(method, payload)
            except TabletSplit as e:
                # The addressed tablet is sealed for (or replaced by) a
                # split: tell the client to invalidate exactly this
                # location entry and re-plan (tserver_error.h
                # TABLET_SPLIT). Raised by the admission seal gate, so
                # every write path funnels here.
                return {"code": "tablet_split", "tablet_id": e.tablet_id}
            finally:
                ent = self._rpc_entity(method)
                ent.counter("rpc_requests_total").increment()
                ent.histogram("rpc_latency_us").observe_duration_us(start)
                t.finish()  # duration must be final before sampling
                self.rpcz.record(t)

    def _dispatch(self, method: str, payload: dict):
        if method.startswith("raft."):
            try:
                peer = self.tablet_manager.get(payload["tablet_id"])
            except TabletNotFound:
                return {"code": "not_found", "term": 0, "granted": False,
                        "success": False, "last_index": 0}
            return peer.raft.handle(method, payload)
        handler = getattr(self, "_h_" + method.replace(".", "_"), None)
        if handler is None:
            raise ValueError(f"unknown method {method}")
        return handler(payload)

    # -- service handlers ----------------------------------------------------
    def _h_ts_create_tablet(self, p: dict):
        meta = TabletMetadata(
            p["tablet_id"], p["table_name"], Schema.from_dict(p["schema"]),
            p["partition_start"], p["partition_end"],
            p.get("engine", "cpu"), indexes=p.get("indexes") or [])
        try:
            self.tablet_manager.create_tablet(meta, p["peers"])
        except Exception as e:  # includes TabletAlreadyExists (idempotent)
            if "TabletAlreadyExists" not in type(e).__name__:
                raise
        self.heartbeater.trigger()
        return {"code": "ok"}

    def _h_ts_delete_tablet(self, p: dict):
        self.tablet_manager.delete_tablet(p["tablet_id"])
        return {"code": "ok"}

    # -- tablet splitting -----------------------------------------------------
    def _h_ts_get_split_key(self, p: dict):
        """Split phase 1: the master asks the parent leader for its
        split point — the median resident key hash (reference:
        TabletServiceAdminImpl::GetSplitKey). Refused when the tablet
        has no interior point (fewer than two distinct hashes, or the
        median collides with a partition bound)."""
        try:
            peer = self.tablet_manager.get(p["tablet_id"])
        except TabletNotFound:
            return {"code": "not_found"}
        if not (peer.raft.is_leader() and peer.raft.leader_ready()):
            return {"code": "not_leader",
                    "leader_hint": peer.raft.leader_uuid()}
        h = peer.split_key_hash()
        lo = peer.tablet.meta.partition_start
        hi = peer.tablet.meta.partition_end
        if h is None or not (lo < h < hi):
            return {"code": "error",
                    "message": "tablet has no interior split point"}
        return {"code": "ok", "split_hash": h}

    def _h_ts_split_seal(self, p: dict):
        """Split phase 4: stop admitting writes on the parent by
        replicating a split_seal entry through its own Raft log — every
        admitted write sits below the seal, so seal-applied implies all
        prior writes applied on this replica."""
        try:
            peer = self.tablet_manager.get(p["tablet_id"])
        except TabletNotFound:
            return {"code": "not_found"}
        try:
            peer.split_seal(timeout=float(p.get("timeout", 10.0)))
        except NotLeader as e:
            return {"code": "not_leader", "leader_hint": e.leader_hint}
        except TimeoutError:
            return {"code": "timed_out"}
        return {"code": "ok"}

    def _h_ts_split_fork(self, p: dict):
        """Split phase 5a: ship the sealed parent's frozen rows clamped
        to one child's hash range [lower, upper)."""
        try:
            peer = self.tablet_manager.get(p["tablet_id"])
        except TabletNotFound:
            return {"code": "not_found"}
        if not (peer.raft.is_leader() and peer.raft.leader_ready()):
            return {"code": "not_leader",
                    "leader_hint": peer.raft.leader_uuid()}
        try:
            entries = peer.split_fork_rows(p["lower"], p["upper"])
        except RuntimeError as e:
            return {"code": "error", "message": str(e)}
        return {"code": "ok",
                "rows": [[key, wire.encode_rows(vers)]
                         for key, vers in entries]}

    def _h_ts_split_seed(self, p: dict):
        """Split phase 5b: replicate the forked rows through the CHILD
        leader's Raft log as ordinary write entries carrying the
        original row hybrid times — every child replica converges on
        byte-identical state (per-replica local forking would
        diverge)."""
        try:
            peer = self.tablet_manager.get(p["tablet_id"])
        except TabletNotFound:
            return {"code": "not_found"}
        rows = [v for _key, vers in p["rows"]
                for v in wire.decode_rows(vers)]
        try:
            n = peer.split_seed(rows,
                                timeout=float(p.get("timeout", 30.0)))
        except NotLeader as e:
            return {"code": "not_leader", "leader_hint": e.leader_hint}
        except TimeoutError:
            return {"code": "timed_out"}
        return {"code": "ok", "seeded": n}

    # -- remote bootstrap -----------------------------------------------------
    def _request_remote_bootstrap(self, tablet_id: str,
                                  peer_uuid: str) -> None:
        """Leader side: tell a lagging peer to re-seed itself from us
        (reference: the StartRemoteBootstrap RPC the leader's consensus
        queue fires, consensus_queue.cc -> remote_bootstrap_service.cc)."""
        try:
            resp = send_with_retry(self.transport, peer_uuid,
                                   "ts.start_remote_bootstrap",
                                   {"tablet_id": tablet_id,
                                    "source": self.uuid}, timeout_s=5.0)
            if resp.get("code") != "ok":
                count_swallowed("tserver.remote_bootstrap", resp.get("code"))
        except Exception as e:  # noqa: BLE001 — retried by the next trigger
            count_swallowed("tserver.remote_bootstrap", e)

    def _h_ts_start_remote_bootstrap(self, p: dict):
        import threading as _threading

        tid = p["tablet_id"]
        with self._rb_lock:
            if tid in self._rb_in_flight:
                return {"code": "ok", "detail": "already running"}
            self._rb_in_flight.add(tid)

        def run():
            try:
                resp = self.transport.send(
                    p["source"], "ts.rb_snapshot", {"tablet_id": tid},
                    timeout=60.0)
                if resp.get("code") == "ok":
                    self.tablet_manager.install_snapshot(tid,
                                                         resp["payload"])
            except Exception:  # noqa: BLE001 — leader re-triggers
                import logging

                logging.getLogger(__name__).exception(
                    "remote bootstrap of %s from %s failed", tid,
                    p["source"])
            finally:
                with self._rb_lock:
                    self._rb_in_flight.discard(tid)

        _threading.Thread(target=run, daemon=True,
                          name=f"rb-{tid[:12]}").start()
        return {"code": "ok"}

    def _h_ts_rb_snapshot(self, p: dict):
        """Source side of a remote-bootstrap session: flush (so the runs
        capture everything and the log tail is short), then ship runs +
        sidecars + log tail + consensus metadata
        (remote_bootstrap_session.cc)."""
        try:
            peer = self.tablet_manager.get(p["tablet_id"])
        except TabletNotFound:
            return {"code": "not_found"}
        if not (peer.raft.is_leader() and peer.raft.leader_ready()):
            return {"code": "not_leader",
                    "leader_hint": peer.raft.leader_uuid()}
        snap = peer.snapshot_for_bootstrap()
        t = peer.tablet
        payload = {
            "table_name": t.meta.table_name,
            "schema": t.meta.schema.to_dict(),
            "partition_start": t.meta.partition_start,
            "partition_end": t.meta.partition_end,
            "engine": t.meta.engine,
            "flushed_op_index": snap["flushed_op_index"],
            "indexes": t.meta.indexes,
            "runs": [[key, wire.encode_rows(vers)]
                     for key, vers in snap["entries"]],
            "intents": t.participant.dump(),
            "retryable": t.retryable.dump(),
            "txn_state": (t.coordinator.dump()
                          if t.coordinator is not None else None),
            "snapshots": {
                sid: {"entries": [[k, wire.encode_rows(vers)]
                                  for k, vers in blob["entries"]],
                      "meta": blob["meta"]}
                for sid, blob in t.dump_snapshots().items()},
        }
        payload.update(snap["tail"])
        return {"code": "ok", "payload": payload}

    def _h_ts_snapshot_op(self, p: dict):
        """Replicated tablet snapshot ops (reference: backup.proto
        TabletSnapshotOp CREATE/RESTORE/DELETE). Each replica captures /
        restores its own local snapshot at the same log position."""
        op = p.get("op")
        if op not in ("create_snapshot", "restore_snapshot",
                      "delete_snapshot"):
            return {"code": "error", "message": f"bad snapshot op {op!r}"}
        sid = p.get("snapshot_id") or ""
        if not sid or "/" in sid or "\\" in sid or sid.startswith(".") \
                or sid.endswith(".tmp"):
            # validated BEFORE replicating: a bad id raising inside the
            # apply stage would wedge every replica's apply thread
            return {"code": "error",
                    "message": f"bad snapshot id {sid!r}"}
        try:
            peer = self.tablet_manager.get(p["tablet_id"])
        except TabletNotFound:
            return {"code": "not_found"}
        if not peer.raft.is_leader():
            return {"code": "not_leader",
                    "leader_hint": peer.raft.leader_uuid()}
        if op == "restore_snapshot" and \
                p["snapshot_id"] not in peer.tablet.list_snapshots():
            # validated BEFORE replicating: the apply stage must never
            # fail (an apply exception would wedge the tablet)
            return {"code": "error",
                    "message": f"snapshot {p['snapshot_id']} not found"}
        try:
            peer.replicate_txn_op(op, {"snapshot_id": p["snapshot_id"]})
        except NotLeader as e:
            return {"code": "not_leader", "leader_hint": e.leader_hint}
        except TimeoutError:
            return {"code": "timed_out"}
        except Exception as e:  # noqa: BLE001 (e.g. snapshot not found)
            return {"code": "error", "message": str(e)}
        return {"code": "ok"}

    def _h_ts_list_snapshots(self, p: dict):
        try:
            peer = self.tablet_manager.get(p["tablet_id"])
        except TabletNotFound:
            return {"code": "not_found"}
        # Leader-gated: a lagging follower hasn't applied the latest
        # snapshot ops and would list a stale set.
        if not peer.raft.is_leader():
            return {"code": "not_leader",
                    "leader_hint": peer.raft.leader_uuid()}
        return {"code": "ok",
                "snapshots": peer.tablet.list_snapshots()}

    def _h_ts_alter_schema(self, p: dict):
        """Adopt a new table schema on one tablet: the LEADER replicates
        it through the tablet's Raft log so every replica switches at the
        same log position (reference: the AlterSchema tablet op the
        master's async AlterTable task invokes)."""
        from yugabyte_db_tpu.models.schema import Schema

        try:
            peer = self.tablet_manager.get(p["tablet_id"])
        except TabletNotFound:
            return {"code": "not_found"}
        new_schema = Schema.from_dict(p["schema"])
        if new_schema.version <= peer.tablet.meta.schema.version:
            return {"code": "ok"}  # already adopted (idempotent retry)
        if not peer.raft.is_leader():
            return {"code": "not_leader",
                    "leader_hint": peer.raft.leader_uuid()}
        try:
            peer.alter_schema(new_schema)
        except NotLeader as e:
            return {"code": "not_leader", "leader_hint": e.leader_hint}
        except TimeoutError:
            return {"code": "timed_out"}
        return {"code": "ok"}

    def _h_ts_set_indexes(self, p: dict):
        """Install the base table's current index set on one tablet (the
        master pushes this after CREATE INDEX)."""
        try:
            peer = self.tablet_manager.get(p["tablet_id"])
        except TabletNotFound:
            return {"code": "not_found"}
        peer.tablet.meta.indexes = list(p["indexes"])
        peer.tablet.meta.save(peer.tablet.meta_path)
        return {"code": "ok"}

    def _maintain_indexes(self, peer, rows,
                          insert_only: bool = False) -> dict | None:
        """Leader-side secondary-index maintenance for a base write
        (reference: Tablet::UpdateQLIndexes, tablet.cc:1015). Index
        entries are written FIRST: on a mid-flight failure the index may
        temporarily hold extra entries (lookups verify against the base
        row) but never misses one. Returns an error dict or None.

        ``insert_only`` (conditional INSERTs): the row must not exist,
        so maintenance treats the old state as absent — no tombstones
        are emitted. A later duplicate_key rejection then leaves at most
        a stale (base-verified-away) extra entry, never a removed one."""
        from yugabyte_db_tpu.index import index_mutations, normalize_index
        from yugabyte_db_tpu.models.encoding import decode_doc_key

        schema = peer.tablet.meta.schema
        key_names = [c.name for c in schema.key_columns]
        indexed_cids = set()
        for i in peer.tablet.meta.indexes:
            ni = normalize_index(i)
            for cname in ni["columns"] + ni["include"]:
                indexed_cids.add(schema.column(cname).col_id)
        for row in rows:
            # Writes that can't change any indexed value skip the old-row
            # read entirely (the hot non-indexed-update path).
            if not row.tombstone and not (indexed_cids & row.columns.keys()):
                continue
            _, hashed, ranges = decode_doc_key(row.key)
            base_kv = dict(zip(key_names, hashed + ranges))
            old = None if insert_only else \
                peer.tablet.current_row_values(row.key)
            for itable, _ischema, hc, rv in index_mutations(
                    schema, peer.tablet.meta.indexes, base_kv, old, row):
                loc = self._locate_by_hash(itable, hc)
                if loc is None:
                    return {"code": "error",
                            "message": f"cannot locate index {itable}"}
                resp = self.txn_router.tablet_rpc(
                    loc["tablet_id"], "ts.write",
                    {"rows": wire.encode_rows([rv])},
                    hint=loc.get("leader"))
                if resp is None or resp.get("code") != "ok":
                    return {"code": "error",
                            "message": f"index write failed: {resp}"}
        return None

    def _locate_by_hash(self, table_name: str, hash_code: int) -> dict | None:
        """Tablet of ``table_name`` owning ``hash_code`` (master lookup,
        briefly cached)."""
        import time as _time

        cached = getattr(self, "_tbl_loc_cache", None)
        if cached is None:
            cached = self._tbl_loc_cache = {}
        ent = cached.get(table_name)
        if ent is None or _time.monotonic() - ent[1] > 5.0:
            resp = None
            targets = list(self.heartbeater.master_uuids)
            for target in targets:
                try:
                    resp = self.transport.send(
                        target, "master.get_table_locations",
                        {"name": table_name}, timeout=2.0)
                except Exception as e:  # noqa: BLE001 — try next master
                    count_swallowed("tserver.get_table_locations", e)
                    continue
                if resp.get("code") == "not_leader":
                    hint = resp.get("leader_hint")
                    if hint and hint not in targets:
                        targets.append(hint)
                    continue
                break
            if resp is None or resp.get("code") != "ok":
                return None
            ent = (resp["tablets"], _time.monotonic())
            cached[table_name] = ent
        for t in ent[0]:
            if t["partition_start"] <= hash_code < t["partition_end"]:
                return t
        return ent[0][-1] if ent[0] else None

    def _h_ts_write(self, p: dict):
        try:
            peer = self.tablet_manager.get(p["tablet_id"])
        except TabletNotFound:
            return {"code": "not_found"}
        # ONE deadline for the whole write RPC: admission backpressure,
        # the commit wait, and any retry rounds debit the same budget.
        deadline = Deadline.after(float(p.get("timeout", 10.0)))
        peer.ops_seen += 1  # split-manager load signal
        if p.get("propagated_ht"):
            from yugabyte_db_tpu.utils.hybrid_time import HybridTime as _HT

            peer.tablet.clock.update(_HT(p["propagated_ht"]))
        payload = p["rows"]
        if isinstance(payload, (bytes, bytearray)):
            # Native write plane: the batch is an encoded row block
            # (storage.rowblock) — admit it without materializing rows.
            # The session only packs plain blind writes into blocks, so
            # the slow machinery (conditionals, counters) can't be
            # needed; tablets with secondary indexes or any pending
            # transaction intents drop to the row path below (the
            # intent lock spans the emptiness check + admission, so an
            # intent admitted concurrently can't be missed).
            from yugabyte_db_tpu.storage import rowblock

            fast = (not peer.tablet.meta.indexes
                    and not p.get("if_not_exists"))
            admitted = None
            if fast:
                with peer._intent_lock:
                    if not peer.tablet.participant.txns:
                        try:
                            admitted = peer.write_admit_block(
                                payload, client_id=p.get("client_id"),
                                request_id=p.get("request_id"))
                        except NotLeader as e:
                            return {"code": "not_leader",
                                    "leader_hint": e.leader_hint}
            if admitted is not None:
                try:
                    ht = peer.write_finish(admitted, timeout=deadline)
                except NotLeader as e:
                    return {"code": "not_leader",
                            "leader_hint": e.leader_hint}
                except TimeoutError:
                    return {"code": "timed_out"}
                return self._write_ok(ht)
            rows = rowblock.rows_from_block(payload)
        else:
            rows = wire.decode_rows(payload)
        # Non-transactional writes still resolve against pending intents:
        # they act as a highest-priority writer and wound any pending txn
        # holding intents on these keys (reference: single-row operations
        # go through the same conflict resolution). The check and the
        # write happen under the intent-admission lock, so an intent write
        # cannot slip between them (and vice versa: an admitted intent's
        # conflict check sees this write applied).
        if peer.tablet.meta.indexes and peer.raft.is_leader():
            err = self._maintain_indexes(
                peer, rows, insert_only=bool(p.get("if_not_exists")))
            if err is not None:
                return err
        keys = [r.key for r in rows]
        needs_full_lock = bool(p.get("if_not_exists")) or \
            any(r.increments for r in rows)
        for _attempt in range(3):
            admitted = None
            with peer._intent_lock:
                conflicting = peer.tablet.participant.pending_on_keys(keys)
                if not conflicting:
                    if needs_full_lock:
                        # Read-modify admission (conditional insert /
                        # counter resolve): the lock must span the check
                        # AND the append+wait so a concurrent duplicate /
                        # increment observes the first one applied.
                        if p.get("if_not_exists"):
                            if peer.raft.is_leader() and any(
                                    peer.tablet.current_row_values(k)
                                    is not None for k in keys):
                                return {"code": "duplicate_key"}
                        if any(r.increments for r in rows):
                            if not peer.raft.is_leader():
                                return {"code": "not_leader", "leader_hint":
                                        peer.raft.leader_uuid()}
                            try:
                                rows = [peer.tablet.resolve_increments(r)
                                        for r in rows]
                            except ValueError as e:
                                return {"code": "error", "message": str(e)}
                        try:
                            ht = peer.write(
                                rows, timeout=deadline,
                                client_id=p.get("client_id"),
                                request_id=p.get("request_id"))
                        except NotLeader as e:
                            return {"code": "not_leader",
                                    "leader_hint": e.leader_hint}
                        except TimeoutError:
                            return {"code": "timed_out"}
                        return self._write_ok(ht)
                    # Blind-write fast path: admission (dedup + stamp +
                    # append) under the lock, the majority wait OUTSIDE
                    # it — concurrent writers pipeline through one
                    # replication round instead of serializing on full
                    # commit latency (reference: preparer.cc batching).
                    try:
                        admitted = peer.write_admit(
                            rows, client_id=p.get("client_id"),
                            request_id=p.get("request_id"))
                    except NotLeader as e:
                        return {"code": "not_leader",
                                "leader_hint": e.leader_hint}
            if admitted is not None:
                try:
                    ht = peer.write_finish(admitted, timeout=deadline)
                except NotLeader as e:
                    return {"code": "not_leader",
                            "leader_hint": e.leader_hint}
                except TimeoutError:
                    return {"code": "timed_out"}
                return self._write_ok(ht)
            err = self._resolve_write_conflicts(
                peer, {"priority": 1 << 62}, conflicting)
            if err is not None:
                return err
        return {"code": "conflict", "message": "intents kept reappearing"}

    def _h_ts_write_admit(self, p: dict):
        """Admission half of the two-phase write: append + start
        replication, return WITHOUT waiting for commit. The client
        pipelines admissions across all its tablets from one thread,
        then collects outcomes with ts.write_sync — the (client_id,
        request_id) pair is the resume token, durable across leader
        changes because it is replicated inside the entry body
        (reference: the fully-async client write pipeline of
        src/yb/client/async_rpc.cc over Preparer batching)."""
        try:
            peer = self.tablet_manager.get(p["tablet_id"])
        except TabletNotFound:
            return {"code": "not_found"}
        payload = p.get("rows")
        cid, rid = p.get("client_id"), p.get("request_id")
        if not isinstance(payload, (bytes, bytearray)) or cid is None or \
                rid is None or p.get("if_not_exists") or \
                peer.tablet.meta.indexes:
            return self._h_ts_write(p)  # full synchronous write
        peer.ops_seen += 1  # split-manager load signal
        if p.get("propagated_ht"):
            from yugabyte_db_tpu.utils.hybrid_time import HybridTime as _HT

            peer.tablet.clock.update(_HT(p["propagated_ht"]))
        admitted = None
        with peer._intent_lock:
            if not peer.tablet.participant.txns:
                try:
                    admitted = peer.write_admit_block(payload, cid, rid)
                except NotLeader as e:
                    return {"code": "not_leader",
                            "leader_hint": e.leader_hint}
        if admitted is None:
            return self._h_ts_write(p)  # pending intents: slow path
        if admitted[0] == "dup":
            return self._write_ok(admitted[1])
        return {"code": "ok", "admitted": True}

    def _h_ts_write_sync(self, p: dict):
        """Completion half of the two-phase write: resolve the outcome
        of an admitted (client_id, request_id). Any replica that already
        APPLIED the write answers from its dedup registry; the leader
        waits for an in-flight one; an id nobody knows means the entry
        was lost to a leader change before commit — the client must
        re-send the full write (same id, so dedup keeps it exactly
        once)."""
        try:
            peer = self.tablet_manager.get(p["tablet_id"])
        except TabletNotFound:
            return {"code": "not_found"}
        cid, rid = p["client_id"], p["request_id"]
        from yugabyte_db_tpu.utils.hybrid_time import HybridTime as _HT

        prev = peer.tablet.retryable.seen(cid, rid)
        if prev is not None:
            return self._write_ok(_HT(prev))
        inflight = peer._inflight_rids.get((cid, rid))
        if inflight is None:
            if peer.raft.is_leader():
                if not peer.raft.leader_ready():
                    # A fresh leader may still hold the admitted entry
                    # uncommitted from the prior term; only once its own
                    # no_op has applied (and with it every surviving
                    # prior-term entry, into the dedup registry) is
                    # "unknown id" proof the entry was lost.
                    return {"code": "timed_out"}
                return {"code": "ok", "retry_write": True}
            return {"code": "not_leader",
                    "leader_hint": peer.raft.leader_uuid()}
        try:
            ht = peer.write_finish(
                ("inflight",) + inflight,
                timeout=Deadline.after(float(p.get("timeout", 10.0))))
        except NotLeader as e:
            return {"code": "not_leader", "leader_hint": e.leader_hint}
        except TimeoutError:
            return {"code": "timed_out"}
        return self._write_ok(ht)

    @staticmethod
    def _write_ok(ht) -> dict:
        from yugabyte_db_tpu.utils.fault_injection import maybe_fault

        if maybe_fault("fault.ts_write_respond_failed"):
            # the write APPLIED; the client sees a failure and retries —
            # exactly-once dedup must absorb it
            return {"code": "timed_out", "injected_fault": True}
        return {"code": "ok", "ht": ht.value}

    @staticmethod
    def _pin_read_point(peer, read_ht: int, timeout: float) -> dict | None:
        """Pin an explicit client read point on one tablet: advance the
        local clock past it so no later write lands at <= read_ht, then
        wait until every in-flight write below it resolves (reference:
        MvccManager::SafeTime wait in Tablet::DoHandleQLReadRequest).
        Returns an error response dict, or None on success."""
        from yugabyte_db_tpu.utils.flags import FLAGS
        from yugabyte_db_tpu.utils.hybrid_time import (BITS_FOR_LOGICAL,
                                                       HybridTime)
        # Never let a client-supplied read point ratchet the clock
        # beyond the skew bound — an arbitrary far-future read_ht would
        # poison every subsequent write HT on this tablet. (Logical
        # clocks in tests have no wall-clock skew semantics: no bound.)
        bound_fn = getattr(peer.tablet.clock, "max_global_now", None)
        if bound_fn is not None and read_ht > bound_fn().value + (
                FLAGS.get("max_clock_skew_us") << BITS_FOR_LOGICAL):
            return {"code": "invalid_read_time"}
        peer.tablet.clock.update(HybridTime(read_ht))
        # Default below the client's 5s per-attempt transport timeout
        # (client.py tablet_rpc) so the clean "timed_out" reply reaches
        # the caller instead of a transport error.
        if not peer.tablet.mvcc.wait_for_safe_time(
                HybridTime(read_ht), timeout=timeout):
            return {"code": "timed_out"}
        return None

    def _rpc_deadline(self, p: dict) -> Deadline:
        """The propagated deadline of one read RPC: the client debits
        its retry budget into ``payload["timeout"]`` (client.py
        tablet_rpc), and every stage below — safe-time wait, engine
        batch, device dispatch rounds — debits this one Deadline."""
        return Deadline.after(float(p.get("timeout", 4.0)))

    def _read_gate(self, p: dict, specs: list | None = None,
                   deadline: Deadline | None = None):
        """The shared read prologue of every scan RPC: tablet lookup,
        HLC causality (ratchet past everything the client observed
        BEFORE choosing the read time, so a fresh read cannot miss its
        own writes), read-point pinning, and intent resolution. With
        ``specs`` (the batch RPC) the gate pins once at the maximum
        explicit read point and resolves intents per spec.
        Returns (peer, specs, None) or (None, None, error-response)."""
        try:
            peer = self.tablet_manager.get(p["tablet_id"])
        except TabletNotFound:
            return None, None, {"code": "not_found"}
        if peer._split_sealing or peer.tablet.meta.split_sealed:
            # A sealed parent must not serve reads: once the split
            # commits, its children take new writes the frozen parent
            # would silently miss.
            return None, None, {"code": "tablet_split",
                                "tablet_id": peer.tablet_id}
        if p.get("propagated_ht"):
            from yugabyte_db_tpu.utils.hybrid_time import HybridTime as _HT

            peer.tablet.clock.update(_HT(p["propagated_ht"]))
        if specs is None:
            specs = [wire.decode_spec(p["spec"])]
        peer.ops_seen += len(specs)  # split-manager load signal
        explicit = [s.read_ht for s in specs if s.read_ht != wire.MAX_HT]
        if explicit:
            timeout = (deadline.timeout() if deadline is not None
                       else p.get("timeout", 4.0))
            err = self._pin_read_point(peer, max(explicit), timeout)
            if err is not None:
                return None, None, err
        prop = p.get("propagated_ht") or 0
        if prop and any(s.read_ht == wire.MAX_HT for s in specs):
            # Session read-your-writes under pipelined apply: writes ack
            # at COMMIT, and the apply stage drains asynchronously — a
            # fresh read must wait for safe time to reach everything the
            # client already observed (its own acked writes ride in
            # propagated_ht), or it would read below them.
            from yugabyte_db_tpu.utils.hybrid_time import HybridTime as _HT

            timeout = (deadline.timeout() if deadline is not None
                       else p.get("timeout", 4.0))
            if not peer.tablet.mvcc.wait_for_safe_time(_HT(prop),
                                                       timeout=timeout):
                return None, None, {"code": "timed_out"}
        read_ht = peer.read_time().value
        for s in specs:
            if s.read_ht == wire.MAX_HT:
                s.read_ht = read_ht
            err = self._resolve_read_intents(peer, s)
            if err is not None:
                return None, None, err
        TRACE("read point resolved")
        return peer, specs, None

    def _h_ts_scan(self, p: dict):
        deadline = self._rpc_deadline(p)
        peer, specs, err = self._read_gate(p, deadline=deadline)
        if err is not None:
            return err
        spec = specs[0]
        try:
            res = peer.scan(spec, allow_stale=p.get("allow_stale", False),
                            deadline=deadline)
        except NotLeader as e:
            return {"code": "not_leader", "leader_hint": e.leader_hint}
        except DeadlineExpired:
            return {"code": "timed_out"}
        out = wire.encode_result(res)
        out["code"] = "ok"
        out["read_ht"] = spec.read_ht
        return out

    def _h_ts_scan_batch(self, p: dict):
        """Many scans (typically point gets) in ONE RPC: one read gate,
        one engine batch — the server hop of the client's multi-key
        reads (reference: the batcher packing many ops into one
        tserver call, src/yb/client/batcher.h:80)."""
        deadline = self._rpc_deadline(p)
        peer, specs, err = self._read_gate(
            p, [wire.decode_spec(s) for s in p["specs"]],
            deadline=deadline)
        if err is not None:
            return err
        try:
            results = peer.scan_many(
                specs, allow_stale=p.get("allow_stale", False),
                deadline=deadline)
        except NotLeader as e:
            return {"code": "not_leader", "leader_hint": e.leader_hint}
        except DeadlineExpired:
            return {"code": "timed_out"}
        out = [wire.encode_result(r) for r in results]
        return {"code": "ok", "results": out,
                "read_ht": max(s.read_ht for s in specs)}

    def _h_ts_scan_wire(self, p: dict):
        """Scan returning SERIALIZED result-page bytes (fmt "cql" = CQL
        cells, "pg" = PG DataRow messages) — the reference's rows_data
        contract (src/yb/common/ql_rowblock.h:66): rows serialize once
        at the tablet and every layer above forwards bytes."""
        deadline = self._rpc_deadline(p)
        peer, specs, err = self._read_gate(p, deadline=deadline)
        if err is not None:
            return err
        spec = specs[0]
        try:
            pg = peer.scan_wire(spec, p.get("fmt", "cql"),
                                allow_stale=p.get("allow_stale", False),
                                deadline=deadline)
        except NotLeader as e:
            return {"code": "not_leader", "leader_hint": e.leader_hint}
        except DeadlineExpired:
            return {"code": "timed_out"}
        return {"code": "ok", "data": pg.data, "nrows": pg.nrows,
                "resume": pg.resume, "columns": pg.columns,
                "read_ht": spec.read_ht}

    def _h_ts_scan_wire_batch(self, p: dict):
        """Many wire-serialized scans in ONE RPC — the batched read hop
        of the native request-batch serving path (docs/serving-path.md):
        one read gate, one engine batch, one serialized result page per
        spec. Replaces a per-op ts.scan_wire round trip for every
        eligible prepared point SELECT in a pipelined CQL batch."""
        deadline = self._rpc_deadline(p)
        peer, specs, err = self._read_gate(
            p, [wire.decode_spec(s) for s in p["specs"]],
            deadline=deadline)
        if err is not None:
            return err
        try:
            pages = peer.scan_wire_many(
                specs, p.get("fmt", "cql"),
                allow_stale=p.get("allow_stale", False),
                deadline=deadline)
        except NotLeader as e:
            return {"code": "not_leader", "leader_hint": e.leader_hint}
        except DeadlineExpired:
            return {"code": "timed_out"}
        return {"code": "ok",
                "pages": [{"data": pg.data, "nrows": pg.nrows,
                           "resume": pg.resume, "columns": pg.columns}
                          for pg in pages],
                "read_ht": max(s.read_ht for s in specs)}

    def _h_ts_redis_read_batch(self, p: dict):
        """Batched redis point GETs served straight from the native
        memtable (yb_wp.Memtable.point_lookup) — no ScanSpec, no
        RowVersion materialization. Strictly an optimization of the
        scan-batch path: whenever the tablet cannot answer natively AND
        definitively (sorted runs, spilled rows, pending txn intents,
        pure-Python memtable) it replies {"code": "ok", "fallback":
        True} ("ok" so the client's TabletInvoker retry loop hands the
        reply straight back) and the frontend re-issues the batch
        through session.get_many, whose gate also resolves intents.
        Values are the raw stored payloads; None = absent row or NULL
        column; False = fall back for that key only."""
        try:
            peer = self.tablet_manager.get(p["tablet_id"])
        except TabletNotFound:
            return {"code": "not_found"}
        if peer._split_sealing or peer.tablet.meta.split_sealed:
            return {"code": "tablet_split", "tablet_id": peer.tablet_id}
        peer.ops_seen += len(p["keys"])  # split-manager load signal
        if p.get("propagated_ht"):
            from yugabyte_db_tpu.utils.hybrid_time import HybridTime as _HT

            peer.tablet.clock.update(_HT(p["propagated_ht"]))
        read_ht = peer.read_time().value
        try:
            values = peer.point_serve(
                p["keys"], read_ht, p["col_id"],
                allow_stale=p.get("allow_stale", False))
        except NotLeader as e:
            return {"code": "not_leader", "leader_hint": e.leader_hint}
        if values is None:
            return {"code": "ok", "fallback": True, "read_ht": read_ht}
        return {"code": "ok", "values": values, "read_ht": read_ht}

    def _resolve_read_intents(self, peer, spec) -> dict | None:
        """Intent-aware read gate (the IntentAwareIterator contract,
        src/yb/docdb/intent_aware_iterator.h:62-81, as a pre-scan gate):
        for each foreign txn with intents in the scanned range, ask its
        status tablet for the state AT spec.read_ht. The coordinator
        ratchets its clock past the asker's read time first, so:
          pending  -> any future commit lands above read_ht: ignore;
          aborted  -> ignore (cleaned lazily);
          committed with commit_ht <= read_ht -> the rows MUST be visible:
                      wait for the local apply to land, then scan.
        """
        part = peer.tablet.participant
        overlapping = part.txns_overlapping(spec.lower, spec.upper)
        for txn_id, meta in overlapping.items():
            resp = self.txn_router.tablet_rpc(
                meta["status_tablet"], "ts.txn_status",
                {"txn_id": txn_id, "read_ht": spec.read_ht})
            if resp is None or resp.get("code") != "ok":
                return {"code": "timed_out",
                        "detail": f"cannot resolve txn {txn_id}"}
            if resp["status"] == "committed" and \
                    resp["commit_ht"] <= spec.read_ht:
                if not part.wait_gone(txn_id, timeout=3.0):
                    return {"code": "timed_out",
                            "detail": f"txn {txn_id} apply lagging"}
        return None

    # -- transaction service --------------------------------------------------
    def _h_ts_write_intents(self, p: dict):
        """Provisional write with server-side conflict resolution
        (reference: docdb::ResolveTransactionConflicts,
        src/yb/docdb/conflict_resolution.cc)."""
        from yugabyte_db_tpu.txn.participant import IntentConflict

        try:
            peer = self.tablet_manager.get(p["tablet_id"])
        except TabletNotFound:
            return {"code": "not_found"}
        if peer._split_sealing or peer.tablet.meta.split_sealed:
            # Intent writes bypass write_admit's seal gate — check here.
            return {"code": "tablet_split", "tablet_id": peer.tablet_id}
        rows = wire.decode_rows(p["rows"])
        for _attempt in range(3):
            try:
                ht = peer.write_intents(p["txn_id"], p["status_tablet"],
                                        p["priority"], p["read_ht"], rows)
                return {"code": "ok", "ht": ht}
            except NotLeader as e:
                return {"code": "not_leader", "leader_hint": e.leader_hint}
            except TimeoutError:
                return {"code": "timed_out"}
            except IntentConflict as e:
                if not e.conflicting:
                    return {"code": "conflict", "message": str(e)}
                err = self._resolve_write_conflicts(peer, p, e.conflicting)
                if err is not None:
                    return err
        return {"code": "conflict", "message": "conflicts kept reappearing"}

    def _resolve_write_conflicts(self, peer, p, conflicting) -> dict | None:
        """Resolve pending foreign intents blocking a write: finish
        committed/aborted txns locally; for live ones run the priority
        duel — the higher-priority writer wounds the lower (aborts it at
        its coordinator), otherwise the writer loses. None = retry."""
        for other_id, other_status_tablet, other_prio in conflicting:
            resp = self.txn_router.tablet_rpc(
                other_status_tablet, "ts.txn_status",
                {"txn_id": other_id,
                 "read_ht": peer.tablet.clock.now().value})
            if resp is None or resp.get("code") != "ok":
                return {"code": "timed_out",
                        "detail": f"cannot resolve txn {other_id}"}
            try:
                if resp["status"] == "committed":
                    peer.replicate_txn_op(
                        "apply_intents",
                        {"txn_id": other_id, "commit_ht": resp["commit_ht"]},
                        ht=resp["commit_ht"])
                elif resp["status"] == "aborted":
                    peer.replicate_txn_op("remove_intents",
                                          {"txn_id": other_id})
                else:  # pending: the duel
                    if p["priority"] > other_prio:
                        ab = self.txn_router.tablet_rpc(
                            other_status_tablet, "ts.txn_abort",
                            {"txn_id": other_id})
                        if ab is None or ab.get("code") not in (
                                "ok", "aborted"):
                            return {"code": "conflict",
                                    "message": f"cannot wound {other_id}"}
                        peer.replicate_txn_op("remove_intents",
                                              {"txn_id": other_id})
                    else:
                        return {"code": "conflict",
                                "message": f"blocked by higher-priority "
                                           f"txn {other_id}"}
            except NotLeader as e:
                return {"code": "not_leader", "leader_hint": e.leader_hint}
        return None

    def _h_ts_apply_txn(self, p: dict):
        try:
            peer = self.tablet_manager.get(p["tablet_id"])
        except TabletNotFound:
            return {"code": "not_found"}
        if not peer.raft.is_leader():
            return {"code": "not_leader",
                    "leader_hint": peer.raft.leader_uuid()}
        if peer.tablet.participant.has_intents(p["txn_id"]):
            # Transactional writes maintain secondary indexes at APPLY
            # time, before the rows become readable — the same
            # index-before-base ordering as plain writes. (The reference
            # writes index intents inside the txn; this simpler commit-
            # time maintenance trades a txn-atomic index for the same
            # never-miss-once-visible invariant.)
            if peer.tablet.meta.indexes:
                rec = peer.tablet.participant.txns.get(p["txn_id"])
                if rec is not None:
                    err = self._maintain_indexes(peer, rec["rows"])
                    if err is not None:
                        return err
            try:
                peer.replicate_txn_op(
                    "apply_intents",
                    {"txn_id": p["txn_id"], "commit_ht": p["commit_ht"]},
                    ht=p["commit_ht"])
            except NotLeader as e:
                return {"code": "not_leader", "leader_hint": e.leader_hint}
        return {"code": "ok"}

    def _h_ts_remove_txn(self, p: dict):
        try:
            peer = self.tablet_manager.get(p["tablet_id"])
        except TabletNotFound:
            return {"code": "not_found"}
        if not peer.raft.is_leader():
            return {"code": "not_leader",
                    "leader_hint": peer.raft.leader_uuid()}
        if peer.tablet.participant.has_intents(p["txn_id"]):
            try:
                peer.replicate_txn_op("remove_intents",
                                      {"txn_id": p["txn_id"]})
            except NotLeader as e:
                return {"code": "not_leader", "leader_hint": e.leader_hint}
        return {"code": "ok"}

    # -- coordinator service (status tablet) ----------------------------------
    def _coord_peer(self, p: dict):
        try:
            peer = self.tablet_manager.get(p["tablet_id"])
        except TabletNotFound:
            return None, {"code": "not_found"}
        if peer.tablet.coordinator is None:
            return None, {"code": "error", "message": "not a status tablet"}
        # leader_ready (own-term entry applied) guarantees every
        # prior-term in-flight commit is applied before we answer status
        # queries — a new leader must not promise "pending" while an old
        # leader's commit entry is still committing through its log.
        if not (peer.raft.is_leader() and peer.raft.has_lease()
                and peer.raft.leader_ready()):
            return None, {"code": "not_leader",
                          "leader_hint": peer.raft.leader_uuid()}
        return peer, None

    def _h_ts_txn_create(self, p: dict):
        peer, err = self._coord_peer(p)
        if err is not None:
            return err
        try:
            peer.replicate_txn_op("txn_status", {
                "action": "create", "txn_id": p["txn_id"]})
        except NotLeader as e:
            return {"code": "not_leader", "leader_hint": e.leader_hint}
        return {"code": "ok", "read_ht": peer.tablet.clock.now().value}

    def _h_ts_txn_heartbeat(self, p: dict):
        peer, err = self._coord_peer(p)
        if err is not None:
            return err
        alive = peer.tablet.coordinator.heartbeat(p["txn_id"])
        return {"code": "ok" if alive else "aborted"}

    def _h_ts_txn_status(self, p: dict):
        peer, err = self._coord_peer(p)
        if err is not None:
            return err
        # resolve_status ratchets the coordinator clock past the asker's
        # read time and waits out in-flight commits, making a "pending"
        # answer a promise that any later commit lands above read_ht
        # (the StatusRequest serving contract).
        st = peer.tablet.coordinator.resolve_status(
            p["txn_id"], p["read_ht"], peer.tablet.clock)
        if st is None:
            return {"code": "timed_out"}
        return {"code": "ok", **st}

    def _h_ts_txn_commit(self, p: dict):
        peer, err = self._coord_peer(p)
        if err is not None:
            return err
        coord = peer.tablet.coordinator
        st = coord.status(p["txn_id"])
        if st["status"] == "committed":
            return {"code": "ok", "commit_ht": st["commit_ht"]}  # retry
        if st["status"] == "aborted":
            return {"code": "aborted"}
        # HLC propagation: every intent write's hybrid time (max'ed by the
        # client) must ratchet this clock before the commit time is
        # chosen, so commit_ht exceeds every intent write — and therefore
        # every read time any participant tablet has already served past.
        from yugabyte_db_tpu.utils.hybrid_time import HybridTime

        peer.tablet.clock.update(HybridTime(p.get("propagated_ht", 0)))
        commit_ht = coord.choose_commit_ht(p["txn_id"], peer.tablet.clock)
        # Deadline propagation (PR-7 convention): the append's
        # backpressure wait and the apply wait debit the client's one
        # remaining budget instead of a fresh hardcoded 10 s each.
        deadline = Deadline.after(float(p.get("timeout", 10.0)))
        try:
            entry = peer.raft.append_leader("txn_status", {
                "action": "commit", "txn_id": p["txn_id"],
                "commit_ht": commit_ht,
                "participants": p.get("participants", []),
            }, ht=commit_ht, deadline=deadline)
        except NotLeader as e:
            coord.finish_commit_attempt(p["txn_id"])
            return {"code": "not_leader", "leader_hint": e.leader_hint}
        except TimeoutError:
            coord.finish_commit_attempt(p["txn_id"])
            return {"code": "timed_out"}
        try:
            # Commit stays an apply-time barrier (NOT the commit-time
            # ack of plain writes): the coordinator's status registry
            # must reflect "committed" before the client is told so.
            peer.raft.wait_applied(entry.op_id, deadline)
        except NotLeader as e:
            # Entry truncated: the commit definitively did not happen.
            coord.finish_commit_attempt(p["txn_id"])
            return {"code": "not_leader", "leader_hint": e.leader_hint}
        except TimeoutError:
            # Outcome UNKNOWN: the entry may still commit with this
            # commit_ht, so the in-flight marker must stay until Raft
            # resolves it (else a status query could promise "pending").
            import threading as _threading

            def _resolve():
                try:
                    while True:
                        try:
                            peer.raft.wait_applied(entry.op_id,
                                                   Deadline.after(10.0))
                            break
                        except NotLeader:
                            break
                        except TimeoutError:
                            if not peer.raft._running:
                                break
                            continue
                except Exception:  # never die silently
                    import logging

                    logging.getLogger(__name__).exception(
                        "commit resolution for txn %s failed", p["txn_id"])
                finally:
                    # The in-flight marker must not leak on any path —
                    # a stuck marker wedges every later status query.
                    coord.finish_commit_attempt(p["txn_id"])

            _threading.Thread(target=_resolve, daemon=True).start()
            return {"code": "timed_out"}
        coord.finish_commit_attempt(p["txn_id"])
        # A racing abort may have been ordered first: report the outcome
        # the log actually chose.
        st = coord.status(p["txn_id"])
        if st["status"] != "committed":
            return {"code": "aborted"}
        self.txn_notifier.trigger()
        return {"code": "ok", "commit_ht": st["commit_ht"]}

    def _h_ts_txn_abort(self, p: dict):
        peer, err = self._coord_peer(p)
        if err is not None:
            return err
        coord = peer.tablet.coordinator
        st = coord.status(p["txn_id"])
        if st["status"] == "committed":
            return {"code": "committed", "commit_ht": st["commit_ht"]}
        if st["status"] == "aborted":
            return {"code": "ok"}
        try:
            peer.replicate_txn_op("txn_status", {
                "action": "abort", "txn_id": p["txn_id"],
                "participants": p.get("participants", []),
            })
        except NotLeader as e:
            return {"code": "not_leader", "leader_hint": e.leader_hint}
        st = coord.status(p["txn_id"])
        if st["status"] == "committed":
            return {"code": "committed", "commit_ht": st["commit_ht"]}
        self.txn_notifier.trigger()
        return {"code": "ok"}

    def _multi_scan_peers(self, p: dict):
        """Shared front half of the multi-tablet mesh scan handlers:
        gather the named peers (all must be leaders holding leases on
        THIS server), pin one repeatable read point across all of them,
        and resolve blocking intents. Returns (peers, spec, None) or
        (None, None, error-reply)."""
        peers = []
        for tid in p["tablet_ids"]:
            try:
                peer = self.tablet_manager.get(tid)
            except TabletNotFound:
                return None, None, {"code": "not_found", "tablet_id": tid}
            if not (peer.raft.is_leader() and peer.raft.has_lease()):
                return None, None, {"code": "not_leader", "tablet_id": tid,
                                    "leader_hint": peer.raft.leader_uuid()}
            peers.append(peer)
        spec = wire.decode_spec(p["spec"])
        if spec.read_ht == wire.MAX_HT:
            # Every tablet can already serve its own safe time; the min is
            # serveable by all without waiting and repeatable everywhere.
            spec.read_ht = min(pr.read_time().value for pr in peers)
        else:
            # One deadline across ALL pins: serial per-peer waits must not
            # sum past the client's propagated budget.
            deadline = self._rpc_deadline(p)
            for peer in peers:
                if deadline.expired():
                    return None, None, {"code": "timed_out"}
                err = self._pin_read_point(peer, spec.read_ht,
                                           deadline.timeout())
                if err is not None:
                    return None, None, err
        for peer in peers:
            err = self._resolve_read_intents(peer, spec)
            if err is not None:
                return None, None, err
        return peers, spec, None

    def _h_ts_multi_agg_scan(self, p: dict):
        """Aggregate over MANY tablets this server leads, as ONE device
        program over the mesh (tablets on the "t" axis, blocks on "b",
        psum/pmax combine over ICI — tserver.mesh_scan). The client falls
        back to per-tablet ts.scan + host combine on any non-ok reply."""
        peers, spec, err = self._multi_scan_peers(p)
        if err is not None:
            return err
        res = self.mesh_scan.aggregate(peers, spec)
        if res is None:
            return {"code": "ineligible"}
        out = wire.encode_result(res)
        out["code"] = "ok"
        out["read_ht"] = spec.read_ht
        return out

    def _h_ts_multi_row_scan(self, p: dict):
        """One LIMIT row page over MANY tablets this server leads, as ONE
        device program over the mesh (the packed MVCC row gather sharded
        on ("t", "b"), match counts psum over ICI — tserver.mesh_scan).
        ``resume`` carries the previous page's cross-tablet resume token,
        opaque to the client; tablet_ids must repeat in the same order
        every page. The client falls back to per-tablet ts.scan paging on
        any non-ok reply."""
        peers, spec, err = self._multi_scan_peers(p)
        if err is not None:
            return err
        res = self.mesh_scan.rows(peers, spec, resume=p.get("resume"))
        if res is None:
            return {"code": "ineligible"}
        out = wire.encode_result(res)
        out["code"] = "ok"
        out["read_ht"] = spec.read_ht
        return out

    def _h_ts_flush(self, p: dict):
        self.tablet_manager.get(p["tablet_id"]).flush()
        return {"code": "ok"}

    def _h_ts_compact(self, p: dict):
        self.tablet_manager.get(p["tablet_id"]).compact(
            p.get("history_cutoff_ht", 0))
        return {"code": "ok"}

    def _h_ts_change_config(self, p: dict):
        peer = self.tablet_manager.get(p["tablet_id"])
        try:
            peer.raft.change_config(p["peers"])
        except NotLeader as e:
            return {"code": "not_leader", "leader_hint": e.leader_hint}
        return {"code": "ok"}

    def _h_ts_transfer_leadership(self, p: dict):
        peer = self.tablet_manager.get(p["tablet_id"])
        peer.raft.transfer_leadership(p["target"])
        return {"code": "ok"}

    def _h_ts_checksum(self, p: dict):
        """Checksum of this replica's visible rows at a read hybrid time
        (reference: ChecksumService / ysck checksum scans,
        src/yb/tserver/tserver_service.proto Checksum). Reads LOCALLY
        (leader or follower) — the caller pins one read_ht across all
        replicas and retries transient divergence while appliers catch
        up. Without read_ht the replica picks its safe time and returns
        it so the caller can pin the rest of the group to it."""
        import hashlib

        from yugabyte_db_tpu.utils import codec

        try:
            peer = self.tablet_manager.get(p["tablet_id"])
        except TabletNotFound:
            return {"code": "not_found"}
        read_ht = p.get("read_ht")
        if read_ht is None:
            read_ht = peer.read_time().value
        else:
            # Same consistency gates as ts.scan: wait out in-flight writes
            # below the pinned point and committed-but-unapplied intents,
            # so applier lag isn't misreported as corruption.
            err = self._pin_read_point(peer, read_ht, p.get("timeout", 4.0))
            if err is not None:
                return err
        spec = ScanSpec(lower=b"", upper=b"", read_ht=read_ht)
        err = self._resolve_read_intents(peer, spec)
        if err is not None:
            return err
        h = hashlib.sha256()
        total = 0
        resume = b""
        while True:
            page = ScanSpec(lower=resume, upper=b"", read_ht=read_ht,
                            limit=4096)
            res = peer.scan(page, allow_stale=True)
            for row in res.rows:
                h.update(codec.encode(row))
            total += len(res.rows)
            if res.resume_key is None:
                break
            resume = res.resume_key
        return {"code": "ok", "read_ht": read_ht, "rows": total,
                "checksum": h.hexdigest()}

    def _h_ts_status(self, p: dict):
        return {
            "code": "ok",
            "uuid": self.uuid,
            "tablets": {pr.tablet_id: pr.stats()
                        for pr in self.tablet_manager.peers()},
        }
