"""MeshScanService: multi-tablet scans over the device mesh.

The cluster read path for a tserver leading several tablets of a table:
instead of one ts.scan per tablet with the CLIENT merging on host (the
reference's shape — per-tablet EvalAggregate partials recombined by the
CQL executor / PG FDW, src/yb/docdb/pgsql_operation.cc:473, and the
batcher's thread-per-tablet row fan-out, src/yb/client/batcher.h:80),
the tserver serves them with ONE device program: tablets sharded over
the mesh "t" axis, each tablet's blocks over "b".

- Aggregates: partials combined with psum / two-plane lexicographic
  pmax over ICI (parallel.sharded.sharded_aggregate).
- Row scans: the packed MVCC row gather runs on every (tablet,
  block-range) shard, per-device match counts psum over ICI, and the
  host decodes only the LIMIT page's rows
  (parallel.sharded.sharded_row_page). Cross-tablet paging rides the
  (tablet index, last key) resume token, opaque to the client.

The client-side merge remains only as the cross-tserver / ineligible-
spec fallback.

Mesh policy: built once from the visible devices — "t" gets the larger
factor (tablet parallelism is the dominant axis), "b" gets 2 when the
device count is even. A single-chip node degenerates to a 1x1 mesh and
still executes the same program (collectives become identities), so the
code path is identical from laptop to pod slice.

Stack lifecycle: stacked device residency is cached per run set. A
flush/compaction replaces ONE tablet's ColumnarRun; when the stack is
un-encoded the cache updates that tablet's slot in place with a jitted
dynamic_update_slice — fed straight from the run's resident device
planes (the PR-15 device-flush output) when they are on device, no host
round trip. Otherwise the superseded stack's residency is released
immediately (close() — in-flight scans holding the old arrays finish
unharmed; the bytes leave the budget when the last reference dies).
"""

from __future__ import annotations

import threading

from yugabyte_db_tpu.storage.scan_spec import ScanResult, ScanSpec
from yugabyte_db_tpu.utils.fault_injection import maybe_fault


class MeshScanService:
    """Per-tserver service executing multi-tablet scans on the device
    mesh. Stateless between calls except for a small cache of stacked
    device residency, invalidated incrementally as flush/compaction
    replace ColumnarRun objects."""

    def __init__(self, max_cached_stacks: int = 2):
        self._lock = threading.Lock()
        self._mesh = None
        self._stacks: dict[tuple, object] = {}
        self._max_cached = max_cached_stacks
        self.served = 0       # aggregates answered on the mesh
        self.served_rows = 0  # row pages answered on the mesh
        self.updated = 0      # stacks refreshed in place (update_tablet)
        self.fallbacks = 0    # ineligible requests bounced to per-tablet
        self.chip_losses = 0  # mesh dispatches lost to a dropped chip

    def _get_mesh(self):
        if self._mesh is None:
            import jax
            from jax.sharding import Mesh
            import numpy as np

            devices = jax.devices()
            n = len(devices)
            mesh_b = 2 if n % 2 == 0 else 1
            mesh_t = n // mesh_b
            self._mesh = Mesh(
                np.array(devices[:mesh_t * mesh_b]).reshape(mesh_t, mesh_b),
                ("t", "b"))
        return self._mesh

    def eligible_peer(self, peer, spec: ScanSpec) -> bool:
        """Engine-state eligibility: TPU engine, exactly one run, no
        memtable data in the scanned range (single-source — the mesh
        program has no host-merge stage)."""
        engine = peer.tablet.engine
        runs = getattr(engine, "runs", None)
        if runs is None or len(runs) != 1:
            return False
        if not hasattr(runs[0], "crun"):
            return False  # cpu engine
        if engine._memtable_in_range(spec) or runs[0].crun.num_versions == 0:
            return False
        return True

    def _get_stack(self, peers: list, runs: list):
        """The cached ShardedTablets for this exact run set, refreshed
        incrementally when exactly one tablet's run changed since a
        cached stack (the flush/compaction case): the changed slot is
        rewritten in place on device, seeded from the run's resident
        flush planes when they exist. Full rebuilds release the
        superseded stack's residency immediately. None = unbuildable
        (caller falls back)."""
        key = tuple(id(r) for r in runs)
        mesh = self._get_mesh()
        with self._lock:
            st = self._stacks.get(key)
            if st is not None:
                return st
            for okey in list(self._stacks):
                if len(okey) != len(key):
                    continue
                diff = [i for i, (a, b) in enumerate(zip(okey, key))
                        if a != b]
                if len(diff) != 1:
                    continue
                t = diff[0]
                ost = self._stacks[okey]
                trun = peers[t].tablet.engine.runs[0]
                dev = getattr(trun, "peek_device", lambda: None)()
                if ost.update_tablet(t, runs[t],
                                     device_arrays=(dev.arrays
                                                    if dev is not None
                                                    else None)):
                    del self._stacks[okey]
                    self._stacks[key] = ost
                    self.updated += 1
                    return ost
                break
            from yugabyte_db_tpu.parallel import ShardedTablets

            schema = peers[0].tablet.meta.schema
            try:
                st = ShardedTablets(schema, runs, mesh)
            except ValueError:
                return None
            while len(self._stacks) >= self._max_cached:
                old = self._stacks.pop(next(iter(self._stacks)))
                old.close()  # release residency; in-flight scans finish
            self._stacks[key] = st
            return st

    def drop_stacks(self) -> int:
        """Release every cached stack's residency (chip loss / device
        hot-unplug: placements on the lost chip are unusable, so the
        whole per-device footprint unwinds — in-flight scans holding
        the old arrays finish unharmed). Subsequent eligible scans
        rebuild on the surviving mesh. Returns the number dropped."""
        with self._lock:
            stacks = list(self._stacks.values())
            self._stacks.clear()
        for st in stacks:
            st.close()
        return len(stacks)

    def _lost_chip(self) -> bool:
        """The ``fault.mesh_dispatch`` point, evaluated right before a
        device dispatch: a fired fault models a mesh chip dropping out
        mid-scan. The service releases all stacked residency and bounces
        the request to the per-tablet host path (byte-identical serve);
        it does NOT retry on the device — the caller's fallback is the
        availability story, exactly like the engine breaker's."""
        if not maybe_fault("fault.mesh_dispatch"):
            return False
        self.chip_losses += 1
        self.fallbacks += 1
        self.drop_stacks()
        return True

    def _eligible_runs(self, peers: list, spec: ScanSpec):
        if not all(self.eligible_peer(p, spec) for p in peers):
            return None
        return [p.tablet.engine.runs[0].crun for p in peers]

    def aggregate(self, peers: list, spec: ScanSpec) -> ScanResult | None:
        """Run spec's aggregates over all peers' tablets on the mesh.
        Returns None when ineligible (caller falls back to per-tablet
        scans + host combine)."""
        from yugabyte_db_tpu.parallel import sharded_aggregate

        if not spec.is_aggregate or spec.group_by:
            self.fallbacks += 1
            return None
        runs = self._eligible_runs(peers, spec)
        st = self._get_stack(peers, runs) if runs else None
        if st is None:
            self.fallbacks += 1
            return None
        if self._lost_chip():
            return None
        try:
            res = sharded_aggregate(st, spec)
        except ValueError:
            self.fallbacks += 1
            return None  # spec not device-exact: fallback
        self.served += 1
        return res

    def rows(self, peers: list, spec: ScanSpec,
             resume: bytes | None = None) -> ScanResult | None:
        """Serve one LIMIT row page over all peers' tablets on the mesh
        (parallel.sharded.sharded_row_page). ``resume`` is the previous
        page's resume token (opaque (tablet index, last key)); tablet
        indices resolve against THIS peer list, so callers must pass the
        same tablet order every page. Returns None when ineligible."""
        from yugabyte_db_tpu.parallel import sharded_row_page

        if spec.is_aggregate or spec.group_by:
            self.fallbacks += 1
            return None
        runs = self._eligible_runs(peers, spec)
        st = self._get_stack(peers, runs) if runs else None
        if st is None:
            self.fallbacks += 1
            return None
        if self._lost_chip():
            return None
        try:
            res = sharded_row_page(st, spec, resume=resume)
        except ValueError:
            self.fallbacks += 1
            return None  # spec not device-exact: fallback
        self.served_rows += 1
        return res
