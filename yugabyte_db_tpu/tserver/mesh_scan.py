"""MeshScanService: multi-tablet aggregates over the device mesh.

The cluster read path for aggregates: instead of one ts.scan per tablet
with the CLIENT merging partial aggregates on host (the reference's shape
— per-tablet EvalAggregate partials recombined by the CQL executor /
PG FDW, src/yb/docdb/pgsql_operation.cc:473), a tserver that leads
several tablets of a table serves them with ONE device program: tablets
sharded over the mesh "t" axis, each tablet's blocks over "b", partials
combined with psum / two-plane lexicographic pmax over ICI
(parallel.sharded.sharded_aggregate). The client-side host merge remains
only as the cross-tserver / ineligible-spec fallback.

Mesh policy: built once from the visible devices — "t" gets the larger
factor (tablet parallelism is the dominant axis), "b" gets 2 when the
device count is even. A single-chip node degenerates to a 1x1 mesh and
still executes the same program (collectives become identities), so the
code path is identical from laptop to pod slice.
"""

from __future__ import annotations

import threading

from yugabyte_db_tpu.storage.scan_spec import ScanResult, ScanSpec


class MeshScanService:
    """Per-tserver service executing multi-tablet aggregate scans on the
    device mesh. Stateless between calls except for a small cache of
    stacked device residency (rebuilt whenever any tablet's run set
    changes — flush/compaction replace ColumnarRun objects)."""

    def __init__(self, max_cached_stacks: int = 2):
        self._lock = threading.Lock()
        self._mesh = None
        self._stacks: dict[tuple, object] = {}
        self._max_cached = max_cached_stacks
        self.served = 0       # aggregates answered on the mesh
        self.fallbacks = 0    # ineligible requests bounced to per-tablet

    def _get_mesh(self):
        if self._mesh is None:
            import jax
            from jax.sharding import Mesh
            import numpy as np

            devices = jax.devices()
            n = len(devices)
            mesh_b = 2 if n % 2 == 0 else 1
            mesh_t = n // mesh_b
            self._mesh = Mesh(
                np.array(devices[:mesh_t * mesh_b]).reshape(mesh_t, mesh_b),
                ("t", "b"))
        return self._mesh

    def eligible_peer(self, peer, spec: ScanSpec) -> bool:
        """Engine-state eligibility: TPU engine, exactly one run, no
        memtable data in the scanned range (single-source — the mesh
        program has no host-merge stage)."""
        engine = peer.tablet.engine
        runs = getattr(engine, "runs", None)
        if runs is None or len(runs) != 1:
            return False
        if not hasattr(runs[0], "crun"):
            return False  # cpu engine
        if engine._memtable_in_range(spec) or runs[0].crun.num_versions == 0:
            return False
        return True

    def aggregate(self, peers: list, spec: ScanSpec) -> ScanResult | None:
        """Run spec's aggregates over all peers' tablets on the mesh.
        Returns None when ineligible (caller falls back to per-tablet
        scans + host combine)."""
        from yugabyte_db_tpu.parallel import ShardedTablets, sharded_aggregate

        if not spec.is_aggregate or spec.group_by:
            self.fallbacks += 1
            return None
        if not all(self.eligible_peer(p, spec) for p in peers):
            self.fallbacks += 1
            return None
        runs = [p.tablet.engine.runs[0].crun for p in peers]
        key = tuple(id(r) for r in runs)
        mesh = self._get_mesh()
        with self._lock:
            st = self._stacks.get(key)
            if st is None:
                schema = peers[0].tablet.meta.schema
                try:
                    st = ShardedTablets(schema, runs, mesh)
                except ValueError:
                    st = None  # counted outside the lock
                else:
                    if len(self._stacks) >= self._max_cached:
                        self._stacks.pop(next(iter(self._stacks)))
                    self._stacks[key] = st
        if st is None:
            self.fallbacks += 1
            return None
        try:
            res = sharded_aggregate(st, spec)
        except ValueError:
            self.fallbacks += 1
            return None  # spec not device-exact: fallback
        self.served += 1
        return res
