"""Tablet server: the data-node daemon.

Reference analog: src/yb/tserver/ — TabletServer (tablet_server.cc) hosting
TabletPeers through TSTabletManager (ts_tablet_manager.cc), serving
reads/writes (TabletServiceImpl, tablet_service.cc:718,1001), and
heartbeating to the master (heartbeater.h:54).
"""

from yugabyte_db_tpu.tserver.tablet_server import TabletServer

__all__ = ["TabletServer"]
