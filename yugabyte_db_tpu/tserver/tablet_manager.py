"""TSTabletManager: the set of tablet replicas hosted by one node.

Reference analog: src/yb/tserver/ts_tablet_manager.cc — opens every tablet
found on disk at startup (each under <fs_root>/tablet-data/<tablet_id>),
creates/deletes replicas on master request, and routes per-tablet RPCs.
"""

from __future__ import annotations

import os
import shutil
import threading

from yugabyte_db_tpu.consensus.raft import RaftOptions
from yugabyte_db_tpu.tablet.tablet import TabletMetadata
from yugabyte_db_tpu.tablet.tablet_peer import TabletPeer


class TabletAlreadyExists(Exception):
    pass


class TabletNotFound(Exception):
    pass


class TSTabletManager:
    def __init__(self, node_uuid: str, fs_root: str, transport,
                 raft_opts: RaftOptions | None = None,
                 engine_options: dict | None = None, fsync: bool = True):
        self.node_uuid = node_uuid
        self.data_root = os.path.join(fs_root, "tablet-data")
        os.makedirs(self.data_root, exist_ok=True)
        self.transport = transport
        self.raft_opts = raft_opts
        self.engine_options = engine_options
        self.fsync = fsync
        self._lock = threading.Lock()
        self._peers: dict[str, TabletPeer] = {}
        # Wired by the TabletServer: called (tablet_id, peer_uuid) when a
        # leader here finds a peer lagging past the log-cache floor.
        self.bootstrap_notifier = None
        self.bootstrap_installs = 0  # observability / tests
        # tablet_ids with a create in flight: reserved atomically under the
        # lock so two concurrent ts.create_tablet RPCs (master dispatch
        # racing the balancer's retry) can never both start a peer on the
        # same WAL directory.
        self._creating: set[str] = set()

    # -- lifecycle ----------------------------------------------------------
    def open_existing(self) -> int:
        """Open every tablet directory found on disk (startup path)."""
        opened = 0
        for tablet_id in sorted(os.listdir(self.data_root)):
            meta_path = os.path.join(self.data_root, tablet_id,
                                     "tablet-meta.json")
            if not os.path.exists(meta_path):
                continue
            meta = TabletMetadata.load(meta_path)
            self._start_peer(meta, initial_peers=[])
            opened += 1
        return opened

    def create_tablet(self, meta: TabletMetadata, peers: list[str]) -> TabletPeer:
        with self._lock:
            if meta.tablet_id in self._peers or \
                    meta.tablet_id in self._creating:
                raise TabletAlreadyExists(meta.tablet_id)
            self._creating.add(meta.tablet_id)
        try:
            tdir = os.path.join(self.data_root, meta.tablet_id)
            os.makedirs(tdir, exist_ok=True)
            meta.save(os.path.join(tdir, "tablet-meta.json"))
            return self._start_peer(meta, peers)
        finally:
            with self._lock:
                self._creating.discard(meta.tablet_id)

    def _start_peer(self, meta: TabletMetadata, initial_peers: list[str]) -> TabletPeer:
        peer = TabletPeer(self.node_uuid, meta, self.data_root,
                          self.transport, initial_peers,
                          engine_options=self.engine_options,
                          fsync=self.fsync, raft_opts=self.raft_opts)
        peer.raft.on_needs_bootstrap = self._notify_bootstrap
        with self._lock:
            self._peers[meta.tablet_id] = peer
        peer.start()
        return peer

    def _notify_bootstrap(self, tablet_id: str, peer_uuid: str) -> None:
        cb = self.bootstrap_notifier
        if cb is not None:
            cb(tablet_id, peer_uuid)

    def install_snapshot(self, tablet_id: str, payload: dict) -> None:
        """Rebuild one tablet from a remote-bootstrap payload: runs +
        sidecars + WAL tail + consensus metadata written to disk, then
        reopened through the NORMAL open path (bootstrap replays the tail
        over the flushed frontier) — reference:
        remote_bootstrap_client.cc installing the downloaded session."""
        from yugabyte_db_tpu.consensus.metadata import (ConsensusMetadata,
                                                        RaftConfig)
        from yugabyte_db_tpu.models.schema import Schema
        from yugabyte_db_tpu.storage import wire
        from yugabyte_db_tpu.storage.run_io import RunPersistence
        from yugabyte_db_tpu.tablet.wal import Log, LogEntry
        from yugabyte_db_tpu.utils import codec

        with self._lock:
            if tablet_id in self._creating:
                return
            self._creating.add(tablet_id)
            peer = self._peers.get(tablet_id)
            # Term fencing: a STALE ex-leader may still believe peers lag
            # and push a snapshot; destroying a healthy replica and
            # regressing its durable term would un-commit acknowledged
            # entries. Only install snapshots from the replica's present
            # or a newer term.
            if peer is not None and \
                    payload["term"] < peer.raft.cmeta.current_term:
                self._creating.discard(tablet_id)
                return
            self._peers.pop(tablet_id, None)
        try:
            if peer is not None:
                peer.shutdown()
            tdir = os.path.join(self.data_root, tablet_id)
            shutil.rmtree(tdir, ignore_errors=True)
            os.makedirs(tdir, exist_ok=True)

            meta = TabletMetadata(
                tablet_id, payload["table_name"],
                Schema.from_dict(payload["schema"]),
                payload["partition_start"], payload["partition_end"],
                payload["engine"], payload["flushed_op_index"],
                payload.get("indexes") or [])
            meta.save(os.path.join(tdir, "tablet-meta.json"))

            entries = [(key, wire.decode_rows(vers))
                       for key, vers in payload["runs"]]
            if entries:
                RunPersistence(os.path.join(tdir, "runs")).save_new(entries)
            from yugabyte_db_tpu.tablet.tablet import Tablet as _Tablet

            _Tablet.install_snapshots(tdir, {
                sid: {"entries": [(k, wire.decode_rows(vers))
                                  for k, vers in blob["entries"]],
                      "meta": blob.get("meta") or {}}
                for sid, blob in (payload.get("snapshots") or {}).items()})
            for name, blob in (("intents.bin", payload.get("intents")),
                               ("retryable.bin", payload.get("retryable"))):
                if blob is not None:
                    with open(os.path.join(tdir, name), "wb") as f:
                        f.write(codec.encode(blob))
            if payload.get("txn_state") is not None:
                import json as _json

                with open(os.path.join(tdir, "txn_state.json"), "w") as f:
                    _json.dump(payload["txn_state"], f)

            log = Log(os.path.join(tdir, "wal"), fsync=self.fsync)
            for rec in payload["log"]:
                log.append(LogEntry.from_record(rec))
            log.sync()
            log.close()

            cmeta = ConsensusMetadata(
                os.path.join(tdir, "consensus-meta.json"), self.node_uuid,
                RaftConfig.from_dict(payload["config"]))
            cmeta.set_term(payload["term"])
            cmeta.flush()
            with self._lock:
                self.bootstrap_installs += 1
            # The peer starts while the tablet id is still reserved, so a
            # racing ts.create_tablet cannot start a second peer on the
            # same WAL directory in the gap.
            self._start_peer(
                TabletMetadata.load(
                    os.path.join(tdir, "tablet-meta.json")),
                initial_peers=[])
        finally:
            with self._lock:
                self._creating.discard(tablet_id)

    def delete_tablet(self, tablet_id: str) -> None:
        with self._lock:
            peer = self._peers.pop(tablet_id, None)
        if peer is not None:
            peer.shutdown()
        tdir = os.path.join(self.data_root, tablet_id)
        if os.path.isdir(tdir):
            shutil.rmtree(tdir)

    def shutdown(self) -> None:
        with self._lock:
            peers = list(self._peers.values())
            self._peers.clear()
        for p in peers:
            p.shutdown()

    # -- access -------------------------------------------------------------
    def get(self, tablet_id: str) -> TabletPeer:
        with self._lock:
            peer = self._peers.get(tablet_id)
        if peer is None:
            raise TabletNotFound(tablet_id)
        return peer

    def peers(self) -> list[TabletPeer]:
        with self._lock:
            return list(self._peers.values())

    def tablet_reports(self) -> list[dict]:
        """Per-tablet state for the master heartbeat (reference:
        TabletReportPB in master.proto)."""
        out = []
        for p in self.peers():
            rs = p.raft.stats()
            out.append({
                "tablet_id": p.tablet_id,
                "table_name": p.tablet.meta.table_name,
                "role": rs["role"],
                "term": rs["term"],
                "leader": rs["leader"],
                "peers": rs["config"]["peers"],
                # index names this replica maintains: the master compares
                # against the catalog and re-pushes ts.set_indexes on
                # mismatch (a lost push must not disable maintenance).
                "index_names": sorted(i["name"]
                                      for i in p.tablet.meta.indexes),
                # Split-manager inputs: on-disk size (WAL segments — a
                # cheap stat that tracks data written) and the raw data-op
                # counter; the master differentiates successive heartbeat
                # samples into the per-tablet op rate.
                "stats": {
                    "size_bytes": self._tablet_size_bytes(p),
                    "ops_seen": p.ops_seen,
                },
            })
        return out

    @staticmethod
    def _tablet_size_bytes(p: TabletPeer) -> int:
        total = 0
        for path in p.tablet.log.segment_paths():
            try:
                total += os.path.getsize(path)
            except OSError:
                pass  # segment GC'd between listing and stat
        return total
