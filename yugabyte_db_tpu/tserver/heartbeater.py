"""Heartbeater: periodic tserver -> master liveness + tablet reports.

Reference analog: src/yb/tserver/heartbeater.{h,cc} — finds the master
leader (trying each master, following NOT_THE_LEADER hints), registers on
first contact, and ships incremental tablet reports; the master answers
with the catalog's view (e.g. tablets to delete).
"""

from __future__ import annotations

import threading
import time

from yugabyte_db_tpu.utils.locking import guarded_by
from yugabyte_db_tpu.utils.retry import RetryPolicy


# The heartbeat thread and the server's start/stop/trigger callers share
# these; _wake/-thread lifecycle needs no lock (Event is self-locking,
# _thread is written before start() returns).
@guarded_by("_lock", "_leader_hint", "_running", "last_response",
            "consecutive_failures")
class Heartbeater:
    def __init__(self, server, master_uuids: list[str],
                 interval_s: float = 0.5):
        self.server = server
        self.master_uuids = list(master_uuids)
        self.interval_s = interval_s
        self._lock = threading.Lock()
        self._leader_hint: str | None = None
        self._running = False
        self._thread: threading.Thread | None = None
        self._wake = threading.Event()
        self.last_response: dict | None = None
        self.consecutive_failures = 0
        # Per-heartbeat budget: a couple of failover sweeps with jittered
        # backoff, bounded well below the stop() join timeout so a
        # leaderless master quorum can't wedge shutdown.
        self.retry_policy = RetryPolicy(
            timeout_s=max(2.0, interval_s * 4),
            initial_backoff_s=0.05, max_backoff_s=0.5)

    def start(self) -> None:
        with self._lock:
            self._running = True
        self._thread = threading.Thread(
            target=self._loop, name=f"heartbeat-{self.server.uuid}",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        with self._lock:
            self._running = False
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def trigger(self) -> None:
        """Heartbeat now (e.g. right after a tablet state change)."""
        self._wake.set()

    def _loop(self) -> None:
        while self._running:
            try:
                self._heartbeat_once()
                with self._lock:
                    self.consecutive_failures = 0
            except Exception:
                with self._lock:
                    self.consecutive_failures += 1
                    self._leader_hint = None
            self._wake.wait(timeout=self.interval_s)
            self._wake.clear()

    def _heartbeat_once(self) -> None:
        req = {
            "ts_uuid": self.server.uuid,
            "addr": self.server.advertised_addr,
            "cloud_info": getattr(self.server, "cloud_info", None) or {},
            "tablets": self.server.tablet_manager.tablet_reports(),
            "num_live_tablets": len(self.server.tablet_manager.peers()),
        }
        last: object = None
        for attempt in self.retry_policy.attempts():
            if not self._running:
                return
            # A fresh hint learned mid-sweep gets tried first next sweep.
            targets = ([self._leader_hint] if self._leader_hint else []) + [
                u for u in self.master_uuids if u != self._leader_hint]
            for target in targets:
                try:
                    resp = self.server.transport.send(
                        target, "master.ts_heartbeat", req,
                        timeout=attempt.timeout(2.0))
                except Exception as e:  # noqa: BLE001 — try the next master
                    last = e
                    continue
                if resp.get("code") == "not_leader":
                    with self._lock:
                        self._leader_hint = resp.get("leader_hint")
                    last = resp
                    continue
                with self._lock:
                    self._leader_hint = target
                    self.last_response = resp
                self.server.process_heartbeat_response(resp)
                return
            attempt.note(last)
        if isinstance(last, Exception):
            raise last
        raise ConnectionError(f"no master leader reachable ({last})")
