"""Proxy: outbound RPC client with call-id multiplexing.

Reference analog: src/yb/rpc/proxy.cc + outbound_call.cc — many concurrent
calls share one connection; responses are matched by call id; deadlines are
per-call. One background reader thread per connection (the reference uses
its reactor for this; a dedicated reader keeps the client usable without a
Messenger, e.g. in tools).
"""

from __future__ import annotations

import socket
import struct
import threading

from yugabyte_db_tpu.rpc.messenger import MAX_FRAME, RpcCallError
from yugabyte_db_tpu.utils import codec
from yugabyte_db_tpu.utils.retry import Deadline

_LEN = struct.Struct("<I")


class _PendingCall:
    __slots__ = ("event", "status", "body")

    def __init__(self):
        self.event = threading.Event()
        self.status = None
        self.body = None


class Proxy:
    def __init__(self, host: str, port: int, connect_timeout: float = 5.0):
        self.addr = (host, port)
        self._sock = socket.create_connection((host, port),
                                              timeout=connect_timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.settimeout(None)
        self._send_lock = threading.Lock()
        self._lock = threading.Lock()
        self._pending: dict[int, _PendingCall] = {}
        self._next_id = 1
        self._closed = False
        self._reader = threading.Thread(target=self._read_loop,
                                        name=f"proxy-read-{host}:{port}",
                                        daemon=True)
        self._reader.start()

    def call(self, method: str, body, timeout: float = 10.0,
             deadline: Deadline | None = None):
        """Send one call and wait for its response. ``deadline`` (the
        propagated utils.retry budget) caps ``timeout`` at the caller's
        remaining budget, so a retry loop's later attempts never wait
        longer than the one deadline they all debit."""
        if deadline is not None:
            timeout = deadline.timeout(timeout)
        with self._lock:
            if self._closed:
                raise ConnectionError(f"proxy to {self.addr} is closed")
            call_id = self._next_id
            self._next_id += 1
            pc = _PendingCall()
            self._pending[call_id] = pc
        payload = codec.encode([call_id, method, body])
        frame = _LEN.pack(len(payload)) + payload
        try:
            with self._send_lock:
                self._sock.sendall(frame)
        except OSError as e:
            with self._lock:
                self._pending.pop(call_id, None)
            self.close()
            raise ConnectionError(f"send to {self.addr} failed: {e}") from e
        if not pc.event.wait(timeout):
            with self._lock:
                self._pending.pop(call_id, None)
            raise TimeoutError(f"rpc {method} to {self.addr} timed out")
        if pc.status == "conn_closed":
            # Transport-level loss, NOT a remote handler error: callers'
            # failover paths key on ConnectionError.
            raise ConnectionError(f"connection to {self.addr} dropped "
                                  f"mid-call ({method})")
        if pc.status != "ok":
            raise RpcCallError(pc.body)
        return pc.body

    def _read_loop(self) -> None:
        buf = bytearray()
        sock = self._sock
        try:
            while True:
                data = sock.recv(256 * 1024)
                if not data:
                    break
                buf.extend(data)
                while len(buf) >= _LEN.size:
                    (length,) = _LEN.unpack_from(buf, 0)
                    if length > MAX_FRAME:
                        raise ValueError("oversized frame")
                    end = _LEN.size + length
                    if len(buf) < end:
                        break
                    call_id, status, body = codec.decode(bytes(buf[_LEN.size:end]))
                    del buf[:end]
                    with self._lock:
                        pc = self._pending.pop(call_id, None)
                    if pc is not None:
                        pc.status, pc.body = status, body
                        pc.event.set()
        except (OSError, ValueError):
            pass  # link-level loss: close() fails pending calls over
        except Exception:  # decode/dispatch bug — never die silently
            import logging

            logging.getLogger(__name__).exception(
                "proxy read loop to %s failed", self.addr)
        finally:
            self.close()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            pending = list(self._pending.values())
            self._pending.clear()
        for pc in pending:
            pc.status, pc.body = "conn_closed", None
            pc.event.set()
        try:
            self._sock.close()
        except OSError:
            pass

    @property
    def closed(self) -> bool:
        return self._closed
