"""The one-method messaging seam every layer above rpc programs against.

Reference analog: the Messenger/Proxy surface of src/yb/rpc/ as consumed
by consensus and the daemons — ``send(dst, method, payload) -> response``
with node-level handlers. The ABC lives here in the rpc layer (not in
consensus) so the dependency points down the stack: consensus,
integration, and the daemons all import the seam from rpc;
implementations are ``LocalTransport`` (consensus.transport, in-process
with fault injection) and ``SocketTransport`` (rpc.transport, real TCP).
"""

from __future__ import annotations

import abc


class TransportError(Exception):
    """Delivery failure (unreachable, partitioned, dropped, timed out)."""


class Transport(abc.ABC):
    @abc.abstractmethod
    def send(self, dst: str, method: str, payload: dict,
             timeout: float = 5.0) -> dict:
        """Deliver a request to node ``dst``; return its response.
        Raises TransportError if the node is unreachable."""

    @abc.abstractmethod
    def register(self, uuid: str, handler) -> None:
        """Register ``handler(method, payload) -> response`` for a node."""

    @abc.abstractmethod
    def unregister(self, uuid: str) -> None:
        ...
