"""SocketTransport: the consensus/cluster Transport over real TCP.

Plugs the rpc layer in behind the same seam LocalTransport implements, so
a TabletPeer group (and later the tserver/master daemons) runs unchanged
over loopback sockets — the reference's MiniCluster mode (real servers on
ephemeral loopback ports, mini_cluster.h:92-106).
"""

from __future__ import annotations

import threading

from yugabyte_db_tpu.rpc.interface import Transport, TransportError
from yugabyte_db_tpu.rpc.proxy import Proxy


class SocketTransport(Transport):
    """Routes ``send(dst_uuid, ...)`` through a Proxy to the address the
    uuid resolves to. The address book is shared and mutable (heartbeats /
    master location updates refresh it)."""

    def __init__(self, address_book: dict[str, tuple[str, int]] | None = None):
        self.address_book = address_book if address_book is not None else {}
        self._proxies: dict[str, Proxy] = {}
        self._lock = threading.Lock()

    def register(self, uuid: str, handler) -> None:
        raise NotImplementedError(
            "socket servers register via Messenger.listen; SocketTransport "
            "is the client side")

    def unregister(self, uuid: str) -> None:
        with self._lock:
            p = self._proxies.pop(uuid, None)
        if p is not None:
            p.close()

    def set_address(self, uuid: str, host: str, port: int) -> None:
        with self._lock:
            old = self.address_book.get(uuid)
            self.address_book[uuid] = (host, port)
            stale = self._proxies.pop(uuid, None) if old != (host, port) else None
        if stale is not None:
            stale.close()

    def _proxy_for(self, uuid: str) -> Proxy:
        with self._lock:
            p = self._proxies.get(uuid)
            if p is not None and not p.closed:
                return p
            addr = self.address_book.get(uuid)
        if addr is None:
            raise TransportError(f"no address for {uuid}")
        try:
            p = Proxy(*addr)
        except OSError as e:
            raise TransportError(f"connect to {uuid}@{addr} failed: {e}") from e
        with self._lock:
            existing = self._proxies.get(uuid)
            if existing is not None and not existing.closed:
                p.close()
                return existing
            self._proxies[uuid] = p
        return p

    def send(self, dst: str, method: str, payload, timeout: float = 5.0):
        from yugabyte_db_tpu.utils.resources import note_blocking

        note_blocking("rpc")
        try:
            return self._proxy_for(dst).call(method, payload, timeout=timeout)
        except (ConnectionError, TimeoutError, OSError) as e:
            raise TransportError(f"rpc to {dst} failed: {e}") from e

    def close(self) -> None:
        with self._lock:
            proxies = list(self._proxies.values())
            self._proxies.clear()
        for p in proxies:
            p.close()
