"""Messenger: reactor event loop + service dispatch.

Reference analog: src/yb/rpc/messenger.cc + reactor.cc — a small number of
event-loop threads own all sockets; complete inbound calls are handed to a
worker pool (service_pool.cc); responses are queued back to the reactor via
a wakeup pipe. ConnectionContext (connection_context.h) turns raw bytes
into calls and serializes responses, so CQL/RESP servers reuse this loop.
"""

from __future__ import annotations

import logging
import selectors
import socket
import struct
import threading
from concurrent.futures import ThreadPoolExecutor

from yugabyte_db_tpu.utils import codec

_LEN = struct.Struct("<I")
MAX_FRAME = 64 * 1024 * 1024


class RpcCallError(Exception):
    """Remote handler raised; carries the remote error message."""


class ConnectionContext:
    """Parses inbound bytes into calls; serializes responses.

    Subclass per wire protocol. ``feed(data)`` returns a list of parsed
    call objects; ``serialize(response)`` returns bytes to write back.

    ``ordered_responses``: foreign byte protocols (RESP, CQL without
    stream ids) match replies to requests by ORDER, so their handlers must
    run one-at-a-time per connection. The native context matches by call
    id and keeps full cross-call concurrency on one connection.
    """

    ordered_responses = True

    def feed(self, data: bytes) -> list:
        raise NotImplementedError

    def serialize(self, response) -> bytes:
        raise NotImplementedError


class RpcConnectionContext(ConnectionContext):
    """The native framed-codec protocol: [len][codec([call_id, method, body])]."""

    ordered_responses = False  # call ids pair requests with responses

    def __init__(self):
        self._buf = bytearray()

    def feed(self, data: bytes) -> list:
        self._buf.extend(data)
        calls = []
        while True:
            if len(self._buf) < _LEN.size:
                return calls
            (length,) = _LEN.unpack_from(self._buf, 0)
            if length > MAX_FRAME:
                raise ValueError(f"frame too large: {length}")
            end = _LEN.size + length
            if len(self._buf) < end:
                return calls
            payload = bytes(self._buf[_LEN.size:end])
            del self._buf[:end]
            call_id, method, body = codec.decode(payload)
            calls.append((call_id, method, body))

    def serialize(self, response) -> bytes:
        call_id, status, body = response
        payload = codec.encode([call_id, status, body])
        return _LEN.pack(len(payload)) + payload


class _Connection:
    def __init__(self, sock: socket.socket, context: ConnectionContext):
        self.sock = sock
        self.context = context
        self.out = bytearray()
        self.out_lock = threading.Lock()
        self.closed = False
        # Ordered-dispatch state (foreign protocols): a FIFO of parsed
        # calls drained by at most one worker at a time.
        self.call_queue: list = []
        self.draining = False


class Messenger:
    """Owns the reactor thread, listeners, and the service worker pool."""

    def __init__(self, name: str = "messenger", num_workers: int = 8):
        self.name = name
        self._sel = selectors.DefaultSelector()
        self._pool = ThreadPoolExecutor(max_workers=num_workers,
                                        thread_name_prefix=f"{name}-svc")
        # Dedicated per-service pools (reference: one ServicePool per
        # service, service_pool.cc). Without them a worker pool full of
        # user writes BLOCKED on majority replication starves the very
        # consensus RPCs that would unblock them.
        self._service_pools: list[tuple[str, ThreadPoolExecutor]] = []
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, ("wake", None))
        self._lock = threading.Lock()
        self._listeners: list[socket.socket] = []
        self._conns: set[_Connection] = set()
        self._running = True
        self._thread = threading.Thread(target=self._reactor_loop,
                                        name=f"reactor-{name}", daemon=True)
        self._thread.start()

    # -- listeners ----------------------------------------------------------
    def listen(self, host: str, port: int, handler,
               context_factory=RpcConnectionContext) -> tuple[str, int]:
        """Serve ``handler(method, body) -> body`` (for the native context)
        or protocol-defined calls (for foreign contexts) on host:port.
        Returns the bound address (port may be ephemeral 0)."""
        srv = socket.create_server((host, port), reuse_port=False)
        srv.setblocking(False)
        with self._lock:
            self._listeners.append(srv)
        self._sel.register(srv, selectors.EVENT_READ,
                           ("accept", (handler, context_factory)))
        self._wake()
        return srv.getsockname()[:2]

    # -- reactor ------------------------------------------------------------
    def _wake(self) -> None:
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass

    def _reactor_loop(self) -> None:
        while self._running:
            try:
                events = self._sel.select(timeout=0.2)
                for key, mask in events:
                    kind, data = key.data
                    if kind == "wake":
                        try:
                            self._wake_r.recv(4096)
                        except BlockingIOError:
                            pass
                        self._flush_writable()
                    elif kind == "accept":
                        self._accept(key.fileobj, *data)
                    elif kind == "conn":
                        self._on_conn_event(key.fileobj, data, mask)
            except Exception:  # a dead reactor silently stops ALL rpc
                logging.getLogger(__name__).exception(
                    "reactor %s: event dispatch failed", self.name)
        # shutdown: close everything
        for srv in self._listeners:
            try:
                srv.close()
            except OSError:
                pass
        for conn in list(self._conns):
            self._close_conn(conn)

    def _accept(self, srv, handler, context_factory) -> None:
        try:
            sock, _ = srv.accept()
        except OSError:
            return
        sock.setblocking(False)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn = _Connection(sock, context_factory())
        conn.handler = handler
        with self._lock:
            self._conns.add(conn)
        self._sel.register(sock, selectors.EVENT_READ, ("conn", conn))

    def _on_conn_event(self, sock, conn: _Connection, mask) -> None:
        if mask & selectors.EVENT_READ:
            try:
                data = sock.recv(256 * 1024)
            except (BlockingIOError, InterruptedError):
                data = None
            except OSError:
                self._close_conn(conn)
                return
            if data == b"":
                self._close_conn(conn)
                return
            if data:
                try:
                    calls = conn.context.feed(data)
                except Exception:
                    self._close_conn(conn)
                    return
                if conn.context.ordered_responses:
                    # Replies pair with requests by order: serialize
                    # handler execution per connection.
                    with conn.out_lock:
                        conn.call_queue.extend(calls)
                        start_drain = calls and not conn.draining
                        if start_drain:
                            conn.draining = True
                    if start_drain:
                        self._pool.submit(self._drain_ordered, conn)
                else:
                    for call in calls:
                        self._pool_for(call[1]).submit(
                            self._dispatch, conn, call)
        if mask & selectors.EVENT_WRITE:
            self._try_write(conn)

    def _drain_ordered(self, conn: _Connection) -> None:
        while True:
            with conn.out_lock:
                if not conn.call_queue or conn.closed:
                    conn.draining = False
                    return
                call = conn.call_queue.pop(0)
            self._dispatch(conn, call)

    def _dispatch(self, conn: _Connection, call) -> None:
        """Worker-side: run the handler, enqueue the response.

        A handler with ``takes_conn = True`` receives the connection as
        its first argument — foreign protocols with server-push frames
        (Redis pubsub/monitor) address pushes via send_on(conn, ...)."""
        call_id, method, body = call
        try:
            if getattr(conn.handler, "takes_conn", False):
                result = conn.handler(conn, method, body)
            else:
                result = conn.handler(method, body)
            response = (call_id, "ok", result)
        except Exception as e:  # propagate as remote error
            response = (call_id, "error", f"{type(e).__name__}: {e}")
        try:
            out = conn.context.serialize(response)
        except Exception:
            self._close_conn(conn)
            return
        if out:
            self.send_on(conn, out)

    def add_service_pool(self, prefix: str, num_workers: int) -> None:
        """Route native-protocol methods starting with ``prefix`` onto a
        dedicated worker pool."""
        self._service_pools.append((prefix, ThreadPoolExecutor(
            max_workers=num_workers,
            thread_name_prefix=f"{self.name}-{prefix.rstrip('.')}")))

    def _pool_for(self, method) -> ThreadPoolExecutor:
        if self._service_pools and isinstance(method, str):
            for prefix, pool in self._service_pools:
                if method.startswith(prefix):
                    return pool
        return self._pool

    def send_on(self, conn: _Connection, data: bytes) -> None:
        """Queue bytes on a connection (thread-safe; used by workers and by
        foreign-protocol servers pushing frames)."""
        with conn.out_lock:
            conn.out.extend(data)
        self._wake()

    def _flush_writable(self) -> None:
        for conn in list(self._conns):
            with conn.out_lock:
                pending = bool(conn.out)
            if pending:
                self._try_write(conn)

    def _try_write(self, conn: _Connection) -> None:
        with conn.out_lock:
            if not conn.out or conn.closed:
                self._watch(conn, write=False)
                return
            try:
                # Bounded chunk: copy at most 256K per send, not the whole
                # pending buffer (a 4MB response would otherwise be O(n^2)).
                n = conn.sock.send(bytes(conn.out[:256 * 1024]))
                del conn.out[:n]
            except (BlockingIOError, InterruptedError):
                n = 0
            except OSError:
                self._close_conn(conn)
                return
            self._watch(conn, write=bool(conn.out))

    def _watch(self, conn: _Connection, write: bool) -> None:
        if conn.closed:
            return
        events = selectors.EVENT_READ | (selectors.EVENT_WRITE if write else 0)
        try:
            self._sel.modify(conn.sock, events, ("conn", conn))
        except (KeyError, ValueError, OSError):
            pass

    def _close_conn(self, conn: _Connection) -> None:
        if conn.closed:
            return
        conn.closed = True
        with self._lock:
            self._conns.discard(conn)
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass

    def shutdown(self) -> None:
        self._running = False
        self._wake()
        self._thread.join(timeout=5.0)
        self._pool.shutdown(wait=False, cancel_futures=True)
        for _prefix, pool in self._service_pools:
            pool.shutdown(wait=False, cancel_futures=True)
        self._sel.close()
        self._wake_r.close()
        self._wake_w.close()
