"""RPC framework: the single IO engine of every daemon.

Reference analog: src/yb/rpc/ — Messenger owning Reactor threads
(reactor.cc), Proxy for outbound calls (proxy.cc), ServicePool dispatching
inbound calls to worker threads (service_pool.cc), and the pluggable
ConnectionContext that lets the SAME server sockets carry foreign byte
protocols (CQL native protocol, RESP) next to the native framed-codec RPC
(cql_rpc.cc / redis_rpc.cc plug in exactly this way).

Wire format (native context): [u32 len][payload], payload =
codec.encode([call_id, method, body]) for requests and
[call_id, status, body] for responses — the spirit of the reference's
Hadoop-IPC-style framing (src/yb/rpc/README:25-33) with the framework's
own codec instead of protobuf.
"""

from yugabyte_db_tpu.rpc.messenger import (ConnectionContext, Messenger,
                                           RpcCallError, RpcConnectionContext)
from yugabyte_db_tpu.rpc.proxy import Proxy
from yugabyte_db_tpu.rpc.transport import SocketTransport

__all__ = ["Messenger", "Proxy", "ConnectionContext", "RpcConnectionContext",
           "RpcCallError", "SocketTransport"]
