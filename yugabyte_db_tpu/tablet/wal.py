"""The write-ahead log: segmented, group-committed, CRC-protected.

Reference analog: src/yb/consensus/log.{h,cc} — "this replicated consistent
log also plays the role of the WAL for the tablet" (consensus/README). The
log stores consensus records (term, index) with opaque payloads; it is the
ONLY durability mechanism (the storage engine never fsyncs its own WAL).

Format per segment file (``wal-<first_index>.seg``):
  repeated records: [u32 len][u32 crc32(payload)][payload]
  payload = codec.encode([term, index, ht, op_type, body])

Group commit: append() buffers; sync() writes+fsyncs once per batch —
callers (the tablet's operation pipeline / Raft) batch many operations per
sync, the reference's Log::AsyncAppend + TaskStream pattern.
"""

from __future__ import annotations

import os
import struct
import threading
import time
import zlib
from dataclasses import dataclass

from yugabyte_db_tpu.utils import codec
from yugabyte_db_tpu.utils.locking import guarded_by

_HEADER = struct.Struct("<II")


@dataclass(frozen=True, order=True)
class OpId:
    """Consensus operation id (term, index) — reference consensus.proto OpId."""

    term: int
    index: int

    @staticmethod
    def min() -> "OpId":
        return OpId(0, 0)


@dataclass
class LogEntry:
    op_id: OpId
    ht: int           # hybrid time of the operation
    op_type: str      # "write" | "no_op" | "change_config" | ...
    body: object      # codec-encodable payload
    committed: int = 0  # commit index known when this entry was appended
    # ``committed`` mirrors the reference piggybacking the committed op id on
    # every replicate message (consensus.proto UpdateConsensus); bootstrap
    # replays only entries known committed and hands the tail back to
    # consensus as pending (tablet_bootstrap.cc).

    def to_record(self) -> list:
        """The single canonical record layout (WAL payload == wire format)."""
        return [self.op_id.term, self.op_id.index, self.ht,
                self.op_type, self.body, self.committed]

    @staticmethod
    def from_record(rec: list) -> "LogEntry":
        return LogEntry(OpId(rec[0], rec[1]), rec[2], rec[3], rec[4],
                        rec[5] if len(rec) > 5 else 0)


@guarded_by("_lock", "_file", "_file_path", "_file_size", "_buffer",
            "_buffer_bytes", "last_appended")
class Log:
    """A tablet's durable log of replicated operations."""

    def __init__(self, wal_dir: str, segment_bytes: int = 8 * 1024 * 1024,
                 fsync: bool = True):
        self.wal_dir = wal_dir
        self.segment_bytes = segment_bytes
        self.fsync = fsync
        # Appends are serialized by the caller (one writer: the consensus
        # pipeline); this lock only guards append/sync/gc/truncate racing
        # each other (e.g. flush-triggered GC vs an append).
        self._lock = threading.RLock()
        os.makedirs(wal_dir, exist_ok=True)
        self._file = None
        self._file_path = None
        self._file_size = 0
        self._buffer: list[bytes] = []
        self._buffer_bytes = 0
        self.last_appended = OpId.min()
        # Recover last_appended from the tail segments only (newest first);
        # the full log is decoded once, by bootstrap replay, not here.
        for path in reversed(self.segment_paths()):
            entries, _ = self._read_segment(path, 0)
            if entries:
                self.last_appended = entries[-1].op_id
                break

    # -- segments ----------------------------------------------------------
    def segment_paths(self) -> list[str]:
        names = sorted(n for n in os.listdir(self.wal_dir)
                       if n.startswith("wal-") and n.endswith(".seg"))
        return [os.path.join(self.wal_dir, n) for n in names]

    def _open_segment_locked(self, first_index: int) -> None:
        self._close_file_locked()
        name = f"wal-{first_index:020d}.seg"
        self._file_path = os.path.join(self.wal_dir, name)
        self._file = open(self._file_path, "ab")
        self._file_size = self._file.tell()

    def _close_file_locked(self) -> None:
        # A closed segment must be durable before sync() reports the group
        # durable: roll-over flushes buffered records into the OLD segment,
        # and the subsequent sync() only fsyncs the NEW file — without this
        # fsync, entries in the closed segment would count toward Raft
        # majority while still sitting in the page cache.
        if self._file is not None:
            self._file.flush()
            if self.fsync:
                # Justified hold: roll-over happens mid-append, so the old
                # segment must be durable before the lock drops — a sync()
                # racing past would only fsync the NEW file.
                from yugabyte_db_tpu.utils.resources import note_blocking
                note_blocking("fsync")
                # yb-lint: disable=iholds/lock-across-blocking
                os.fsync(self._file.fileno())
            self._file.close()
            self._file = None

    # -- append ------------------------------------------------------------
    def append(self, entry: LogEntry) -> None:
        """Buffer an entry; durable after the next sync()."""
        with self._lock:
            self._append_locked(entry)

    def _append_locked(self, entry: LogEntry) -> None:
        if entry.op_id <= self.last_appended:
            raise ValueError(
                f"non-monotonic append {entry.op_id} after {self.last_appended}")
        payload = codec.encode(entry.to_record())
        rec = _HEADER.pack(len(payload), zlib.crc32(payload)) + payload
        if self._file is None or \
                self._file_size + self._buffer_bytes >= self.segment_bytes:
            # Roll BEFORE buffering this record so the new segment's name
            # (its first index) truthfully covers it — GC relies on that.
            self._flush_buffer_locked()
            self._open_segment_locked(entry.op_id.index)
        self._buffer.append(rec)
        self._buffer_bytes += len(rec)
        self.last_appended = entry.op_id

    def _flush_buffer_locked(self) -> None:
        if not self._buffer or self._file is None:
            return
        data = b"".join(self._buffer)
        self._buffer.clear()
        self._buffer_bytes = 0
        self._file.write(data)
        self._file_size += len(data)

    def sync(self) -> None:
        """Group commit: flush buffered records and fsync the segment."""
        from yugabyte_db_tpu.utils.fault_injection import (FaultInjected,
                                                           maybe_fault)

        if maybe_fault("fault.wal_sync_failed"):
            raise FaultInjected("injected WAL sync failure")
        from yugabyte_db_tpu.utils.metrics import observe_wal_sync_ms
        from yugabyte_db_tpu.utils.watchdog import watchdog

        # Standing stall check (reference: kernel_stack_watchdog.h):
        # a wedged fsync surfaces as a flagged stall, not silence.
        with watchdog().watch("wal.sync", threshold_s=2.0):
            start = time.monotonic()
            f = None
            with self._lock:
                if self._file is None and self._buffer:
                    self._open_segment_locked(max(1, self.last_appended.index))
                self._flush_buffer_locked()
                f = self._file
                if f is not None:
                    # flush() stays under the lock: BufferedWriter is not
                    # thread-safe against a concurrent _flush_buffer_locked.
                    f.flush()
            if f is not None and self.fsync:
                try:
                    from yugabyte_db_tpu.utils.resources import note_blocking
                    note_blocking("fsync")
                    # fsync OUTSIDE the lock — the group-commit shape:
                    # appenders keep buffering into the next group while
                    # this one reaches disk.
                    os.fsync(f.fileno())
                except (ValueError, OSError):
                    # A concurrent roll-over closed this segment after we
                    # snapshotted it; _close_file_locked flushed AND fsynced
                    # it before closing, so the group is durable anyway.
                    pass
            observe_wal_sync_ms((time.monotonic() - start) * 1e3)

    # -- read / replay -----------------------------------------------------
    def read_all(self, min_index: int = 0):
        """Yield entries with index >= min_index, tolerating a torn tail
        (a partial last record after a crash is dropped, matching WAL
        recovery semantics)."""
        for path in self.segment_paths():
            entries, clean = self._read_segment(path, min_index)
            yield from entries
            if not clean:
                return  # stop replay at first torn/corrupt record globally

    @staticmethod
    def _read_segment(path: str, min_index: int) -> tuple[list, bool]:
        """-> (entries, clean). clean=False on torn tail or CRC mismatch."""
        with open(path, "rb") as f:
            data = f.read()
        pos = 0
        out: list[LogEntry] = []
        while pos + _HEADER.size <= len(data):
            length, crc = _HEADER.unpack_from(data, pos)
            start = pos + _HEADER.size
            end = start + length
            if end > len(data):
                return out, False  # torn tail
            payload = data[start:end]
            if zlib.crc32(payload) != crc:
                return out, False  # corruption: stop at last good record
            entry = LogEntry.from_record(codec.decode(payload))
            if entry.op_id.index >= min_index:
                out.append(entry)
            pos = end
        return out, True

    # -- truncation --------------------------------------------------------
    def truncate_after(self, last_kept_index: int) -> int:
        """Drop every entry with index > last_kept_index (a follower erasing
        a conflicting suffix on divergence from a new leader). Returns the
        number of entries dropped. Rewrites only the segments that contain
        dropped entries; earlier segments are untouched."""
        with self._lock:
            return self._truncate_after_locked(last_kept_index)

    def _truncate_after_locked(self, last_kept_index: int) -> int:
        self.sync()
        self._close_file_locked()
        dropped = 0
        # Newest-first so a crash mid-truncation always leaves a CONTIGUOUS
        # prefix (a tail segment is fully gone before an earlier one is
        # rewritten) — recovery then sees a valid, if longer, log.
        for path in reversed(self.segment_paths()):
            entries, _ = self._read_segment(path, 0)
            if not entries or entries[-1].op_id.index <= last_kept_index:
                continue
            kept = [e for e in entries if e.op_id.index <= last_kept_index]
            dropped += len(entries) - len(kept)
            if kept:
                tmp = path + ".tmp"
                with open(tmp, "wb") as f:
                    for e in kept:
                        payload = codec.encode(e.to_record())
                        f.write(_HEADER.pack(len(payload),
                                             zlib.crc32(payload)) + payload)
                    f.flush()
                    # Justified hold: divergence repair rewrites segments in
                    # place; an append interleaving with the rewrite would
                    # corrupt the log, so the whole repair stays locked.
                    # This is the rare follower-conflict path, never the
                    # steady-state write path.
                    # yb-lint: disable=iholds/lock-across-blocking
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            else:
                os.unlink(path)
        self.last_appended = OpId.min()
        for path in reversed(self.segment_paths()):
            entries, _ = self._read_segment(path, 0)
            if entries:
                self.last_appended = entries[-1].op_id
                break
        return dropped

    # -- GC ----------------------------------------------------------------
    def gc(self, min_retained_index: int) -> int:
        """Delete whole segments whose every entry index < min_retained_index.
        Returns segments deleted. (Reference: Log::GC after flushed frontier
        advances.)"""
        with self._lock:
            return self._gc_locked(min_retained_index)

    def _gc_locked(self, min_retained_index: int) -> int:
        paths = self.segment_paths()
        deleted = 0
        # A segment's name carries its first index; a segment can be deleted
        # when the NEXT segment's first index is still <= min_retained.
        for i, path in enumerate(paths[:-1]):  # never delete the active tail
            nxt_first = int(os.path.basename(paths[i + 1])[4:-4])
            if nxt_first <= min_retained_index:
                os.unlink(path)
                deleted += 1
            else:
                break
        return deleted

    def close(self) -> None:
        self.sync()
        with self._lock:
            self._close_file_locked()
