"""Tablet: one shard — storage engine + WAL + MVCC + operation pipeline.

Reference analog: src/yb/tablet/tablet.{h,cc} and the operation lifecycle of
operations/operation_driver.h:70-95 (Prepare -> Replicate(WAL) -> Apply),
with TabletBootstrap (tablet_bootstrap.cc) replaying the log over the
flushed frontier on restart.

Single-node consensus note: this tablet runs under a LocalConsensus-style
pipeline (append + fsync locally == replicated); consensus.RaftConsensus
drives the same hooks for replicated tablets — the tablet only sees
``replicate(entry) -> op_id`` and ``apply(entry)``.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass

from yugabyte_db_tpu.models.schema import Schema
from yugabyte_db_tpu.storage.engine import make_engine
from yugabyte_db_tpu.storage.row_version import RowVersion
from yugabyte_db_tpu.storage.scan_spec import ScanResult, ScanSpec
# Canonical row wire codec (shared with RPC payloads).
from yugabyte_db_tpu.storage.wire import decode_rows as _decode_rows
from yugabyte_db_tpu.storage.wire import encode_rows as _encode_rows
from yugabyte_db_tpu.tablet.mvcc import MvccManager
from yugabyte_db_tpu.tablet.wal import Log, LogEntry, OpId
from yugabyte_db_tpu.utils.hybrid_time import HybridClock, HybridTime


@dataclass
class TabletMetadata:
    """The tablet superblock (reference: tablet_metadata.cc RaftGroupMetadata)."""

    tablet_id: str
    table_name: str
    schema: Schema
    partition_start: int
    partition_end: int
    engine: str = "cpu"              # tablet_storage_engine option
    flushed_op_index: int = 0        # WAL replay frontier
    # Secondary indexes the leader maintains on writes:
    # [{"name", "column", "index_table"}] (reference: the IndexMap the
    # tablet consults in UpdateQLIndexes, tablet.cc:1015).
    indexes: list = None
    # Sealed for a tablet split: every data RPC answers "tablet_split"
    # and the frozen state has been (or is being) forked into the
    # children. Persisted so a crash between the seal and the parent's
    # deletion cannot resurrect a writable parent — the seal entry
    # itself may sit below the flushed replay frontier by then
    # (reference: the kSplit tablet-data state of tablet_metadata.h).
    split_sealed: bool = False

    def __post_init__(self):
        if self.indexes is None:
            self.indexes = []

    def save(self, path: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({
                "tablet_id": self.tablet_id,
                "table_name": self.table_name,
                "schema": self.schema.to_dict(),
                "partition_start": self.partition_start,
                "partition_end": self.partition_end,
                "engine": self.engine,
                "flushed_op_index": self.flushed_op_index,
                "indexes": self.indexes,
                "split_sealed": self.split_sealed,
            }, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    @staticmethod
    def load(path: str) -> "TabletMetadata":
        with open(path) as f:
            d = json.load(f)
        return TabletMetadata(
            d["tablet_id"], d["table_name"], Schema.from_dict(d["schema"]),
            d["partition_start"], d["partition_end"], d["engine"],
            d["flushed_op_index"], d.get("indexes") or [],
            d.get("split_sealed", False),
        )


class Tablet:
    """A live tablet. Thread-safe: writes serialize through the apply lock
    (the reference serializes through the single-threaded Preparer +
    per-tablet apply token)."""

    def __init__(self, meta: TabletMetadata, data_root: str,
                 clock: HybridClock | None = None,
                 engine_options: dict | None = None,
                 fsync: bool = True, consensus_managed: bool = False):
        self.meta = meta
        self.dir = os.path.join(data_root, meta.tablet_id)
        os.makedirs(self.dir, exist_ok=True)
        self.meta_path = os.path.join(self.dir, "tablet-meta.json")
        self.clock = clock or HybridClock()
        self.mvcc = MvccManager(self.clock)
        opts = dict(engine_options or {})
        opts.setdefault("data_dir", os.path.join(self.dir, "runs"))
        # unique per live instance: one process may host several replicas
        # of the same tablet id (MiniCluster)
        opts.setdefault("tracker_name", f"{meta.tablet_id}:{id(self):x}")
        self.engine = make_engine(meta.engine, meta.schema, opts)
        self.log = Log(os.path.join(self.dir, "wal"), fsync=fsync)
        self._write_lock = threading.Lock()
        self._term = 1
        # consensus_managed: a RaftConsensus owns the log (appends, term
        # tracking) and drives applies through apply_replicated(); the
        # tablet's own write() path is disabled.
        self.consensus_managed = consensus_managed
        self._last_index = self.log.last_appended.index
        self._applied_index = meta.flushed_op_index
        # Transaction machinery: every tablet can hold intents
        # (participant); tablets of the status table additionally run the
        # coordinator state machine. Both rebuild from sidecar snapshots +
        # WAL replay exactly like the engine.
        from yugabyte_db_tpu.tablet.retryable import RetryableRequests
        from yugabyte_db_tpu.txn.coordinator import (TXN_STATUS_TABLE,
                                                     TransactionCoordinator)
        from yugabyte_db_tpu.txn.participant import TransactionParticipant

        self.participant = TransactionParticipant(self.dir)
        self.retryable = RetryableRequests(self.dir)
        self.coordinator = (TransactionCoordinator(self.dir)
                            if meta.table_name == TXN_STATUS_TABLE else None)
        self.bootstrap()

    # -- bootstrap ----------------------------------------------------------
    def bootstrap(self) -> None:
        """Replay WAL entries newer than the flushed frontier into the
        engine (reference: TabletBootstrap::PlaySegments). Under consensus
        management only entries known committed (from the piggybacked commit
        watermark) are applied — the uncommitted tail is left for Raft to
        commit or truncate (tablet_bootstrap.cc hands those back as
        pending)."""
        # Replay happens before the peer serves, but holding the write
        # lock keeps the _last_index/_applied_index invariant uniform
        # (and a re-bootstrap racing a stray write is then safe too).
        with self._write_lock:
            all_entries = list(self.log.read_all(0))
            if self.consensus_managed:
                committed_frontier = max((e.committed for e in all_entries),
                                         default=0)
                # Consensus reuses this single decode pass for its entry
                # cache (avoids a second full-log read at startup).
                self.bootstrap_entries = all_entries
            else:
                committed_frontier = None  # local-consensus: all durable
            replayed = 0
            for entry in all_entries:
                self._last_index = max(self._last_index, entry.op_id.index)
                self.clock.update(HybridTime(entry.ht))
                if entry.op_id.index <= self.meta.flushed_op_index:
                    continue  # already durable in the flushed runs
                if committed_frontier is not None and \
                        entry.op_id.index > committed_frontier:
                    continue
                self._apply_entry_body(entry)
                if entry.op_type == "write":
                    replayed += 1
                self._applied_index = max(self._applied_index,
                                          entry.op_id.index)
            self._replayed_on_bootstrap = replayed

    def _apply_write_body(self, entry) -> None:
        """Apply a "write" entry. Bodies are one of: an encoded row BLOCK
        (bytes, storage.rowblock — the native write plane's zero-copy
        form), the legacy raw row list, or {"rows": <either>, "rid":
        [client_id, request_id]} — the rid is recorded for exactly-once
        retry dedup (retryable.py)."""
        # Leader fast path: the writer attached its already-stamped
        # RowVersions to the in-memory entry (tablet_peer.write), so the
        # leader's apply skips the wire round trip; followers and WAL
        # replay decode from the body. (Block bodies need no such
        # attachment: every replica ingests the block natively.)
        decoded = getattr(entry, "decoded_rows", None)
        body = entry.body
        rows = body["rows"] if isinstance(body, dict) else body
        if isinstance(rows, (bytes, bytearray)):
            self.engine.apply_block(rows)
        else:
            self.engine.apply(decoded if decoded is not None
                              else _decode_rows(rows))
        if isinstance(body, dict):
            rid = body.get("rid")
            if rid:
                self.retryable.record(rid[0], rid[1], entry.ht)

    def _apply_txn_op(self, entry) -> None:
        """Apply transaction ops (intents / commit-apply / abort-remove /
        coordinator status records) from the log."""
        if entry.op_type == "intents":
            self.participant.apply_intents_op(entry.body)
        elif entry.op_type == "apply_intents":
            self.participant.apply_commit_op(entry.body, self.engine.apply)
        elif entry.op_type == "remove_intents":
            self.participant.apply_remove_op(entry.body)
        elif entry.op_type == "txn_status" and self.coordinator is not None:
            self.coordinator.apply_status_op(entry.body)

    # -- snapshots (reference: Tablet::CreateCheckpoint, tablet.h:348,
    # via rocksdb hard-link checkpoints, checkpoint.cc:53; cluster RPCs
    # in backup.proto TabletSnapshotOp CREATE/RESTORE/DELETE) ------------
    def snapshots_dir(self) -> str:
        return os.path.join(self.dir, "snapshots")

    def list_snapshots(self) -> list[str]:
        d = self.snapshots_dir()
        if not os.path.isdir(d):
            return []
        return sorted(n for n in os.listdir(d) if not n.endswith(".tmp"))

    def _apply_snapshot_op(self, op_type: str, body: dict) -> None:
        """Apply a replicated snapshot op. Runs at a fixed log position on
        every replica, so each replica's snapshot captures the same
        logical state; all three ops are idempotent across WAL replays
        (a re-created snapshot re-captures the same position's state
        because replay applies entries in order)."""
        import shutil as _shutil

        sid = body["snapshot_id"]
        if "/" in sid or sid.startswith("."):
            raise ValueError(f"bad snapshot id {sid!r}")
        sdir = os.path.join(self.snapshots_dir(), sid)
        if op_type == "create_snapshot":
            if os.path.exists(sdir):
                return  # replayed: already captured at this position
            self.engine.flush()  # runs now hold every applied write
            tmp = sdir + ".tmp"
            _shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp)
            for path in self.engine.persist.files:
                dst = os.path.join(tmp, os.path.basename(path))
                try:
                    os.link(path, dst)  # hard link: cheap, immutable file
                except OSError:
                    _shutil.copy2(path, dst)
            with open(os.path.join(tmp, "snapshot-meta.json"), "w") as f:
                import json as _json

                _json.dump({"schema": self.meta.schema.to_dict(),
                            "ht": self.clock.now().value}, f)
            os.rename(tmp, sdir)
        elif op_type == "restore_snapshot":
            if not os.path.isdir(sdir):
                # Leaders validate existence before replicating; a miss
                # here (non-consensus misuse, manual dir removal) must
                # not wedge the apply stage.
                if not self.consensus_managed:
                    raise RuntimeError(f"snapshot {sid} not found")
                import logging

                logging.getLogger(__name__).error(
                    "tablet %s: restore of missing snapshot %s skipped",
                    self.meta.tablet_id, sid)
                return
            from yugabyte_db_tpu.storage.merge import merge_entry_streams
            from yugabyte_db_tpu.storage.run_io import load_run

            runs = [load_run(os.path.join(sdir, n))
                    for n in sorted(os.listdir(sdir))
                    if n.startswith("run-")]
            entries = list(merge_entry_streams(runs)) if runs else []
            self.engine.restore_entries(entries)
        else:  # delete_snapshot
            _shutil.rmtree(sdir, ignore_errors=True)

    def dump_snapshots(self) -> dict:
        """Every snapshot's logical content (for remote bootstrap: a
        re-seeded replica must be able to apply later restore_snapshot
        entries, so the snapshots travel with the storage payload)."""
        import json as _json

        from yugabyte_db_tpu.storage.merge import merge_entry_streams
        from yugabyte_db_tpu.storage.run_io import load_run

        out = {}
        for sid in self.list_snapshots():
            sdir = os.path.join(self.snapshots_dir(), sid)
            runs = [load_run(os.path.join(sdir, n))
                    for n in sorted(os.listdir(sdir))
                    if n.startswith("run-")]
            meta = {}
            mpath = os.path.join(sdir, "snapshot-meta.json")
            if os.path.exists(mpath):
                with open(mpath) as f:
                    meta = _json.load(f)
            out[sid] = {"entries": list(merge_entry_streams(runs))
                        if runs else [], "meta": meta}
        return out

    @staticmethod
    def install_snapshots(tablet_dir: str, snapshots: dict) -> None:
        """Materialize dumped snapshots into a (re)built tablet dir."""
        import json as _json

        from yugabyte_db_tpu.storage.run_io import RunPersistence

        for sid, blob in (snapshots or {}).items():
            sdir = os.path.join(tablet_dir, "snapshots", sid)
            os.makedirs(sdir, exist_ok=True)
            if blob["entries"]:
                RunPersistence(sdir).save_new(blob["entries"])
            with open(os.path.join(sdir, "snapshot-meta.json"), "w") as f:
                _json.dump(blob.get("meta") or {}, f)

    def snapshot_op(self, op_type: str, snapshot_id: str) -> None:
        """Direct snapshot op (non-consensus tablets; replicated tablets
        go through TabletPeer.replicate_txn_op)."""
        if self.consensus_managed:
            raise RuntimeError("snapshot ops go through the TabletPeer")
        with self._write_lock:
            self._apply_snapshot_op(op_type, {"snapshot_id": snapshot_id})

    def alter_schema(self, new_schema) -> None:
        """Direct schema change (non-consensus tablets; replicated
        tablets go through TabletPeer.alter_schema)."""
        if self.consensus_managed:
            raise RuntimeError("schema changes go through the TabletPeer")
        with self._write_lock:
            self._apply_alter_schema({"schema": new_schema.to_dict()})

    # -- write path ---------------------------------------------------------
    def write(self, rows: list[RowVersion],
              if_not_exists: bool = False) -> HybridTime:
        """Apply one write operation (a batch of row versions, HT-stamped
        here). Durable (WAL fsync) before apply, matching the reference's
        Replicate-before-Apply invariant.

        ``if_not_exists``: atomic uniqueness enforcement — the existence
        check runs under the same write lock as the apply, so concurrent
        duplicate inserts cannot both pass (the SQL INSERT contract;
        reference: the read-modify-write inside the tablet,
        cql_operation.cc QLWriteOperation)."""
        if self.consensus_managed:
            raise RuntimeError("writes must go through the TabletPeer")
        with self._write_lock:
            if if_not_exists:
                from yugabyte_db_tpu.utils.status import AlreadyPresent

                for r in rows:
                    if self.current_row_values(r.key) is not None:
                        raise AlreadyPresent(
                            "duplicate key value violates unique "
                            "constraint")
            rows = [self.resolve_increments(r) for r in rows]
            ht = self.clock.now()
            self.mvcc.add_pending(ht)
            try:
                stamped = [
                    RowVersion(r.key, ht=ht.value, tombstone=r.tombstone,
                               liveness=r.liveness, columns=r.columns,
                               expire_ht=r.resolve_ttl(ht.value),
                               write_id=i)
                    for i, r in enumerate(rows)
                ]
                self._last_index += 1
                op_id = OpId(self._term, self._last_index)
                # Justified hold (here and the sync below): the standalone
                # (non-consensus) tablet is single-writer BY DESIGN —
                # append order must match apply order into the engine, and
                # flush() swaps the memtable under this same lock. The
                # replicated path acks at commit with pipelined apply
                # instead; this path serves tests and single-node tools.
                # yb-lint: disable=iholds/lock-across-blocking
                self.log.append(LogEntry(op_id, ht.value, "write",
                                         _encode_rows(stamped)))
                # yb-lint: disable=iholds/lock-across-blocking
                self.log.sync()  # group commit point (batching comes from callers)
                self.engine.apply(stamped)
                self._applied_index = op_id.index
            except BaseException:
                self.mvcc.aborted(ht)
                raise
            self.mvcc.replicated(ht)
            return ht

    def apply_replicated(self, entry) -> None:
        """Apply one committed log entry (the Raft apply stage; reference:
        Tablet::ApplyRowOperations, tablet.cc:667). Rows carry their hybrid
        time already (stamped by the leader before replication). Runs under
        the write lock: engines have no internal locking, and flush() swaps
        the memtable under the same lock — an apply racing that swap would
        vanish while the replay frontier still advances past it."""
        with self._write_lock:
            self._apply_entry_body(entry)
            self._applied_index = max(self._applied_index, entry.op_id.index)
            self._last_index = max(self._last_index, entry.op_id.index)
        self.clock.update(HybridTime(entry.ht))

    def _apply_entry_body(self, entry) -> None:
        """The ONE dispatch for committed entries — the Raft apply stage
        and WAL-replay bootstrap both route through it, so no op type can
        apply live but silently vanish on replay."""
        if entry.op_type == "write":
            self._apply_write_body(entry)
        elif entry.op_type == "alter_schema":
            self._apply_alter_schema(entry.body)
        elif entry.op_type == "split_seal":
            self._apply_split_seal()
        elif entry.op_type in ("create_snapshot", "restore_snapshot",
                               "delete_snapshot"):
            self._apply_snapshot_op(entry.op_type, entry.body)
        else:
            self._apply_txn_op(entry)

    def _apply_split_seal(self) -> None:
        """Apply the split-seal entry: freeze this tablet for its split.
        Runs at one log position on every replica, so each rejects data
        RPCs from the same point in the write sequence; everything at or
        below the seal is captured by the parent's fork snapshot, and
        everything after it is bounced to the clients with the
        ``tablet_split`` code to retry against the children. Idempotent
        across WAL replays; persisted immediately so a post-flush crash
        cannot replay the tablet back into service unsealed."""
        if self.meta.split_sealed:
            return
        self.meta.split_sealed = True
        self.meta.save(self.meta_path)

    def _apply_alter_schema(self, body: dict) -> None:
        """Adopt a replicated schema change (idempotent across replays:
        versions only move forward). Reference: the AlterSchema operation
        (tablet.cc AlterSchema / ChangeMetadataOperation)."""
        from yugabyte_db_tpu.models.schema import Schema

        new_schema = Schema.from_dict(body["schema"])
        if new_schema.version <= self.meta.schema.version:
            return  # stale replay
        self.meta.schema = new_schema
        self.meta.save(self.meta_path)
        self.engine.alter_schema(new_schema)

    # -- read path ----------------------------------------------------------
    def read_time(self) -> HybridTime:
        return self.mvcc.safe_time()

    def _read_fence(self, read_ht: int, deadline=None) -> None:
        """MVCC read fence for the pipelined-apply write path: a write is
        acked at COMMIT and applies asynchronously, with its pending HT
        holding safe time below it until the apply lands. A read at or
        above that HT must wait for the drain or it would miss an acked
        write. Best-effort on timeout: proceeding matches pre-pipelining
        behaviour, and apply lag is already bounded by write backpressure
        (--raft_max_inflight_ops)."""
        timeout = 10.0
        if deadline is not None:
            timeout = max(0.0, min(timeout, deadline.remaining()))
        self.mvcc.wait_for_safe_time(HybridTime(read_ht), timeout=timeout)

    def scan(self, spec: ScanSpec, deadline=None) -> ScanResult:
        self._read_fence(spec.read_ht, deadline)
        return self.engine.scan_batch([spec], deadline=deadline)[0]

    def scan_wire(self, spec: ScanSpec, fmt: str = "cql", deadline=None):
        """Scan serving serialized protocol bytes (storage page server;
        reference: rows_data serialized once at the tablet,
        src/yb/common/ql_rowblock.h:66)."""
        self._read_fence(spec.read_ht, deadline)
        return self.engine.scan_batch_wire([spec], fmt,
                                           deadline=deadline)[0]

    def scan_many(self, specs: list[ScanSpec],
                  deadline=None) -> list[ScanResult]:
        """One engine batch for many scans (the multi-key read RPC's
        storage hop — point gets share the bloom/merge machinery).
        ``deadline`` is the RPC edge's propagated budget (utils.retry)."""
        if specs:
            self._read_fence(max(s.read_ht for s in specs), deadline)
        return self.engine.scan_batch(specs, deadline=deadline)

    def scan_wire_many(self, specs: list[ScanSpec], fmt: str = "cql",
                       deadline=None):
        """One engine batch of wire-serialized scans — the batched read
        RPC's storage hop for the native request-batch serving path."""
        if specs:
            self._read_fence(max(s.read_ht for s in specs), deadline)
        return self.engine.scan_batch_wire(specs, fmt, deadline=deadline)

    def point_serve(self, keys: list[bytes], read_ht: int, col_id: int):
        """Native batch point-value serve. None unless the whole visible
        state is servable from the native memtable: pending transaction
        intents live outside the engine, so any intent on this tablet
        forces the general read path (which resolves them)."""
        if self.participant.txns:
            return None
        self._read_fence(read_ht)
        return self.engine.point_serve(keys, read_ht, col_id)

    # -- maintenance --------------------------------------------------------
    def flush(self) -> None:
        """Flush memtable to a durable run, advance the replay frontier,
        GC fully-flushed WAL segments. Transaction state (intents,
        coordinator records) snapshots alongside — it too stops being
        recoverable from the log once segments below the frontier go."""
        with self._write_lock:
            self.engine.flush()
            self.participant.snapshot()
            self.retryable.snapshot()
            if self.coordinator is not None:
                self.coordinator.snapshot()
            self.meta.flushed_op_index = self._applied_index
            # Justified hold (save + sync): the flush barrier — the replay
            # frontier may only advance (and WAL segments drop) while no
            # write can move the memtable out from under the captured
            # snapshot. Flush is rare maintenance, not the serving path.
            # yb-lint: disable=iholds/lock-across-blocking
            self.meta.save(self.meta_path)
            # yb-lint: disable=iholds/lock-across-blocking
            self.log.sync()
            self.log.gc(self.meta.flushed_op_index + 1)

    def resolve_increments(self, row: RowVersion) -> RowVersion:
        """Turn pending counter deltas into absolute column values by
        reading the row's current state — callers MUST hold the lock
        that serializes writes to this tablet (the write lock here, the
        tserver's intent-admission lock on the replicated path), which
        is what makes concurrent increments atomic."""
        if not row.increments:
            return row
        by_id = {c.col_id: c.name for c in self.meta.schema.value_columns}
        cur = self.current_row_values(row.key) or {}
        columns = dict(row.columns)
        for cid, delta in row.increments.items():
            name = by_id.get(cid)
            if name is None:
                # stale client schema (column dropped/recreated): refuse
                # rather than append a value under a retired column id
                raise ValueError(f"unknown column id {cid} in increment")
            base = cur.get(name)
            columns[cid] = (base if isinstance(base, int) else 0) + delta
        return RowVersion(row.key, ht=row.ht, tombstone=row.tombstone,
                          liveness=row.liveness, columns=columns,
                          expire_ht=row.expire_ht, ttl_us=row.ttl_us,
                          write_id=row.write_id)

    def current_row_values(self, key: bytes) -> dict | None:
        """Merged value-column values of one row by name (None if the row
        doesn't exist) — the old-state read of index maintenance."""
        names = [c.name for c in self.meta.schema.value_columns]
        spec = ScanSpec(lower=key, upper=key + b"\x00",
                        read_ht=self.read_time().value,
                        projection=names, limit=1)
        res = self.engine.scan(spec)
        if not res.rows:
            return None
        return dict(zip(names, res.rows[0]))

    # -- transaction support -------------------------------------------------
    def latest_committed_ht(self, key: bytes) -> int:
        """Newest committed version ht of a row key (0 if none) — the
        first-committer-wins conflict check input."""
        eng = self.engine
        best = 0
        mem = getattr(eng, "memtable", None)
        if mem is not None:
            for v in mem.versions(key):
                best = max(best, v.ht)
        for run in getattr(eng, "runs", []):
            crun = getattr(run, "crun", run)  # TpuRun wraps; CpuRun is flat
            versions = (crun.find_versions(key) if hasattr(crun, "find_versions")
                        else crun.get(key))
            for v in versions:
                best = max(best, v.ht)
        return best

    def compact(self, history_cutoff_ht: int = 0) -> None:
        self.engine.compact(history_cutoff_ht)

    def stats(self) -> dict:
        s = self.engine.stats()
        s.update({
            "tablet_id": self.meta.tablet_id,
            "last_index": self._last_index,
            "applied_index": self._applied_index,
            "flushed_op_index": self.meta.flushed_op_index,
            "wal_segments": len(self.log.segment_paths()),
        })
        return s

    def close(self) -> None:
        self.log.close()
        self.engine.close()

    # -- lifecycle helpers ---------------------------------------------------
    @staticmethod
    def create(meta: TabletMetadata, data_root: str, **kwargs) -> "Tablet":
        tdir = os.path.join(data_root, meta.tablet_id)
        os.makedirs(tdir, exist_ok=True)
        meta.save(os.path.join(tdir, "tablet-meta.json"))
        return Tablet(meta, data_root, **kwargs)

    @staticmethod
    def open(tablet_id: str, data_root: str, **kwargs) -> "Tablet":
        meta = TabletMetadata.load(
            os.path.join(data_root, tablet_id, "tablet-meta.json"))
        return Tablet(meta, data_root, **kwargs)


