"""MvccManager: tracks in-flight operations and the safe read time.

Reference analog: src/yb/tablet/mvcc.h:46 — operations register their hybrid
time before applying; the safe time is the largest HT such that no operation
with a smaller-or-equal HT can still arrive. Reads pick read_ht <= safe time
so results are stable (no write can later commit "in the past" of a read).
"""

from __future__ import annotations

import threading

from yugabyte_db_tpu.utils.hybrid_time import HybridClock, HybridTime


class MvccManager:
    def __init__(self, clock: HybridClock):
        self.clock = clock
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: list[int] = []      # in-flight operation HTs (sorted-ish)
        self._last_replicated = 0

    def add_pending(self, ht: HybridTime) -> None:
        with self._lock:
            self._pending.append(ht.value)

    def replicated(self, ht: HybridTime) -> None:
        with self._cond:
            try:
                self._pending.remove(ht.value)
            except ValueError:
                raise ValueError(f"replicated unknown ht {ht}")
            if ht.value > self._last_replicated:
                self._last_replicated = ht.value
            self._cond.notify_all()

    def aborted(self, ht: HybridTime) -> None:
        with self._cond:
            self._pending.remove(ht.value)
            self._cond.notify_all()

    def safe_time(self) -> HybridTime:
        """Largest HT at which a read sees a stable snapshot.

        With pending ops: just below the smallest pending HT. Without: the
        clock's current bound, observed WITHOUT issuing a timestamp (any
        future write still gets a strictly larger HT from the same clock).
        """
        with self._lock:
            if self._pending:
                return HybridTime(min(self._pending) - 1)
        return self.clock.max_global_now()

    def wait_for_safe_time(self, ht: HybridTime, timeout: float = 10.0) -> bool:
        """Block until safe_time() >= ht (for follower/snapshot reads)."""
        deadline_ok = True
        with self._cond:
            def safe_enough():
                if self._pending and min(self._pending) <= ht.value:
                    return False
                return True
            deadline_ok = self._cond.wait_for(safe_enough, timeout=timeout)
        return deadline_ok

    @property
    def last_replicated_ht(self) -> HybridTime:
        with self._lock:
            return HybridTime(self._last_replicated)
