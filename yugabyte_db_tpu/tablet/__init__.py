"""The replicated tablet: one shard of a table.

Reference analog: src/yb/tablet (Tablet, TabletPeer, MvccManager, operation
pipeline, TabletBootstrap) + src/yb/consensus/log* (the WAL). A tablet owns
its storage engine behind the pluggable seam (tablet.h:648), an MVCC manager
for safe-time reads (mvcc.h:46), and its durability comes from the
replicated log — the storage engine has no WAL of its own, matching the
reference's disabled-rocksdb-WAL design (consensus/README).
"""

from yugabyte_db_tpu.tablet.wal import Log, LogEntry, OpId
from yugabyte_db_tpu.tablet.mvcc import MvccManager
from yugabyte_db_tpu.tablet.tablet import Tablet, TabletMetadata


def __getattr__(name):
    # Lazy: tablet_peer pulls in consensus, which itself builds on the WAL
    # defined here — importing it eagerly would be a cycle.
    if name == "TabletPeer":
        from yugabyte_db_tpu.tablet.tablet_peer import TabletPeer
        return TabletPeer
    raise AttributeError(name)
