"""RetryableRequests: exactly-once semantics for client write retries.

Reference analog: src/yb/consensus/retryable_requests.h:34 — each write
carries a (client id, request id); the tablet remembers applied ids so a
client retry after a lost response returns the ORIGINAL outcome instead
of double-applying. The registry is rebuilt deterministically: request
ids ride inside the replicated write entries, are recorded at APPLY
time on every replica, snapshot to a sidecar at flush, and replay from
the WAL tail on restart — exactly the intents/coordinator discipline.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

# Per-client retention: retries arrive within seconds; 4096 outstanding
# ids per client is far beyond any batcher's in-flight window
# (reference: bounded by the client's running-request watermark).
MAX_IDS_PER_CLIENT = 4096


class RetryableRequests:
    def __init__(self, tablet_dir: str):
        self._lock = threading.Lock()
        self.path = os.path.join(tablet_dir, "retryable.bin")
        # client_id -> OrderedDict[request_id -> ht] (insertion = age)
        self.clients: dict[str, OrderedDict] = {}
        self.load()

    def seen(self, client_id: str, request_id: int) -> int | None:
        """The original write's hybrid time, or None if unseen."""
        with self._lock:
            reqs = self.clients.get(client_id)
            if reqs is None:
                return None
            return reqs.get(request_id)

    def record(self, client_id: str, request_id: int, ht: int) -> None:
        """Called at apply time (replicated, deterministic on every
        replica)."""
        with self._lock:
            reqs = self.clients.setdefault(client_id, OrderedDict())
            reqs[request_id] = ht
            while len(reqs) > MAX_IDS_PER_CLIENT:
                reqs.popitem(last=False)

    # -- persistence (sidecar at flush, like intents) -----------------------
    def load(self) -> None:
        from yugabyte_db_tpu.utils import codec

        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            d = codec.decode(f.read())
        with self._lock:
            for cid, pairs in d.items():
                self.clients[cid] = OrderedDict(pairs)

    def dump(self) -> dict:
        with self._lock:
            return {cid: list(reqs.items())
                    for cid, reqs in self.clients.items()}

    def snapshot(self) -> None:
        from yugabyte_db_tpu.utils import codec

        d = self.dump()
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(codec.encode(d))
            f.flush()
            # Justified hold: snapshot() runs under the tablet's flush
            # barrier (write + maintenance locks) by contract — the WAL
            # frontier may not advance past state that isn't durable yet.
            # yb-lint: disable=iholds/lock-across-blocking
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def stats(self) -> dict:
        with self._lock:
            return {"clients": len(self.clients),
                    "request_ids": sum(len(r) for r in
                                       self.clients.values())}
