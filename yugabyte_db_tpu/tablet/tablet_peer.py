"""TabletPeer: one replica of one tablet — Tablet storage + RaftConsensus.

Reference analog: src/yb/tablet/tablet_peer.{h,cc} — owns the tablet, the
consensus instance and the log; routes writes through the Raft pipeline
(Prepare -> Replicate -> Apply, operations/operation_driver.h:70-95) and
gates reads on leadership + leases.

Read semantics: leader replicas serve reads at the MVCC safe time while
holding the majority-ack lease; follower replicas can serve explicitly
requested stale reads at their last-applied state (the reference's
follower reads are opt-in the same way).
"""

from __future__ import annotations

import os
import threading

from yugabyte_db_tpu.consensus.metadata import ConsensusMetadata, RaftConfig
from yugabyte_db_tpu.consensus.raft import (NotLeader, RaftConsensus,
                                            RaftOptions)
from yugabyte_db_tpu.storage.row_version import RowVersion
from yugabyte_db_tpu.utils.trace import TRACE
from yugabyte_db_tpu.storage.scan_spec import ScanResult, ScanSpec
from yugabyte_db_tpu.tablet.tablet import (Tablet, TabletMetadata,
                                           _encode_rows)
from yugabyte_db_tpu.utils.hybrid_time import HybridClock, HybridTime
from yugabyte_db_tpu.utils.status import TabletSplit


def _key_hash(key: bytes) -> int:
    """Partition hash of an encoded DocKey: the big-endian uint16 after
    the hash tag byte (models/encoding.py encode_doc_key_prefix).
    Range-partitioned keys (no hash tag) all map to 0 — they live in a
    single full-range tablet and are never split."""
    import struct

    from yugabyte_db_tpu.models.encoding import TAG_HASH
    if len(key) >= 3 and key[0] == TAG_HASH:
        return struct.unpack(">H", key[1:3])[0]
    return 0


class TabletPeer:
    def __init__(self, node_uuid: str, meta: TabletMetadata, data_root: str,
                 transport, initial_peers: list[str],
                 clock: HybridClock | None = None,
                 engine_options: dict | None = None,
                 fsync: bool = True, raft_opts: RaftOptions | None = None):
        self.node_uuid = node_uuid
        self.tablet = Tablet(meta, data_root, clock=clock,
                             engine_options=engine_options, fsync=fsync,
                             consensus_managed=True)
        cmeta = ConsensusMetadata(
            os.path.join(self.tablet.dir, "consensus-meta.json"),
            node_uuid, RaftConfig(list(initial_peers)))
        self.raft = RaftConsensus(
            meta.tablet_id, cmeta, self.tablet.log, transport,
            self.tablet.clock, self._apply, raft_opts,
            initial_applied_index=self.tablet._applied_index,
            preloaded_entries=self.tablet.bootstrap_entries)
        del self.tablet.bootstrap_entries  # one-shot handoff
        self._maintenance_lock = threading.Lock()
        # Serializes conflict-check + intent replication: without it two
        # concurrent writers to the same key both pass the check and both
        # plant intents (the reference holds its SharedLockManager batch
        # across the whole doc-write, shared_lock_manager.h).
        self._intent_lock = threading.Lock()
        # (client_id, request_id) -> (op_id, ht) of an APPENDED but not
        # yet applied write: a racing retry waits on the original entry
        # instead of appending a duplicate (the admission lock no longer
        # spans the majority wait). Two-phase writes (ts.write_admit /
        # ts.write_sync) leave entries registered past apply; admissions
        # purge applied ones lazily (_purge_inflight_rids).
        self._inflight_rids: dict = {}
        # op_id -> pending HybridTime of writes THIS replica admitted
        # into MVCC. Resolution rides the Raft outcome itself: the apply
        # stage calls mvcc.replicated, a log-suffix truncation calls
        # mvcc.aborted — so a pending HT can never leak (no waiter
        # required; clients may disappear after admission).
        self._mvcc_unresolved: dict = {}
        self.raft.on_entries_truncated = self._on_entries_truncated
        # Monotone count of data ops (writes + scans) this replica
        # served — reported raw in the master heartbeat, which turns
        # successive samples into the per-tablet op RATE the split
        # manager and leader balancer feed on. Bumped without a lock
        # (a lost increment only shaves the rate estimate).
        self.ops_seen = 0
        # Set (under _intent_lock) the moment a split seal is being
        # appended: admissions behind the flag bounce with TabletSplit
        # BEFORE entering the log, so every admitted write sits below
        # the seal entry and is captured by the fork snapshot.
        self._split_sealing = False

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        self.raft.start()

    def shutdown(self) -> None:
        self.raft.shutdown()
        self.tablet.close()

    @property
    def tablet_id(self) -> str:
        return self.tablet.meta.tablet_id

    def is_leader(self) -> bool:
        return self.raft.is_leader()

    # -- write path ---------------------------------------------------------
    def write(self, rows: list[RowVersion], timeout=10.0,
              client_id: str | None = None,
              request_id: int | None = None) -> HybridTime:
        """Leader-side write: stamp a hybrid time, replicate through Raft,
        return once majority-durable (commit-time ack; apply is pipelined).

        A (client_id, request_id) pair makes the write EXACTLY-ONCE under
        client retries: a replayed id returns the original write's hybrid
        time without re-applying (retryable_requests.h:34). Admission
        (dedup check + stamp + append) and completion (majority wait)
        are split so the tserver's intent-admission lock covers ONLY
        admission — concurrent writes to one tablet pipeline through one
        replication round instead of serializing on full commit latency
        (reference: leader-side batching, src/yb/tablet/preparer.cc).
        Writes also require leader_ready() — an own-term entry applied —
        which guarantees every prior-term entry (including any original
        of a retried id) has already applied into the dedup registry
        before a new leader accepts writes."""
        admitted = self.write_admit(rows, client_id, request_id)
        return self.write_finish(admitted, timeout)

    def write_admit(self, rows: list[RowVersion],
                    client_id: str | None = None,
                    request_id: int | None = None):
        """Admission phase. The CALLER serializes admissions for one
        tablet (the tserver holds the intent-admission lock across this
        call). Returns an opaque token for write_finish."""
        if not (self.raft.is_leader() and self.raft.leader_ready()):
            raise NotLeader(self.node_uuid, self.raft.leader_uuid())
        if self._split_sealing or self.tablet.meta.split_sealed:
            raise TabletSplit(self.tablet_id)
        self._purge_inflight_rids()
        if any(r.increments for r in rows):
            # increments resolve under the tserver's intent-admission
            # lock (the serialization point); reaching here unresolved
            # would silently drop the delta
            raise ValueError("unresolved counter increments; route the "
                             "write through the tserver handler")
        rid = None
        rid_key = None
        if client_id is not None and request_id is not None:
            prev = self.tablet.retryable.seen(client_id, request_id)
            if prev is not None:
                return ("dup", HybridTime(prev))  # replay: original result
            rid_key = (client_id, request_id)
            inflight = self._inflight_rids.get(rid_key)
            if inflight is not None:
                # A retry raced its in-flight original (timeout + resend):
                # wait on the ORIGINAL entry, never append a second copy.
                return ("inflight",) + inflight
            rid = [client_id, request_id]
        ht = self.tablet.clock.now()
        TRACE("write: %d row(s) stamped at ht=%d", len(rows), ht.value)
        stamped = [
            RowVersion(r.key, ht=ht.value, tombstone=r.tombstone,
                       liveness=r.liveness, columns=r.columns,
                       expire_ht=r.resolve_ttl(ht.value), write_id=i)
            for i, r in enumerate(rows)
        ]
        self.tablet.mvcc.add_pending(ht)
        try:
            body = ({"rows": _encode_rows(stamped), "rid": rid}
                    if rid else _encode_rows(stamped))
            entry = self.raft.append_leader(
                "write", body, ht=ht.value, decoded_rows=stamped,
                on_append=lambda e: self._mvcc_unresolved.__setitem__(
                    e.op_id, ht))
            TRACE("write: appended %d.%d", entry.op_id.term,
                  entry.op_id.index)
        except BaseException:
            self.tablet.mvcc.aborted(ht)  # never entered the log
            raise
        if rid_key is not None:
            self._inflight_rids[rid_key] = (entry.op_id, ht)
        return ("appended", entry.op_id, ht, rid_key)

    def write_admit_block(self, block: bytes,
                          client_id: str | None = None,
                          request_id: int | None = None):
        """Admission phase of the native write plane: same contract as
        write_admit, but the batch arrives as an encoded row block
        (storage.rowblock) and is commit-stamped by ONE native pass —
        no per-row Python objects anywhere (reference: the C++
        leader-side batch assembly of src/yb/tablet/preparer.cc). The
        block then rides the WAL body and Raft replication verbatim."""
        from yugabyte_db_tpu.storage import rowblock

        if not (self.raft.is_leader() and self.raft.leader_ready()):
            raise NotLeader(self.node_uuid, self.raft.leader_uuid())
        if self._split_sealing or self.tablet.meta.split_sealed:
            raise TabletSplit(self.tablet_id)
        self._purge_inflight_rids()
        rid = None
        rid_key = None
        if client_id is not None and request_id is not None:
            prev = self.tablet.retryable.seen(client_id, request_id)
            if prev is not None:
                return ("dup", HybridTime(prev))  # replay: original result
            rid_key = (client_id, request_id)
            inflight = self._inflight_rids.get(rid_key)
            if inflight is not None:
                return ("inflight",) + inflight
            rid = [client_id, request_id]
        ht = self.tablet.clock.now()
        TRACE("write: block stamped at ht=%d", ht.value)
        stamped = rowblock.stamp_block(block, ht.value)
        self.tablet.mvcc.add_pending(ht)
        try:
            body = {"rows": stamped, "rid": rid} if rid else stamped
            entry = self.raft.append_leader(
                "write", body, ht=ht.value,
                on_append=lambda e: self._mvcc_unresolved.__setitem__(
                    e.op_id, ht))
        except BaseException:
            self.tablet.mvcc.aborted(ht)  # never entered the log
            raise
        if rid_key is not None:
            self._inflight_rids[rid_key] = (entry.op_id, ht)
        return ("appended", entry.op_id, ht, rid_key)

    def _purge_inflight_rids(self) -> None:
        """Drop in-flight rid entries whose entry has applied (their
        outcome now lives in the durable dedup registry) — two-phase
        writes never pop their own entry. Amortized: only sweeps once
        the registry has accumulated a few entries."""
        if len(self._inflight_rids) <= 8:
            return
        applied = self.raft._applied_index
        for k, (op_id, _ht) in list(self._inflight_rids.items()):
            if op_id.index <= applied:
                self._inflight_rids.pop(k, None)

    def write_finish(self, admitted, timeout=10.0) -> HybridTime:
        """Completion phase: wait for COMMIT (majority-durable), not
        apply — the pipelined-apply ack point. The apply stage drains
        committed entries asynchronously behind the MVCC read fence
        (the pending HT added at admission holds safe time below this
        write until it applies), so an acked-but-unapplied write is
        never visible to a read and never lost (majority-durable WAL
        entries replay on restart). Safe to run OUTSIDE the admission
        lock. MVCC resolution is NOT the waiter's job — the apply stage
        / truncation hooks resolve the pending HT whether or not anyone
        is waiting. ``timeout`` is float seconds or a utils.retry
        Deadline. The rid registration is NOT popped on success: the
        entry may not have reached the durable dedup registry yet (that
        happens at apply) — _purge_inflight_rids sweeps it once
        applied."""
        kind = admitted[0]
        if kind == "dup":
            return admitted[1]
        if kind == "inflight":
            _k, op_id, ht = admitted
            self.raft.wait_committed(op_id, timeout)
            return ht
        _k, op_id, ht, rid_key = admitted
        try:
            self.raft.wait_committed(op_id, timeout)
        except NotLeader:
            if rid_key is not None:
                self._inflight_rids.pop(rid_key, None)
            raise
        return ht

    # -- transaction write path ---------------------------------------------
    def write_intents(self, txn_id: str, status_tablet: str, priority: int,
                      read_ht: int, rows: list[RowVersion],
                      timeout: float = 10.0) -> int:
        """Write provisional rows for a transaction: conflict-check on the
        leader, then replicate an "intents" entry (reference:
        Tablet::AcquireLocksAndPerformDocOperations + the intents write of
        PrepareTransactionWriteBatch, src/yb/docdb/docdb.h:169). Raises
        txn.participant.IntentConflict on conflict.

        Returns the entry's hybrid time. The caller MUST propagate it to
        the transaction's commit request: the coordinator ratchets its
        clock past every intent write before choosing commit_ht, so a
        pinned read that advanced this tablet's clock (and therefore this
        entry's ht) past its read time can never be overtaken by the
        commit (the HLC-propagation half of the safe-time contract)."""
        if not (self.raft.is_leader() and self.raft.leader_ready()):
            raise NotLeader(self.node_uuid, self.raft.leader_uuid())
        from yugabyte_db_tpu.storage.wire import encode_rows
        with self._intent_lock:
            self.tablet.participant.check_conflicts(
                txn_id, [r.key for r in rows], read_ht,
                self.tablet.latest_committed_ht)
            body = {
                "txn_id": txn_id, "status_tablet": status_tablet,
                "priority": priority, "read_ht": read_ht,
                "rows": encode_rows(rows),
            }
            # Tracked in MVCC like a write: a pinned read below this
            # entry's ht must wait for the apply, or it would miss the
            # intents entirely (they'd land after its intent-gate check).
            # Justified hold: conflict check and log position must be
            # atomic — two conflicting transactions checked against the
            # same intent table could otherwise both replicate. Same
            # shape as the reference's intent-admission serialization.
            # yb-lint: disable=iholds/lock-across-blocking
            return self.replicate_txn_op("intents", body, timeout,
                                         track_mvcc=True)

    def alter_schema(self, new_schema, timeout: float = 10.0) -> None:
        """Replicate a schema change through this tablet's Raft log so
        every replica adopts it at the same log position (reference:
        AlterSchema as a ChangeMetadataOperation through consensus)."""
        self.replicate_txn_op("alter_schema",
                              {"schema": new_schema.to_dict()}, timeout)

    def replicate_txn_op(self, op_type: str, body: dict,
                         timeout: float = 10.0, ht: int | None = None,
                         track_mvcc: bool = False) -> int:
        """Replicate one transaction op through this tablet's Raft log and
        wait until applied locally. Returns the entry hybrid time."""
        if not self.raft.is_leader():
            raise NotLeader(self.node_uuid, self.raft.leader_uuid())
        if ht is None:
            ht = self.tablet.clock.now().value
        hto = HybridTime(ht)
        if track_mvcc:
            self.tablet.mvcc.add_pending(hto)
            on_append = lambda e: self._mvcc_unresolved.__setitem__(  # noqa: E731
                e.op_id, hto)
        else:
            on_append = None
        try:
            entry = self.raft.append_leader(op_type, body, ht=ht,
                                            on_append=on_append)
        except BaseException:
            if track_mvcc:
                self.tablet.mvcc.aborted(hto)
            raise
        self.raft.wait_applied(entry.op_id, timeout)
        return ht

    def _apply(self, entry) -> None:
        self.tablet.apply_replicated(entry)
        # Resolve the MVCC pending of a write this replica admitted —
        # AFTER the apply, so a reader released by the advancing safe
        # time always sees the applied rows.
        ht = self._mvcc_unresolved.pop(entry.op_id, None)
        if ht is not None:
            self.tablet.mvcc.replicated(ht)

    def _on_entries_truncated(self, entries) -> None:
        """A truncated suffix is a definite abort for every entry this
        replica admitted: release their MVCC pendings and drop their
        in-flight rid registrations (a retry must re-append)."""
        dropped_ids = set()
        for e in entries:
            dropped_ids.add(e.op_id)
            ht = self._mvcc_unresolved.pop(e.op_id, None)
            if ht is not None:
                self.tablet.mvcc.aborted(ht)
        if self._inflight_rids:
            for k, (op_id, _ht) in list(self._inflight_rids.items()):
                if op_id in dropped_ids:
                    self._inflight_rids.pop(k, None)

    # -- read path ----------------------------------------------------------
    def read_time(self) -> HybridTime:
        return self.tablet.mvcc.safe_time()

    def scan(self, spec: ScanSpec, allow_stale: bool = False,
             deadline=None) -> ScanResult:
        """Serve a scan. Leader-with-lease only, unless the caller opted
        into stale follower reads. ``deadline`` is the RPC edge's
        propagated budget (utils.retry.Deadline)."""
        if not allow_stale:
            if not self.raft.is_leader():
                raise NotLeader(self.node_uuid, self.raft.leader_uuid())
            if not self.raft.has_lease():
                raise NotLeader(self.node_uuid, None)
        TRACE("scan: read_ht=%d", spec.read_ht)
        res = self.tablet.scan(spec, deadline=deadline)
        TRACE("scan: %d row(s), %d scanned", len(res.rows),
              res.rows_scanned)
        return res

    def scan_wire(self, spec: ScanSpec, fmt: str = "cql",
                  allow_stale: bool = False, deadline=None):
        """Wire-serialized scan (leader-with-lease gate as scan)."""
        if not allow_stale:
            if not self.raft.is_leader():
                raise NotLeader(self.node_uuid, self.raft.leader_uuid())
            if not self.raft.has_lease():
                raise NotLeader(self.node_uuid, None)
        return self.tablet.scan_wire(spec, fmt, deadline=deadline)

    def scan_many(self, specs, allow_stale: bool = False, deadline=None):
        """Batched scans under ONE leader-with-lease gate (the
        multi-key read RPC)."""
        if not allow_stale:
            if not self.raft.is_leader():
                raise NotLeader(self.node_uuid, self.raft.leader_uuid())
            if not self.raft.has_lease():
                raise NotLeader(self.node_uuid, None)
        return self.tablet.scan_many(specs, deadline=deadline)

    def scan_wire_many(self, specs, fmt: str = "cql",
                       allow_stale: bool = False, deadline=None):
        """Batched wire-serialized scans under ONE leader-with-lease
        gate (the native request-batch serving path's read RPC)."""
        if not allow_stale:
            if not self.raft.is_leader():
                raise NotLeader(self.node_uuid, self.raft.leader_uuid())
            if not self.raft.has_lease():
                raise NotLeader(self.node_uuid, None)
        return self.tablet.scan_wire_many(specs, fmt, deadline=deadline)

    def point_serve(self, keys, read_ht: int, col_id: int,
                    allow_stale: bool = False):
        """Batched native point-value serve under one leader-with-lease
        gate. None when the tablet cannot answer natively."""
        if not allow_stale:
            if not self.raft.is_leader():
                raise NotLeader(self.node_uuid, self.raft.leader_uuid())
            if not self.raft.has_lease():
                raise NotLeader(self.node_uuid, None)
        return self.tablet.point_serve(keys, read_ht, col_id)

    # -- maintenance --------------------------------------------------------
    def flush(self) -> None:
        with self._maintenance_lock:
            # Pipelined apply: a write is acked at commit, so drain the
            # apply stage first or the flush could capture a memtable
            # missing acked rows (and advance no frontier for them).
            self.raft.wait_apply_drained()
            self.tablet.flush()
            # Everything at/below the flushed frontier is durable in the
            # engine's runs: bound the in-memory Raft entry cache too.
            # Lagging peers past the eviction floor are re-seeded via
            # remote bootstrap.
            self.raft.evict_cache(self.tablet.meta.flushed_op_index)

    def snapshot_for_bootstrap(self) -> dict:
        """Consistent remote-bootstrap payload pieces: flush, dump the
        runs, and capture the log tail under ONE maintenance-lock hold —
        a concurrent flush between the dump and the tail capture would
        otherwise evict entries out of both."""
        with self._maintenance_lock:
            self.raft.wait_apply_drained()
            self.tablet.flush()
            self.raft.evict_cache(self.tablet.meta.flushed_op_index)
            entries = self.tablet.engine.dump_entries()
            tail = self.raft.log_tail_snapshot()
            flushed = self.tablet.meta.flushed_op_index
        return {"entries": entries, "tail": tail,
                "flushed_op_index": flushed}

    # -- tablet splitting ----------------------------------------------------
    def split_key_hash(self) -> int | None:
        """The partition hash of this tablet's median RESIDENT key —
        the split point a size/load-triggered split divides the range
        at (reference: the mid-key the reference asks the largest SST
        for in TabletServiceAdminImpl::GetSplitKey). Flushes first so
        the memtable is counted. None when the resident keys span
        fewer than two distinct hash codes (nothing to divide)."""
        with self._maintenance_lock:
            self.raft.wait_apply_drained()
            self.tablet.flush()
            entries = self.tablet.engine.dump_entries()
        hashes = sorted({_key_hash(key) for key, _vers in entries})
        if len(hashes) < 2:
            return None
        # Split ABOVE the median hash: keys at the median stay in the
        # low child, so both children are non-empty by construction.
        return hashes[len(hashes) // 2]

    def split_seal(self, timeout=10.0) -> None:
        """Seal this tablet for a split: replicate a ``split_seal``
        entry through its own Raft log. The sealing flag flips under
        the intent-admission lock BEFORE the append, so every admitted
        write sits at a lower log index than the seal — once the seal
        entry applies (in order, behind them all), the tablet's state
        is the complete frozen prefix the children are forked from.
        Idempotent; leader-only."""
        if not (self.raft.is_leader() and self.raft.leader_ready()):
            raise NotLeader(self.node_uuid, self.raft.leader_uuid())
        with self._intent_lock:
            if self.tablet.meta.split_sealed:
                return
            self._split_sealing = True
        try:
            self.replicate_txn_op("split_seal", {}, timeout)
        except BaseException:
            # Replication failed (leader change / timeout): don't leave
            # this replica wedged rejecting writes for a seal that may
            # never commit — the flag re-arms if the master retries here.
            with self._intent_lock:
                if not self.tablet.meta.split_sealed:
                    self._split_sealing = False
            raise

    def split_fork_rows(self, lower: int, upper: int) -> list:
        """Range-clamped frozen rows of a SEALED parent: every
        (key, versions) entry whose partition hash falls in
        [lower, upper), tombstones and all — the seed payload for one
        child. The seal already froze the log, so after the apply
        drain + flush the dump is the tablet's final state."""
        if not self.tablet.meta.split_sealed:
            raise RuntimeError(
                f"tablet {self.tablet_id} is not sealed for split")
        with self._maintenance_lock:
            self.raft.wait_apply_drained()
            self.tablet.flush()
            entries = self.tablet.engine.dump_entries()
        return [(key, vers) for key, vers in entries
                if lower <= _key_hash(key) < upper]

    def split_seed(self, rows: list[RowVersion], timeout=10.0,
                   chunk: int = 1024) -> int:
        """Seed a CHILD tablet from its parent's forked rows: the child
        LEADER replicates ordinary ``write`` entries through the
        child's OWN Raft log (chunked), so every child replica builds
        the identical seeded state from the log — seeding each replica
        from its local parent copy would diverge, the replicas sit at
        different apply points. Rows keep their original hybrid times
        (the bodies are encoded pre-stamped), so MVCC visibility,
        TTL expiry and tombstone ordering match the parent exactly."""
        n = 0
        for i in range(0, len(rows), chunk):
            batch = rows[i:i + chunk]
            self.replicate_txn_op("write", _encode_rows(batch), timeout,
                                  track_mvcc=True)
            n += len(batch)
        return n

    def compact(self, history_cutoff_ht: int = 0) -> None:
        with self._maintenance_lock:
            self.tablet.compact(history_cutoff_ht)

    def stats(self) -> dict:
        s = self.tablet.stats()
        s["raft"] = self.raft.stats()
        return s
