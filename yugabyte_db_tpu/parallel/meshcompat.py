"""JAX mesh-API compatibility seam for the sharded read path.

The mesh program targets two generations of the JAX SPMD API:

- ``jax.shard_map`` + ``jax.lax.pcast`` (the varying-types world,
  jax >= 0.6): collective-carrying loop bodies must mark replicated
  initial carries as device-varying before the ``fori_loop`` traces.
- ``jax.experimental.shard_map.shard_map`` (0.4.x): no varying types;
  replication is checked structurally, and ``check_rep=False`` is
  required for bodies whose per-device control flow diverges (the row
  page program's ``while_loop`` runs a different trip count per shard).

Every shard_map in parallel/ goes through :func:`shard_map` /
:func:`varying` below, so the one version split lives here.  When
NEITHER API exists the mesh path is unavailable: :func:`mesh_unavailable`
returns the reason string, callers fall back to the per-tablet host
path, and the test suite's capability probe (tests/conftest.py) skips
the mesh rigs with that reason instead of failing them.
"""

from __future__ import annotations

import jax

_UNAVAILABLE: str | None = None
_SHARD_MAP = None
_MODERN = hasattr(jax, "shard_map")

if _MODERN:
    _SHARD_MAP = jax.shard_map
else:
    try:
        from jax.experimental.shard_map import shard_map as _SHARD_MAP
    except ImportError:  # pragma: no cover - no known-good API present
        _UNAVAILABLE = ("jax %s has neither jax.shard_map nor "
                        "jax.experimental.shard_map" % jax.__version__)


def mesh_unavailable() -> str | None:
    """None when a usable shard_map exists, else the reason string."""
    return _UNAVAILABLE


def shard_map(body, mesh, in_specs, out_specs):
    """Version-portable shard_map.

    The experimental API defaults to replication CHECKING, which rejects
    per-device-divergent control flow (and psum-of-loop-carry shapes)
    that the typed API expresses with varying types — disable it there;
    the modern API needs no flag.
    """
    if _UNAVAILABLE is not None:
        raise RuntimeError(_UNAVAILABLE)
    if _MODERN:
        return _SHARD_MAP(body, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs)
    return _SHARD_MAP(body, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


def varying(x, axes):
    """Mark a replicated value as device-varying over ``axes`` before it
    becomes a collective-carrying loop carry.  Identity on the 0.4.x
    API, where no varying type system exists."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to="varying")
    return x
