"""Sharded multi-tablet aggregate: shard_map over a ("t", "b") mesh.

Layout: every tablet's ColumnarRun planes are stacked to [T, B, R, ...] and
placed with NamedSharding(P("t", "b")) — tablets split over the "t" mesh
axis (data parallel; the reference's unit of sharding, one tablet per
scanning thread at best), blocks of each tablet split over "b" (sequence
parallel; no reference analog — a tablet scan there is strictly
single-threaded). Each device fori_loops scan windows over its local
(tablet, block-range) shard reusing ops.scan.scan_window, folds exact
per-block aggregate partials into carry-safe accumulators, and the final
combine rides ICI collectives:

- count / n / fsum: ``psum`` over both axes;
- integer sums: base-2^16 digit vectors (int32) with a carry-propagation
  step per window so digits never overflow int32, ``psum``-ed then
  recombined host-side in arbitrary precision — bit-exact at any scale;
- min/max: two-int32-plane lexicographic maxima via a two-step collective
  (pmax on the high plane, then pmax on the tie-masked low plane).

Group/window invariant: key groups never span blocks (storage.columnar
build invariant), so any contiguous block range — in particular a device's
"b"-shard — is segment-complete and partials add up exactly.

Reference analog of the combine being replaced: the client-side merge of
per-tablet partial aggregates (src/yb/yql/cql/ql/exec/eval_aggr.cc,
src/yb/docdb/pgsql_operation.cc:473).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from yugabyte_db_tpu.models.schema import Schema
from yugabyte_db_tpu.ops import scan as dscan
from yugabyte_db_tpu.utils.jitting import compile_contract
from yugabyte_db_tpu.ops.agg_fold import (agg_init, check_limb_bound,
                                          finalize, fold_window, lower_aggs,
                                          pred_literal)
from yugabyte_db_tpu.ops.scan import I32_MAX, I32_MIN
from yugabyte_db_tpu.storage.columnar import ColumnarRun
from yugabyte_db_tpu.storage.residency import device_nbytes, hbm_cache
from yugabyte_db_tpu.storage.scan_spec import ScanResult, ScanSpec
from yugabyte_db_tpu.utils import planes as PL
from yugabyte_db_tpu.utils.memtracker import root_tracker


# -- host-side assembly ------------------------------------------------------

class ShardedTablets:
    """Stacked, mesh-sharded device residency for T tablets' single runs.

    Each tablet contributes one ColumnarRun (compact first); runs are padded
    to a common block count divisible by mesh_b * window and stacked to
    [T, B, R, ...]. Dummy all-invalid tablets pad T to a multiple of mesh_t.
    """

    def __init__(self, schema: Schema, runs: list[ColumnarRun], mesh: Mesh,
                 window_blocks: int = 8):
        if not runs:
            raise ValueError("need at least one run")
        R = runs[0].R
        if any(r.R != R for r in runs):
            raise ValueError("all runs must share rows_per_block")
        self.schema = schema
        self.mesh = mesh
        self.K = window_blocks
        self.R = R
        mesh_t = mesh.shape["t"]
        mesh_b = mesh.shape["b"]
        self.T = len(runs)
        self.runs = runs
        pad_t = (-self.T) % mesh_t
        chunk = mesh_b * window_blocks
        Bmax = max(r.B for r in runs)
        self.B = Bmax + ((-Bmax) % chunk)
        self.Bl = self.B // mesh_b
        if self.Bl % window_blocks:
            raise AssertionError("local block count not a window multiple")

        stacked = self._stack(runs, pad_t)
        spec_tb = P("t", "b")
        # Mesh placement must shard, not cache: plane-group residency for
        # sharded arrays is accounted (and pinned) via add_external below.
        self.arrays = jax.tree.map(
            lambda a: jax.device_put(a, NamedSharding(mesh, spec_tb)),  # yb-lint: disable=ijax/unmanaged-device-put
            stacked)
        self.padded_T = self.T + pad_t
        # The stacked mesh arrays live outside the demand-upload path but
        # inside the same HBM budget: account them as a pinned external
        # entry so /memz, /metrics and eviction pressure see them.
        self._res_key = hbm_cache().add_external(
            self, device_nbytes(self.arrays),
            root_tracker().child("device").child("sharded"), "sharded_mesh")

    def close(self) -> None:
        """Release the mesh arrays' residency accounting (the arrays
        themselves free when the last reference dies)."""
        if self._res_key is not None:
            hbm_cache().invalidate(self._res_key)
            self._res_key = None
        self.arrays = None

    def _stack(self, runs, pad_t):
        B, R = self.B, self.R
        T = len(runs) + pad_t

        def alloc(shape, dtype, fill=0):
            return np.full((T, B) + shape, fill, dtype=dtype)

        out = {
            "valid": alloc((R,), bool, False),
            # pad rows are their own groups so they never join a real one
            "group_start": alloc((R,), bool, True),
            "tomb": alloc((R,), bool, False),
            "live": alloc((R,), bool, False),
            "ht_hi": alloc((R,), np.int32),
            "ht_lo": alloc((R,), np.int32),
            "exp_hi": alloc((R,), np.int32),
            "exp_lo": alloc((R,), np.int32),
            "cols": {},
        }
        for c in self.schema.value_columns:
            nplanes = runs[0].cols[c.col_id].cmp_planes.shape[-1]
            entry = {
                "set": alloc((R,), bool, False),
                "isnull": alloc((R,), bool, False),
                "cmp": alloc((R, nplanes), np.int32),
            }
            if runs[0].cols[c.col_id].arith is not None:
                entry["arith"] = alloc((R,), np.float32)
            out["cols"][c.col_id] = entry
        for t, run in enumerate(runs):
            b = run.B
            out["valid"][t, :b] = run.valid
            out["group_start"][t, :b] = run.group_start
            out["tomb"][t, :b] = run.tomb
            out["live"][t, :b] = run.live
            out["ht_hi"][t, :b] = run.ht_hi
            out["ht_lo"][t, :b] = run.ht_lo
            out["exp_hi"][t, :b] = run.exp_hi
            out["exp_lo"][t, :b] = run.exp_lo
            for cid, col in run.cols.items():
                e = out["cols"][cid]
                e["set"][t, :b] = col.set_
                e["isnull"][t, :b] = col.isnull
                e["cmp"][t, :b] = col.cmp_planes
                if col.arith is not None:
                    e["arith"][t, :b] = col.arith
        return out

    # -- per-tablet exact row bounds (host bisection over full key bytes) ---
    def row_bounds(self, lower: bytes, upper: bytes):
        lo = np.zeros(self.padded_T, dtype=np.int32)
        hi = np.zeros(self.padded_T, dtype=np.int32)
        for t, run in enumerate(self.runs):
            lo[t] = run.lower_row(lower)
            hi[t] = run.upper_row(upper)
        return lo, hi


# -- the device program ------------------------------------------------------

def _lex_collective_ext(hi, lo, is_max, axes):
    """Lexicographic (hi, lo) extreme across mesh axes: pmax the high plane,
    then pmax the low plane masked to high-plane ties."""
    red = jax.lax.pmax if is_max else jax.lax.pmin
    fill = I32_MIN if is_max else I32_MAX
    mhi = red(hi, axes)
    mlo = red(jnp.where(hi == mhi, lo, fill), axes)
    return mhi, mlo


def _combine_across_mesh(sig_aggs, acc, scanned, axes=("t", "b")):
    out = []
    for ag, a in zip(sig_aggs, acc):
        if ag.fn == "count":
            out.append({"count": jax.lax.psum(a["count"], axes)})
        elif ag.fn == "sum":
            if ag.kind in ("f32", "f64"):
                out.append({"fsum": jax.lax.psum(a["fsum"], axes),
                            "fcomp": jax.lax.psum(a["fcomp"], axes),
                            "n": jax.lax.psum(a["n"], axes)})
            else:
                out.append({"digits": jax.lax.psum(a["digits"], axes),
                            "n": jax.lax.psum(a["n"], axes)})
        else:
            is_max = ag.fn == "max"
            n = jax.lax.psum(a["n"], axes)
            if ag.kind == "f32":
                red = jax.lax.pmax if is_max else jax.lax.pmin
                out.append({"fext": red(a["fext"], axes), "n": n})
            elif ag.kind == "i32":
                red = jax.lax.pmax if is_max else jax.lax.pmin
                out.append({"ext": red(a["ext"], axes), "n": n})
            else:
                mhi, mlo = _lex_collective_ext(a["ext_hi"], a["ext_lo"],
                                               is_max, axes)
                out.append({"ext_hi": mhi, "ext_lo": mlo, "n": n})
    return out, jax.lax.psum(scanned, axes)


def _shard_body(sig: dscan.ScanSig, Tl: int, Bl: int, R: int,
                run, row_lo, row_hi, read_hi, read_lo, rexp_hi, rexp_lo,
                pred_lits):
    """Runs on one device over its [Tl, Bl, R] shard. Returns replicated
    combined aggregate partials + scanned-row count."""
    K = sig.K
    W = Bl // K
    block_off = jax.lax.axis_index("b") * Bl
    # Loop carries become device-varying inside the loop body; mark the
    # replicated initial values as varying so the carry types match.
    varying = lambda x: jax.lax.pcast(x, ("t", "b"), to="varying")
    acc = jax.tree.map(varying, agg_init(sig.aggs))
    scanned = varying(jnp.int32(0))
    for t in range(Tl):
        local = jax.tree.map(lambda a: a[t], run)
        lo_t, hi_t = row_lo[t], row_hi[t]
        body = functools.partial(
            fold_window, sig, local, row_lo=lo_t, row_hi=hi_t,
            read_planes=(read_hi, read_lo, rexp_hi, rexp_lo),
            pred_lits=pred_lits, block_off=block_off)
        # Local window bounds: only windows of this shard overlapping the
        # tablet's row range (floor division is floor for negatives too).
        w_first = jnp.clip((lo_t // R - block_off) // K, 0, W)
        w_last = jnp.clip(((hi_t - 1) // R - block_off) // K + 1, 0, W)
        acc, scanned = jax.lax.fori_loop(
            w_first, w_last, lambda w, c: body(w, c), (acc, scanned))
    return _combine_across_mesh(sig.aggs, acc, scanned)


@functools.lru_cache(maxsize=64)
@compile_contract("dist_agg", max_compiles=64)
def _compiled_dist_agg(sig: dscan.ScanSig, mesh: Mesh, Tl: int, Bl: int):
    """One jitted shard_map program per (scan signature, mesh). Mesh is
    hashable and the cache entry keeps it alive only until eviction."""
    spec_tb = P("t", "b")
    in_specs = (
        _run_specs(sig, spec_tb),  # stacked run pytree
        P("t"), P("t"),            # row bounds
        P(), P(), P(), P(),        # read/expiry planes
        P(),                       # predicate literals (replicated)
    )
    body = functools.partial(_shard_body, sig, Tl, Bl, sig.R)
    smapped = jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                            out_specs=(_acc_specs(sig), P()))
    return jax.jit(smapped)


def _run_specs(sig, spec_tb):
    cols = {}
    for cs in sig.cols:
        entry = {"set": spec_tb, "isnull": spec_tb, "cmp": spec_tb}
        if cs.kind != "str":
            entry["arith"] = spec_tb
        cols[cs.col_id] = entry
    return {
        "valid": spec_tb, "group_start": spec_tb, "tomb": spec_tb,
        "live": spec_tb, "ht_hi": spec_tb, "ht_lo": spec_tb,
        "exp_hi": spec_tb, "exp_lo": spec_tb, "cols": cols,
    }


def _acc_specs(sig):
    return [jax.tree.map(lambda _: P(), a)
            for a in agg_init(sig.aggs)]


# -- public API --------------------------------------------------------------

def sharded_aggregate(st: ShardedTablets, spec: ScanSpec) -> ScanResult:
    """Evaluate spec's aggregates over all tablets on the mesh.

    Constraints (callers fall back to the per-tablet host path otherwise):
    aggregate-only spec, no GROUP BY, device-exact predicates only
    (non-key i32/i64/f64 columns), numeric aggregate columns.
    """
    if not spec.is_aggregate or spec.group_by:
        raise ValueError("sharded_aggregate handles plain aggregate specs")
    schema = st.schema
    name_to_id = {c.name: c.col_id for c in schema.value_columns}
    kinds = {c.col_id: _kind(c) for c in schema.value_columns}
    key_names = {c.name for c in schema.key_columns}

    pred_sigs, pred_lits = [], []
    for p in spec.predicates:
        if p.column in key_names or p.op == "IN":
            raise ValueError(f"predicate on {p.column} not device-exact")
        cid = name_to_id[p.column]
        if kinds[cid] in ("str", "f32"):
            raise ValueError(f"predicate kind {kinds[cid]} not device-exact")
        pred_sigs.append(dscan.PredSig(cid, kinds[cid], p.op))
        pred_lits.append(pred_literal(kinds[cid], p.value))

    for a in spec.aggregates:
        if a.column and a.column not in name_to_id:
            raise ValueError(f"aggregate on key column {a.column}")
        if a.column and kinds[name_to_id[a.column]] == "str" and a.fn != "count":
            raise ValueError("string min/max needs the host path")
    dev_aggs, lowering = lower_aggs(spec.aggregates, name_to_id, kinds)

    check_limb_bound(st.R, st.K)
    col_sigs = tuple(dscan.ColSig(c.col_id, kinds[c.col_id])
                     for c in schema.value_columns)
    sig = dscan.ScanSig(B=st.B, R=st.R, K=st.K, cols=col_sigs,
                        preds=tuple(pred_sigs), aggs=dev_aggs,
                        apply_preds=True)

    lo, hi = st.row_bounds(spec.lower, spec.upper)
    from yugabyte_db_tpu.storage.row_version import MAX_HT
    r_hi, r_lo = PL.scalar_ht_planes(min(spec.read_ht, MAX_HT))
    e_hi, e_lo = PL.scalar_ht_planes(min(spec.read_ht, MAX_HT - 1))

    Tl = st.padded_T // st.mesh.shape["t"]
    fn = _compiled_dist_agg(sig, st.mesh, Tl, st.Bl)
    acc, scanned = fn(st.arrays, jnp.asarray(lo), jnp.asarray(hi),
                      jnp.int32(r_hi), jnp.int32(r_lo),
                      jnp.int32(e_hi), jnp.int32(e_lo), tuple(pred_lits))
    # Both outputs in one explicit fetch — finalize() reads every limb
    # of acc, so an implicit per-limb transfer would pay the link
    # round-trip len(acc) times.
    acc, scanned = jax.device_get((acc, scanned))

    out_row, names = [], []
    for a, (fn_name, di) in zip(spec.aggregates, lowering):
        names.append(f"{a.fn}({a.column or '*'})")
        out_row.append(finalize(dev_aggs[di], acc[di], fn_name))
    return ScanResult(names, [tuple(out_row)], None, int(scanned))


def _kind(c):
    from yugabyte_db_tpu.ops.device_run import dtype_kind
    return dtype_kind(c.dtype)


# -- sharded row/paging path -------------------------------------------------
#
# The cluster ROW read path on the mesh: each device computes the exact
# flat-run match mask over its (tablet, block-range) shard and emits the
# first M matching row indices; the host assembles LIMIT pages in tablet
# order (a device's "b"-shard covers a contiguous disjoint row range, so
# concatenating shard outputs in "b" order is already key order). This
# is the device-sharded analog of the per-tablet parallel read fan-out
# (reference: src/yb/client/batcher.h:80) — the reference scans one
# tablet per thread; here tablets AND block ranges split over the mesh.

_PAGE_BUCKETS = (128, 512, 2048)


def _le2(a_hi, a_lo, b_hi, b_lo):
    return (a_hi < b_hi) | ((a_hi == b_hi) & (a_lo <= b_lo))


def _flat_pred_mask(kind, cmp, lit):
    if kind == "i32":
        v = cmp[..., 0]
        x = lit[0]
        return {"=": v == x, "!=": v != x, "<": v < x, "<=": v <= x,
                ">": v > x, ">=": v >= x}
    hi, lo = cmp[..., 0], cmp[..., 1]
    lhi, llo = lit
    eq = (hi == lhi) & (lo == llo)
    lt = (hi < lhi) | ((hi == lhi) & (lo < llo))
    return {"=": eq, "!=": ~eq, "<": lt, "<=": lt | eq,
            ">": ~(lt | eq), ">=": ~lt}


def _rows_body(col_ids, pred_items, Tl, Bl, R, M, run, row_lo, row_hi,
               r_hi, r_lo, e_hi, e_lo, pred_lits):
    """Per-device: exact flat-run match masks over the [Tl, Bl, R] shard
    and the first M matching global row indices per local tablet.
    Semantics mirror the host page index (storage.host_page.masks):
    MVCC visibility at the read point, tombstones, TTL, liveness/column
    existence, device-exact predicates."""
    base = jax.lax.axis_index("b") * (Bl * R)
    n = Bl * R
    ridx = base + jnp.arange(n, dtype=jnp.int32)
    out_idx, out_cnt = [], []
    for t in range(Tl):
        local = jax.tree.map(lambda a: a[t], run)
        flat = lambda a: a.reshape((n,) + a.shape[2:])  # noqa: E731
        visible = flat(local["valid"]) & _le2(
            flat(local["ht_hi"]), flat(local["ht_lo"]), r_hi, r_lo)
        expired = _le2(flat(local["exp_hi"]), flat(local["exp_lo"]),
                       e_hi, e_lo)
        alive = visible & ~flat(local["tomb"])
        not_exp = ~expired
        exists = alive & flat(local["live"]) & not_exp
        notnull = {}
        for cid in col_ids:
            c = local["cols"][cid]
            nn = alive & flat(c["set"]) & ~flat(c["isnull"]) & not_exp
            notnull[cid] = nn
            exists = exists | nn
        match = exists & (ridx >= row_lo[t]) & (ridx < row_hi[t])
        for (cid, kind, op), lit in zip(pred_items, pred_lits):
            cmp = flat(local["cols"][cid]["cmp"])
            match = match & notnull[cid] & \
                _flat_pred_mask(kind, cmp, lit)[op]
        cnt = jnp.sum(match, dtype=jnp.int32)
        pos = jnp.nonzero(match, size=M, fill_value=n)[0]
        out_idx.append((base + pos.astype(jnp.int32))[None, None, :])
        out_cnt.append(cnt[None, None])
    return (jnp.concatenate(out_idx, axis=0),
            jnp.concatenate(out_cnt, axis=0))


@functools.lru_cache(maxsize=64)
@compile_contract("dist_rows", max_compiles=64)
def _compiled_dist_rows(cols_desc, pred_items, mesh, Tl, Bl, R, M):
    spec_tb = P("t", "b")
    cols = {}
    for cid, has_arith in cols_desc:
        entry = {"set": spec_tb, "isnull": spec_tb, "cmp": spec_tb}
        if has_arith:
            entry["arith"] = spec_tb
        cols[cid] = entry
    col_ids = tuple(cid for cid, _a in cols_desc)
    run_spec = {
        "valid": spec_tb, "group_start": spec_tb, "tomb": spec_tb,
        "live": spec_tb, "ht_hi": spec_tb, "ht_lo": spec_tb,
        "exp_hi": spec_tb, "exp_lo": spec_tb, "cols": cols,
    }
    body = functools.partial(_rows_body, col_ids, pred_items, Tl, Bl, R,
                             M)
    smapped = jax.shard_map(
        body, mesh=mesh,
        in_specs=(run_spec, P("t"), P("t"), P(), P(), P(), P(), P()),
        out_specs=(P("t", "b"), P("t", "b")))
    return jax.jit(smapped)


def sharded_row_page(st: ShardedTablets, spec: ScanSpec,
                     resume: bytes | None = None) -> ScanResult:
    """LIMIT page over all tablets on the mesh: ONE device dispatch
    computes every tablet's matching rows; the host takes the first
    `limit` in (tablet, key) order and materializes them from the host
    mirror (result-proportional work). Constraints: flat runs, exact
    (i32/i64/f64 value-column) predicates, no aggregates.

    Cross-tablet paging: the returned resume_key encodes
    (tablet index, last key) — pass it back as ``resume`` to continue
    (the QLPagingStatePB next_partition_key + next_row_key shape)."""
    if spec.is_aggregate:
        raise ValueError("sharded_row_page serves row scans")
    schema = st.schema
    if any(r.max_group_versions > 1 for r in st.runs):
        raise ValueError("sharded_row_page needs flat runs")
    name_to_id = {c.name: c.col_id for c in schema.value_columns}
    kinds = {c.col_id: _kind(c) for c in schema.value_columns}
    key_names = {c.name for c in schema.key_columns}
    pred_items, pred_lits = [], []
    for p in spec.predicates:
        if p.column in key_names or p.op == "IN":
            raise ValueError(f"predicate on {p.column} not device-exact")
        cid = name_to_id[p.column]
        kind = kinds[cid]
        if kind not in ("i32", "i64", "f64"):
            raise ValueError(f"predicate kind {kind} not device-exact")
        if kind == "i32":
            lit = (int(p.value),)
        elif kind == "i64":
            phi, plo = PL.i64_to_ordered_planes(
                np.array([int(p.value)], dtype=np.int64))
            lit = (int(phi[0]), int(plo[0]))
        else:
            phi, plo = PL.f64_to_ordered_planes(
                np.array([p.value], dtype=np.float64))
            lit = (int(phi[0]), int(plo[0]))
        pred_items.append((cid, kind, p.op))
        pred_lits.append(tuple(jnp.int32(v) for v in lit))

    limit = spec.limit if spec.limit is not None else _PAGE_BUCKETS[-1]
    M = next((m for m in _PAGE_BUCKETS if m >= limit),
             -(-limit // 128) * 128)
    start_t = 0
    start_key = spec.lower
    from yugabyte_db_tpu.utils import codec as _codec

    if resume is not None:
        start_t, last_key = _codec.decode(resume)
        start_key = max(spec.lower, last_key + b"\x00")
    lo, hi = st.row_bounds(spec.lower, spec.upper)
    if resume is not None:
        for t in range(min(start_t, len(st.runs))):
            lo[t] = hi[t]  # earlier tablets: already consumed
        if start_t < len(st.runs):
            lo[start_t] = max(lo[start_t],
                              st.runs[start_t].lower_row(start_key))
    from yugabyte_db_tpu.storage.row_version import MAX_HT

    r_hi, r_lo = PL.scalar_ht_planes(min(spec.read_ht, MAX_HT))
    e_hi, e_lo = PL.scalar_ht_planes(min(spec.read_ht, MAX_HT - 1))
    Tl = st.padded_T // st.mesh.shape["t"]
    cols_desc = tuple(
        (c.col_id, st.runs[0].cols[c.col_id].arith is not None)
        for c in schema.value_columns)
    fn = _compiled_dist_rows(cols_desc, tuple(pred_items), st.mesh, Tl,
                             st.Bl, st.R, M)
    idx, cnt = fn(st.arrays, jnp.asarray(lo), jnp.asarray(hi),
                  jnp.int32(r_hi), jnp.int32(r_lo), jnp.int32(e_hi),
                  jnp.int32(e_lo), tuple(pred_lits))
    # One explicit batched fetch for both outputs (one link round-trip,
    # not one per array): idx [padded_T, mesh_b, M] global row indices,
    # cnt [padded_T, mesh_b].
    idx, cnt = jax.device_get((idx, cnt))

    projection = spec.projection or [c.name for c in schema.columns]
    key_pos = {c.name: i for i, c in enumerate(schema.key_columns)}
    rows: list[tuple] = []
    scanned = 0
    budget = limit
    mesh_b = st.mesh.shape["b"]
    shard_rows = st.Bl * st.R
    resume_out = None
    for t, run in enumerate(st.runs):
        truncated = False
        sel: list[int] = []
        for b in range(mesh_b):
            c = int(cnt[t, b])
            take = min(c, M)
            if c > M:
                truncated = True  # tablet has matches beyond M
            sel.extend(int(g) for g in idx[t, b, :take])
        scanned += sum(int(cnt[t, b]) for b in range(mesh_b))
        more_in_tablet = truncated or len(sel) > budget
        sel = sel[:budget]
        for g in sel:
            rows.append(_materialize_row(run, schema, g, projection,
                                         key_pos))
        budget -= len(sel)
        page_full = budget <= 0
        if sel and (more_in_tablet
                    or (page_full and t + 1 < len(st.runs))):
            resume_out = _codec.encode([t, run.key_at(sel[-1])])
            break
        if page_full:
            break
    return ScanResult(list(projection), rows, resume_out, scanned)


def _materialize_row(run, schema, g, projection, key_pos):
    """One selected global row from the run's host mirror (the same
    payload sources the page server uses)."""
    R = run.R
    b, r = divmod(g, R)
    key_vals = None
    out = []
    for nm in projection:
        if nm in key_pos:
            if key_vals is None:
                key_vals = run.key_vals_at(g)
            out.append(key_vals[key_pos[nm]])
            continue
        col = schema.column(nm)
        cd = run.cols[col.col_id]
        if not cd.set_[b, r] or cd.isnull[b, r]:
            out.append(None)
            continue
        kind = _kind(col)
        if kind in ("str", "f32"):
            out.append(run.row_versions[b][r].columns[col.col_id])
        elif kind == "i32":
            v = int(cd.cmp_planes[b, r, 0])
            from yugabyte_db_tpu.models.datatypes import DataType

            out.append(bool(v) if col.dtype == DataType.BOOL else v)
        elif kind == "i64":
            out.append(int(PL.ordered_planes_to_i64(
                cd.cmp_planes[b, r, 0:1], cd.cmp_planes[b, r, 1:2])[0]))
        else:
            out.append(float(PL.ordered_planes_to_f64(
                cd.cmp_planes[b, r, 0:1], cd.cmp_planes[b, r, 1:2])[0]))
    return tuple(out)
