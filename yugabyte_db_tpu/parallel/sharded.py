"""Sharded multi-tablet aggregate: shard_map over a ("t", "b") mesh.

Layout: every tablet's ColumnarRun planes are stacked to [T, B, R, ...] and
placed with NamedSharding(P("t", "b")) — tablets split over the "t" mesh
axis (data parallel; the reference's unit of sharding, one tablet per
scanning thread at best), blocks of each tablet split over "b" (sequence
parallel; no reference analog — a tablet scan there is strictly
single-threaded). Each device fori_loops scan windows over its local
(tablet, block-range) shard reusing ops.scan.scan_window, folds exact
per-block aggregate partials into carry-safe accumulators, and the final
combine rides ICI collectives:

- count / n / fsum: ``psum`` over both axes;
- integer sums: base-2^16 digit vectors (int32) with a carry-propagation
  step per window so digits never overflow int32, ``psum``-ed then
  recombined host-side in arbitrary precision — bit-exact at any scale;
- min/max: two-int32-plane lexicographic maxima via a two-step collective
  (pmax on the high plane, then pmax on the tie-masked low plane).

Group/window invariant: key groups never span blocks (storage.columnar
build invariant), so any contiguous block range — in particular a device's
"b"-shard — is segment-complete and partials add up exactly.

Reference analog of the combine being replaced: the client-side merge of
per-tablet partial aggregates (src/yb/yql/cql/ql/exec/eval_aggr.cc,
src/yb/docdb/pgsql_operation.cc:473).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from yugabyte_db_tpu.models.schema import Schema
from yugabyte_db_tpu.ops import encodings
from yugabyte_db_tpu.ops import row_gather as RG
from yugabyte_db_tpu.ops import scan as dscan
from yugabyte_db_tpu.parallel import meshcompat
from yugabyte_db_tpu.utils.jitting import compile_contract
from yugabyte_db_tpu.ops.agg_fold import (agg_init, check_limb_bound,
                                          finalize, fold_window, lower_aggs,
                                          pred_literal)
from yugabyte_db_tpu.ops.scan import I32_MAX, I32_MIN
from yugabyte_db_tpu.storage.columnar import ColumnarRun
from yugabyte_db_tpu.storage.residency import device_nbytes, hbm_cache
from yugabyte_db_tpu.storage.scan_spec import ScanResult, ScanSpec
from yugabyte_db_tpu.utils import planes as PL
from yugabyte_db_tpu.utils.memtracker import root_tracker


# -- host-side assembly ------------------------------------------------------

def shard_dev_bytes(tree) -> dict:
    """Per-device byte map of a sharded array pytree: each leaf's
    addressable shards charged to the chip holding them — the
    ``dev_bytes`` the residency cache partitions its budget by.
    Replicated leaves charge every device (each holds a copy)."""
    from yugabyte_db_tpu.ops.device_run import device_label

    out: dict[str, int] = {}
    stack = [tree]
    while stack:
        node = stack.pop()
        if isinstance(node, dict):
            stack.extend(node.values())
        elif isinstance(node, (list, tuple)):
            stack.extend(node)
        elif node is not None:
            for sh in node.addressable_shards:
                lbl = device_label(sh.device)
                out[lbl] = (out.get(lbl, 0)
                            + int(sh.data.size) * sh.data.dtype.itemsize)
    return out


# -- encoding-aware tree structure -------------------------------------------
#
# Stacked planes may carry compressed leaves (ops.encodings): a leaf is
# either a plain [T, B, ...] ndarray or a single-key dict naming the
# encoding. shard_map in_specs, per-tablet slicing and device placement
# all dispatch on that structure, captured once per stack as a hashable
# ``enc_struct`` so the compiled-program caches key on it.

_ENC_SPEC_PARTS = {
    "bits": ("bw",),
    "delta16": ("dbase", "doff"),
    "rle": ("rid", "rvals"),
    "dict": ("codes",),
}


def _tree_struct(tree):
    """Hashable encoding structure of a stacked plane tree: leaf name ->
    encoding kind (None = plain), per top-level plane and per column."""
    planes = tuple(sorted((n, encodings.leaf_kind(l))
                          for n, l in tree.items() if n != "cols"))
    cols = tuple(sorted(
        (cid, tuple(sorted((n, encodings.leaf_kind(p))
                           for n, p in col.items())))
        for cid, col in tree["cols"].items()))
    return planes, cols


def _leaf_spec(kind, spec_tb):
    """shard_map PartitionSpec subtree for one leaf: components carrying
    the (tablet, block) axes shard P("t", "b"); components without a
    block axis (const cval, dict dhi/dlo) replicate."""
    if kind is None:
        return spec_tb
    if kind == "const":
        return {"const": {"cval": P()}}
    parts = {n: spec_tb for n in _ENC_SPEC_PARTS[kind]}
    if kind == "dict":
        parts["dhi"] = P()
        parts["dlo"] = P()
    return {kind: parts}


def _specs_from_struct(struct, spec_tb):
    planes, cols = struct
    out = {n: _leaf_spec(k, spec_tb) for n, k in planes}
    out["cols"] = {cid: {n: _leaf_spec(k, spec_tb) for n, k in entry}
                   for cid, entry in cols}
    return out


def _tree_shardings(struct, mesh):
    specs = _specs_from_struct(struct, P("t", "b"))
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def _tablet_slice(tree, t):
    """Slice one tablet out of a device-local [Tl, Bl, ...] shard tree,
    keeping encoded-leaf structure: replicated components (const cval,
    dict dhi/dlo) carry no tablet axis and pass through unchanged."""
    def one(leaf):
        k = encodings.leaf_kind(leaf)
        if k is None:
            return leaf[t]
        if k == "const":
            return leaf
        no_t = {"dict": ("dhi", "dlo")}.get(k, ())
        return {k: {n: (a if n in no_t else a[t])
                    for n, a in leaf[k].items()}}

    out = {n: one(l) for n, l in tree.items() if n != "cols"}
    out["cols"] = {cid: {n: one(p) for n, p in col.items()}
                   for cid, col in tree["cols"].items()}
    return out


def _encode_stack(stacked):
    """Re-encode stacked [T, B, ...] planes with the host encoders
    (ops.encodings) over the flattened [T*B, ...] block axis, then fold
    the leading axis of every block-dimensioned component back to
    [T, B, ...]. Padding (invalid blocks / pad tablets) is already baked
    into the plain planes, so decode is byte-identical by construction.
    The stack-level encoder never emits dict leaves (those come from
    per-run device flush output); pathological planes stay plain."""
    T, B = stacked["valid"].shape[:2]

    def enc(plane, how):
        leaf = how(plane.reshape((T * B,) + plane.shape[2:]))
        k = encodings.leaf_kind(leaf)
        if k is None:
            return plane
        if k == "const":
            return leaf
        return {k: {n: a.reshape((T, B) + a.shape[1:])
                    for n, a in leaf[k].items()}}

    out = {n: enc(stacked[n], encodings.encode_bool_plane)
           for n in ("valid", "group_start", "tomb", "live")}
    for n in ("ht_hi", "ht_lo", "exp_hi", "exp_lo"):
        out[n] = enc(stacked[n], encodings.encode_int_plane)
    out["cols"] = {}
    for cid, col in stacked["cols"].items():
        e = {"set": enc(col["set"], encodings.encode_bool_plane),
             "isnull": enc(col["isnull"], encodings.encode_bool_plane),
             "cmp": enc(col["cmp"], encodings.encode_int_plane)}
        if "arith" in col:
            e["arith"] = enc(col["arith"], encodings.encode_float_plane)
        out["cols"][cid] = e
    return out


class ShardedTablets:
    """Stacked, mesh-sharded device residency for T tablets' single runs.

    Each tablet contributes one ColumnarRun (compact first); runs are padded
    to a common block count divisible by mesh_b * window and stacked to
    [T, B, R, ...]. Dummy all-invalid tablets pad T to a multiple of mesh_t.
    """

    def __init__(self, schema: Schema, runs: list[ColumnarRun], mesh: Mesh,
                 window_blocks: int = 8, encode: bool | None = None):
        if not runs:
            raise ValueError("need at least one run")
        R = runs[0].R
        if any(r.R != R for r in runs):
            raise ValueError("all runs must share rows_per_block")
        self.schema = schema
        self.mesh = mesh
        self.K = window_blocks
        self.R = R
        mesh_t = mesh.shape["t"]
        mesh_b = mesh.shape["b"]
        self.T = len(runs)
        self.runs = runs
        pad_t = (-self.T) % mesh_t
        chunk = mesh_b * window_blocks
        Bmax = max(r.B for r in runs)
        self.B = Bmax + ((-Bmax) % chunk)
        self.Bl = self.B // mesh_b
        if self.Bl % window_blocks:
            raise AssertionError("local block count not a window multiple")

        stacked = self._stack(runs, pad_t)
        if encode is None:
            from yugabyte_db_tpu.utils.flags import FLAGS
            encode = FLAGS.get("tpu_plane_encoding") != "off"
        if encode:
            stacked = _encode_stack(stacked)
        self.enc_struct = _tree_struct(stacked)
        self.encoded = encodings.tree_encoded(stacked)
        # Mesh placement must shard, not cache: plane-group residency for
        # sharded arrays is accounted (and pinned) via add_external below.
        self.arrays = jax.tree.map(
            lambda a, s: jax.device_put(a, s),  # yb-lint: disable=ijax/unmanaged-device-put
            stacked, _tree_shardings(self.enc_struct, mesh))
        self.padded_T = self.T + pad_t
        # The stacked mesh arrays live outside the demand-upload path but
        # inside the same HBM budget: account them as a pinned external
        # entry so /memz, /metrics and eviction pressure see them.  The
        # charge is a per-device map — one shard's bytes on the chip
        # that actually holds it — so each chip's budget bucket sees its
        # true share, not T devices each blamed for the whole stack.
        self._res_key = hbm_cache().add_external(
            self, device_nbytes(self.arrays),
            root_tracker().child("device").child("sharded"), "sharded_mesh",
            dev_bytes=shard_dev_bytes(self.arrays))

    def close(self) -> None:
        """Release the mesh arrays' residency accounting. The arrays
        stay usable for scans already holding this stack (they free when
        the last reference dies) — a flush/compaction can supersede a
        stack mid-serve without crashing the in-flight page."""
        if self._res_key is not None:
            hbm_cache().invalidate(self._res_key)
            self._res_key = None

    def update_tablet(self, t: int, run: ColumnarRun,
                      device_arrays=None) -> bool:
        """Replace tablet ``t``'s slot of the stacked mesh arrays in
        place (one jitted dynamic_update_slice over the tree) — the
        incremental path when a flush/compaction swaps a single tablet's
        run. ``device_arrays``, when given, is a DeviceRun.arrays tree
        already ON device (ops.flush output): its planes reshard over
        the mesh directly, no host round trip. Returns False when the
        stack must be rebuilt instead (encoded stack, block overflow,
        row-shape or column mismatch); residency accounting is unchanged
        either way because every shape is."""
        if self.encoded or t >= self.T or run.R != self.R:
            return False
        if max(run.B, 1) > self.B:
            return False
        src = None
        if device_arrays is not None:
            src = self._device_src(device_arrays)
        if src is None:
            src = self._stack([run], 0)
        if _tree_struct(src) != self.enc_struct:
            return False
        spec_b = P(None, "b")
        src = jax.tree.map(
            lambda a: jax.device_put(a, NamedSharding(self.mesh, spec_b)),  # yb-lint: disable=ijax/unmanaged-device-put
            src)
        cols_desc = tuple(sorted(
            (cid, "arith" in col)
            for cid, col in self.arrays["cols"].items()))
        fn = _compiled_stack_update(self.padded_T, self.B, self.R,
                                    cols_desc)
        out = fn(self.arrays, src, jnp.int32(t))
        # Pin the result back to the stack's sharding (GSPMD is free to
        # choose otherwise for the update program's output).
        self.arrays = jax.tree.map(
            lambda a, s: jax.device_put(a, s),  # yb-lint: disable=ijax/unmanaged-device-put
            out, _tree_shardings(self.enc_struct, self.mesh))
        self.runs = list(self.runs)
        self.runs[t] = run
        return True

    def _device_src(self, arrays):
        """[1, self.B, ...] plain source tree built from device-resident
        run planes: encoded leaves decode ON DEVICE (ops.encodings jnp
        decode — dict cmp drops its third code plane), the block axis
        pads to the stack's B with the stack's padding values. Returns
        None when the planes don't fit the stack's shape."""
        B = int(arrays["valid"].shape[0])
        if B > self.B or arrays["valid"].shape[1] != self.R:
            return None

        def prep(leaf, ones=False):
            k = encodings.leaf_kind(leaf)
            if k is not None:
                leaf = encodings.decode_leaf(leaf, B, self.R)
                if k == "dict":
                    leaf = leaf[..., :2]
            leaf = jnp.asarray(leaf)
            pad = self.B - leaf.shape[0]
            if pad:
                fill = (jnp.ones if ones else jnp.zeros)(
                    (pad,) + leaf.shape[1:], leaf.dtype)
                leaf = jnp.concatenate([leaf, fill], axis=0)
            return leaf[None]

        out = {n: prep(arrays[n], ones=(n == "group_start"))
               for n in ("valid", "group_start", "tomb", "live",
                         "ht_hi", "ht_lo", "exp_hi", "exp_lo")}
        out["cols"] = {cid: {n: prep(p) for n, p in col.items()}
                       for cid, col in arrays["cols"].items()}
        return out

    def _stack(self, runs, pad_t):
        B, R = self.B, self.R
        T = len(runs) + pad_t

        def alloc(shape, dtype, fill=0):
            return np.full((T, B) + shape, fill, dtype=dtype)

        out = {
            "valid": alloc((R,), bool, False),
            # pad rows are their own groups so they never join a real one
            "group_start": alloc((R,), bool, True),
            "tomb": alloc((R,), bool, False),
            "live": alloc((R,), bool, False),
            "ht_hi": alloc((R,), np.int32),
            "ht_lo": alloc((R,), np.int32),
            "exp_hi": alloc((R,), np.int32),
            "exp_lo": alloc((R,), np.int32),
            "cols": {},
        }
        for c in self.schema.value_columns:
            nplanes = runs[0].cols[c.col_id].cmp_planes.shape[-1]
            entry = {
                "set": alloc((R,), bool, False),
                "isnull": alloc((R,), bool, False),
                "cmp": alloc((R, nplanes), np.int32),
            }
            if runs[0].cols[c.col_id].arith is not None:
                entry["arith"] = alloc((R,), np.float32)
            out["cols"][c.col_id] = entry
        for t, run in enumerate(runs):
            b = run.B
            out["valid"][t, :b] = run.valid
            out["group_start"][t, :b] = run.group_start
            out["tomb"][t, :b] = run.tomb
            out["live"][t, :b] = run.live
            out["ht_hi"][t, :b] = run.ht_hi
            out["ht_lo"][t, :b] = run.ht_lo
            out["exp_hi"][t, :b] = run.exp_hi
            out["exp_lo"][t, :b] = run.exp_lo
            for cid, col in run.cols.items():
                e = out["cols"][cid]
                e["set"][t, :b] = col.set_
                e["isnull"][t, :b] = col.isnull
                e["cmp"][t, :b] = col.cmp_planes
                if col.arith is not None:
                    e["arith"][t, :b] = col.arith
        return out

    # -- per-tablet exact row bounds (host bisection over full key bytes) ---
    def row_bounds(self, lower: bytes, upper: bytes):
        lo = np.zeros(self.padded_T, dtype=np.int32)
        hi = np.zeros(self.padded_T, dtype=np.int32)
        for t, run in enumerate(self.runs):
            lo[t] = run.lower_row(lower)
            hi[t] = run.upper_row(upper)
        return lo, hi


# -- the device program ------------------------------------------------------

def _lex_collective_ext(hi, lo, is_max, axes):
    """Lexicographic (hi, lo) extreme across mesh axes: pmax the high plane,
    then pmax the low plane masked to high-plane ties."""
    red = jax.lax.pmax if is_max else jax.lax.pmin
    fill = I32_MIN if is_max else I32_MAX
    mhi = red(hi, axes)
    mlo = red(jnp.where(hi == mhi, lo, fill), axes)
    return mhi, mlo


def _combine_across_mesh(sig_aggs, acc, scanned, axes=("t", "b")):
    out = []
    for ag, a in zip(sig_aggs, acc):
        if ag.fn == "count":
            out.append({"count": jax.lax.psum(a["count"], axes)})
        elif ag.fn == "sum":
            if ag.kind in ("f32", "f64"):
                out.append({"fsum": jax.lax.psum(a["fsum"], axes),
                            "fcomp": jax.lax.psum(a["fcomp"], axes),
                            "n": jax.lax.psum(a["n"], axes)})
            else:
                out.append({"digits": jax.lax.psum(a["digits"], axes),
                            "n": jax.lax.psum(a["n"], axes)})
        else:
            is_max = ag.fn == "max"
            n = jax.lax.psum(a["n"], axes)
            if ag.kind == "f32":
                red = jax.lax.pmax if is_max else jax.lax.pmin
                out.append({"fext": red(a["fext"], axes), "n": n})
            elif ag.kind == "i32":
                red = jax.lax.pmax if is_max else jax.lax.pmin
                out.append({"ext": red(a["ext"], axes), "n": n})
            else:
                mhi, mlo = _lex_collective_ext(a["ext_hi"], a["ext_lo"],
                                               is_max, axes)
                out.append({"ext_hi": mhi, "ext_lo": mlo, "n": n})
    return out, jax.lax.psum(scanned, axes)


def _shard_body(sig: dscan.ScanSig, Tl: int, Bl: int, R: int,
                run, row_lo, row_hi, read_hi, read_lo, rexp_hi, rexp_lo,
                pred_lits):
    """Runs on one device over its [Tl, Bl, R] shard. Returns replicated
    combined aggregate partials + scanned-row count."""
    K = sig.K
    W = Bl // K
    block_off = jax.lax.axis_index("b") * Bl
    # Loop carries become device-varying inside the loop body; mark the
    # replicated initial values as varying so the carry types match.
    varying = lambda x: meshcompat.varying(x, ("t", "b"))
    acc = jax.tree.map(varying, agg_init(sig.aggs))
    scanned = varying(jnp.int32(0))
    for t in range(Tl):
        local = _tablet_slice(run, t)
        lo_t, hi_t = row_lo[t], row_hi[t]
        body = functools.partial(
            fold_window, sig, local, row_lo=lo_t, row_hi=hi_t,
            read_planes=(read_hi, read_lo, rexp_hi, rexp_lo),
            pred_lits=pred_lits, block_off=block_off)
        # Local window bounds: only windows of this shard overlapping the
        # tablet's row range (floor division is floor for negatives too).
        w_first = jnp.clip((lo_t // R - block_off) // K, 0, W)
        w_last = jnp.clip(((hi_t - 1) // R - block_off) // K + 1, 0, W)
        acc, scanned = jax.lax.fori_loop(
            w_first, w_last, lambda w, c: body(w, c), (acc, scanned))
    return _combine_across_mesh(sig.aggs, acc, scanned)


@functools.lru_cache(maxsize=64)
@compile_contract("dist_agg", max_compiles=64)
def _compiled_dist_agg(sig: dscan.ScanSig, mesh: Mesh, enc_struct,
                       Tl: int, Bl: int):
    """One jitted shard_map program per (scan signature, mesh, stack
    encoding structure). Mesh is hashable and the cache entry keeps it
    alive only until eviction."""
    spec_tb = P("t", "b")
    in_specs = (
        _specs_from_struct(enc_struct, spec_tb),  # stacked run pytree
        P("t"), P("t"),            # row bounds
        P(), P(), P(), P(),        # read/expiry planes
        P(),                       # predicate literals (replicated)
    )
    body = functools.partial(_shard_body, sig, Tl, Bl, sig.R)
    smapped = meshcompat.shard_map(body, mesh, in_specs,
                                   (_acc_specs(sig), P()))
    return jax.jit(smapped)


@functools.lru_cache(maxsize=32)
@compile_contract("stack_update", max_compiles=32)
def _compiled_stack_update(padded_T: int, B: int, R: int, cols_desc):
    """One in-place tablet-slot update program per stack shape: every
    leaf gets its [1, B, ...] source written at block row ``t`` with a
    traced dynamic_update_slice (no per-tablet recompiles)."""
    def upd(dst, src, t):
        return jax.tree.map(
            lambda d, s: jax.lax.dynamic_update_slice(
                d, s.astype(d.dtype), (t,) + (0,) * (d.ndim - 1)),
            dst, src)

    return jax.jit(upd)


def _acc_specs(sig):
    return [jax.tree.map(lambda _: P(), a)
            for a in agg_init(sig.aggs)]


# -- public API --------------------------------------------------------------

def sharded_aggregate(st: ShardedTablets, spec: ScanSpec) -> ScanResult:
    """Evaluate spec's aggregates over all tablets on the mesh.

    Constraints (callers fall back to the per-tablet host path otherwise):
    aggregate-only spec, no GROUP BY, device-exact predicates only
    (non-key i32/i64/f64 columns), numeric aggregate columns.
    """
    if not spec.is_aggregate or spec.group_by:
        raise ValueError("sharded_aggregate handles plain aggregate specs")
    schema = st.schema
    name_to_id = {c.name: c.col_id for c in schema.value_columns}
    kinds = {c.col_id: _kind(c) for c in schema.value_columns}
    key_names = {c.name for c in schema.key_columns}

    pred_sigs, pred_lits = [], []
    for p in spec.predicates:
        if p.column in key_names or p.op == "IN":
            raise ValueError(f"predicate on {p.column} not device-exact")
        cid = name_to_id[p.column]
        if kinds[cid] in ("str", "f32"):
            raise ValueError(f"predicate kind {kinds[cid]} not device-exact")
        pred_sigs.append(dscan.PredSig(cid, kinds[cid], p.op))
        pred_lits.append(pred_literal(kinds[cid], p.value))

    for a in spec.aggregates:
        if a.expr is not None:
            # lower_aggs drops the expression tree silently; without
            # this guard a sum(a*b) spec would fold the wrong thing.
            raise ValueError("expression aggregates need the host path")
        if a.column and a.column not in name_to_id:
            raise ValueError(f"aggregate on key column {a.column}")
        if a.column and kinds[name_to_id[a.column]] == "str" and a.fn != "count":
            raise ValueError("string min/max needs the host path")
    dev_aggs, lowering = lower_aggs(spec.aggregates, name_to_id, kinds)

    check_limb_bound(st.R, st.K)
    col_sigs = tuple(dscan.ColSig(c.col_id, kinds[c.col_id])
                     for c in schema.value_columns)
    sig = dscan.ScanSig(B=st.B, R=st.R, K=st.K, cols=col_sigs,
                        preds=tuple(pred_sigs), aggs=dev_aggs,
                        apply_preds=True)

    lo, hi = st.row_bounds(spec.lower, spec.upper)
    from yugabyte_db_tpu.storage.row_version import MAX_HT
    r_hi, r_lo = PL.scalar_ht_planes(min(spec.read_ht, MAX_HT))
    e_hi, e_lo = PL.scalar_ht_planes(min(spec.read_ht, MAX_HT - 1))

    Tl = st.padded_T // st.mesh.shape["t"]
    fn = _compiled_dist_agg(sig, st.mesh, st.enc_struct, Tl, st.Bl)
    acc, scanned = fn(st.arrays, jnp.asarray(lo), jnp.asarray(hi),
                      jnp.int32(r_hi), jnp.int32(r_lo),
                      jnp.int32(e_hi), jnp.int32(e_lo), tuple(pred_lits))
    # Both outputs in one explicit fetch — finalize() reads every limb
    # of acc, so an implicit per-limb transfer would pay the link
    # round-trip len(acc) times.
    acc, scanned = jax.device_get((acc, scanned))

    out_row, names = [], []
    for a, (fn_name, di) in zip(spec.aggregates, lowering):
        names.append(f"{a.fn}({a.column or '*'})")
        out_row.append(finalize(dev_aggs[di], acc[di], fn_name))
    return ScanResult(names, [tuple(out_row)], None, int(scanned))


def _kind(c):
    from yugabyte_db_tpu.ops.device_run import dtype_kind
    return dtype_kind(c.dtype)


# -- sharded row/paging path -------------------------------------------------
#
# The cluster ROW read path on the mesh: each device runs the packed
# row-gather program (ops.row_gather — the same MVCC resolve + top_k
# compaction the single-chip engine serves pages with) over its
# (tablet, block-range) shard, emitting the first M matches IN KEY ORDER
# plus a per-device match count combined with psum over ICI; the host
# assembles LIMIT pages in tablet order (a device's "b"-shard covers a
# contiguous disjoint row range, so concatenating shard outputs in "b"
# order is already key order) and decodes ONLY the page's rows from the
# fetched value planes. This is the device-sharded analog of the
# per-tablet parallel read fan-out (reference: src/yb/client/batcher.h:80)
# — the reference scans one tablet per thread; here tablets AND block
# ranges split over the mesh, and multi-version (MVCC) groups, encoded
# planes, tombstones and TTL all resolve on device.

_PAGE_BUCKETS = (128, 512, 2048)


def _page_body(sig: RG.GatherSig, Tl: int, Bl: int, R: int,
               run, iparams, fparams):
    """Per-device: the packed gather over each local tablet's [Bl, R]
    shard. ``iparams`` rows carry GLOBAL row bounds in the w_first/
    w_last/row_lo/row_hi/scan_from slots; each shard rebases them to its
    own block range (clipping to empty when the tablet's range misses
    the shard) so the while_loop walks only overlapping windows — the
    per-device trip counts diverge, which is exactly what the compat
    seam's check_rep=False / varying-types split exists for."""
    base = jax.lax.axis_index("b") * (Bl * R)
    KR = sig.K * R
    Wl = Bl // sig.K
    outs = []
    counts = meshcompat.varying(jnp.int32(0), ("t", "b"))
    for t in range(Tl):
        local = _tablet_slice(run, t)
        ip = iparams[t]
        lo = jnp.clip(ip[2] - base, 0, Bl * R)
        hi = jnp.clip(ip[3] - base, 0, Bl * R)
        sf = jnp.clip(ip[8] - base, 0, Bl * R)
        w_first = jnp.clip(lo // KR, 0, Wl - 1)
        w_last = jnp.where(hi > lo,
                           jnp.clip((hi - 1) // KR, 0, Wl - 1),
                           w_first - 1)
        head = jnp.stack([w_first, w_last, lo, hi, ip[4], ip[5], ip[6],
                          ip[7], sf])
        ipl = jnp.concatenate([head, ip[RG.PARAM_FIXED:]])
        buf = RG.gather_rows(sig, local, ipl, fparams)
        counts = counts + buf[sig.M, 0]
        outs.append(buf[None, None])
    # The per-device match-count combine rides ICI; the buffers ride the
    # ("t", "b")-sharded output (the host fetches only the page's rows).
    total = jax.lax.psum(counts, ("t", "b"))
    return jnp.concatenate(outs, axis=0), total


@functools.lru_cache(maxsize=64)
@compile_contract("dist_page", max_compiles=64)
def _compiled_dist_page(sig: RG.GatherSig, mesh: Mesh, enc_struct,
                        Tl: int, Bl: int):
    spec_tb = P("t", "b")
    body = functools.partial(_page_body, sig, Tl, Bl, sig.R)
    smapped = meshcompat.shard_map(
        body, mesh,
        (_specs_from_struct(enc_struct, spec_tb), P("t"), P()),
        (P("t", "b"), P()))
    return jax.jit(smapped)


def sharded_row_page(st: ShardedTablets, spec: ScanSpec,
                     resume: bytes | None = None) -> ScanResult:
    """LIMIT page over all tablets on the mesh: ONE device dispatch runs
    the packed MVCC row gather on every (tablet, block-range) shard; the
    host takes the first `limit` in (tablet, key) order and decodes them
    from the fetched value planes (result-proportional host work —
    varlen/f32 payloads fetch by setter index from the host mirror, the
    engine gather path's split). Serves multi-version AND encoded
    stacks. Constraints (callers fall back to the per-tablet host path):
    exact (i32/i64/f64 value-column) predicates, no aggregates.

    Cross-tablet paging: the returned resume_key encodes
    (tablet index, last key) — pass it back as ``resume`` to continue
    (the QLPagingStatePB next_partition_key + next_row_key shape)."""
    if spec.is_aggregate:
        raise ValueError("sharded_row_page serves row scans")
    schema = st.schema
    name_to_id = {c.name: c.col_id for c in schema.value_columns}
    kinds = {c.col_id: _kind(c) for c in schema.value_columns}
    key_names = {c.name for c in schema.key_columns}
    pred_sigs, int_lits = [], []
    for p in spec.predicates:
        if p.column in key_names or p.op == "IN":
            raise ValueError(f"predicate on {p.column} not device-exact")
        cid = name_to_id[p.column]
        kind = kinds[cid]
        if kind not in ("i32", "i64", "f64"):
            raise ValueError(f"predicate kind {kind} not device-exact")
        if kind == "i32":
            int_lits.append(int(p.value))
        elif kind == "i64":
            phi, plo = PL.i64_to_ordered_planes(
                np.array([int(p.value)], dtype=np.int64))
            int_lits += [int(phi[0]), int(plo[0])]
        else:
            phi, plo = PL.f64_to_ordered_planes(
                np.array([p.value], dtype=np.float64))
            int_lits += [int(phi[0]), int(plo[0])]
        pred_sigs.append(dscan.PredSig(cid, kind, p.op))

    limit = spec.limit if spec.limit is not None else _PAGE_BUCKETS[-1]
    M = next((m for m in _PAGE_BUCKETS if m >= limit),
             -(-limit // 128) * 128)
    projection = spec.projection or [c.name for c in schema.columns]
    key_pos = {c.name: i for i, c in enumerate(schema.key_columns)}
    out_cols = tuple(
        RG.OutCol(name_to_id[nm],
                  2 if kinds[name_to_id[nm]] in ("i64", "f64", "str")
                  else 1,
                  kinds[name_to_id[nm]] in ("str", "f32"))
        for nm in projection if nm not in key_pos)
    col_sigs = tuple(dscan.ColSig(c.col_id, kinds[c.col_id])
                     for c in schema.value_columns)
    flat = all(r.max_group_versions <= 1 for r in st.runs)
    sig = RG.GatherSig(B=st.Bl, R=st.R, K=st.K, M=M, cols=col_sigs,
                       preds=tuple(pred_sigs), apply_preds=True,
                       out_cols=out_cols, flat=flat, packed=True)

    start_t = 0
    start_key = spec.lower
    from yugabyte_db_tpu.utils import codec as _codec

    if resume is not None:
        start_t, last_key = _codec.decode(resume)
        start_key = max(spec.lower, last_key + b"\x00")
    lo, hi = st.row_bounds(spec.lower, spec.upper)
    if resume is not None:
        for t in range(min(start_t, len(st.runs))):
            lo[t] = hi[t]  # earlier tablets: already consumed
        if start_t < len(st.runs):
            lo[start_t] = max(lo[start_t],
                              st.runs[start_t].lower_row(start_key))
    from yugabyte_db_tpu.storage.row_version import MAX_HT

    r_hi, r_lo = PL.scalar_ht_planes(min(spec.read_ht, MAX_HT))
    e_hi, e_lo = PL.scalar_ht_planes(min(spec.read_ht, MAX_HT - 1))
    ip = np.zeros((st.padded_T, RG.PARAM_FIXED + len(int_lits)),
                  dtype=np.int32)
    for t in range(st.padded_T):
        ip[t], _f = RG.pack_params(0, 0, int(lo[t]), int(hi[t]),
                                   (r_hi, r_lo, e_hi, e_lo), int_lits,
                                   [])
    fparams = np.zeros((1,), dtype=np.float32)
    Tl = st.padded_T // st.mesh.shape["t"]
    fn = _compiled_dist_page(sig, st.mesh, st.enc_struct, Tl, st.Bl)
    bufs, total = fn(st.arrays, jnp.asarray(ip), jnp.asarray(fparams))
    # One explicit batched fetch for both outputs (one link round-trip,
    # not one per array): bufs [padded_T, mesh_b, M+1, W] packed pages,
    # total the psum-combined match count.
    bufs, total = jax.device_get((bufs, total))

    W, col_offs = RG.out_layout(sig)
    rows: list[tuple] = []
    budget = limit
    mesh_b = st.mesh.shape["b"]
    shard_rows = st.Bl * st.R
    KR = st.K * st.R
    Wl = st.Bl // st.K
    resume_out = None
    for t, run in enumerate(st.runs):
        truncated = False
        sel: list[tuple] = []  # (global row, buf row, shard base)
        for b in range(mesh_b):
            buf = bufs[t, b]
            c = int(buf[M, 0])
            w_end = int(buf[M, 2])
            base = b * shard_rows
            lo_loc = min(max(int(lo[t]) - base, 0), shard_rows)
            hi_loc = min(max(int(hi[t]) - base, 0), shard_rows)
            w_last = (hi_loc - 1) // KR if hi_loc > lo_loc else -1
            # Early exit (count hit M before w_last) leaves windows
            # unscanned: matches may remain beyond the buffer.
            if c > M or (c >= M and w_end <= min(w_last, Wl - 1)):
                truncated = True
            for m in range(min(c, M)):
                sel.append((base + int(buf[m, 0]), buf[m], base))
        more_in_tablet = truncated or len(sel) > budget
        sel = sel[:budget]
        for g, br, sbase in sel:
            rows.append(_decode_buf_row(run, schema, br, col_offs,
                                        sbase, projection, key_pos,
                                        kinds))
        budget -= len(sel)
        page_full = budget <= 0
        if sel and (more_in_tablet
                    or (page_full and t + 1 < len(st.runs))):
            resume_out = _codec.encode([t, run.key_at(sel[-1][0])])
            break
        if page_full:
            break
    return ScanResult(list(projection), rows, resume_out, int(total))


def _decode_buf_row(run, schema, buf_row, col_offs, shard_base,
                    projection, key_pos, kinds):
    """One packed gather output row -> result tuple (the engine's
    fetched-plane decode split: fixed-width values from the device
    planes, varlen/f32 payloads by setter index from the host mirror,
    key columns from the group-start key)."""
    from yugabyte_db_tpu.models.datatypes import DataType

    key_vals = None
    out = []
    for nm in projection:
        if nm in key_pos:
            if key_vals is None:
                key_vals = run.key_vals_at(shard_base + int(buf_row[0]))
            out.append(key_vals[key_pos[nm]])
            continue
        col = schema.column(nm)
        cmp_off, null_off, idx_off = col_offs[col.col_id]
        if buf_row[null_off]:
            out.append(None)
            continue
        kind = kinds[col.col_id]
        if kind in ("str", "f32"):
            g = shard_base + int(buf_row[idx_off])
            b, r = divmod(g, run.R)
            out.append(run.row_versions[b][r].columns[col.col_id])
        elif kind == "i32":
            v = int(buf_row[cmp_off])
            out.append(bool(v) if col.dtype == DataType.BOOL else v)
        elif kind == "i64":
            out.append(int(PL.ordered_planes_to_i64(
                buf_row[cmp_off:cmp_off + 1],
                buf_row[cmp_off + 1:cmp_off + 2])[0]))
        else:
            out.append(float(PL.ordered_planes_to_f64(
                buf_row[cmp_off:cmp_off + 1],
                buf_row[cmp_off + 1:cmp_off + 2])[0]))
    return tuple(out)
