"""Device-mesh parallelism: multi-tablet scans/aggregates over ICI.

The reference has NO intra-node scan parallelism — one thread walks one
RocksDB iterator per tablet (src/yb/docdb/doc_rowwise_iterator.cc:545), and
multi-tablet aggregates are merged client-side
(src/yb/docdb/pgsql_operation.cc:473, yql/cql/ql/exec/eval_aggr.cc). Here
the tablet axis is data-parallel ("dp") and the block axis within a tablet
is sequence-parallel ("sp"): tablets shard over the mesh's "t" axis, each
tablet's HBM-resident block sequence shards over "b", and the aggregate
combine that the reference does client-side becomes psum / two-plane
lexicographic pmax over ICI (BASELINE config 5).
"""

from yugabyte_db_tpu.parallel.sharded import (ShardedTablets,
                                              sharded_aggregate,
                                              sharded_row_page)
